"""Rank liveness + shrink-and-resume: the survival layer over the
process group.

The collective lane (gloo under ``jax.distributed`` on CPU, ICI/DCN on
TPU) is throughput-optimal and failure-blind: when a peer dies
mid-allreduce every survivor blocks inside the dispatch, and the
platform heartbeat only resolves it by killing the survivors too. This
module adds the three pieces that turn that deadlock into a logged,
recovered event (ROADMAP "survive" pillar):

* **liveness** — a per-rank TCP heartbeat responder (daemon thread,
  stdlib sockets) plus a prober that pings every peer each
  ``dist_heartbeat_ms``; ``max_misses`` consecutive failures mark a
  rank dead within a bounded window even while the collective lane is
  wedged. The wire protocol is a 12-byte magic echo followed by the
  responder's 8-byte wall-clock stamp — each probe doubles as a
  Cristian clock sample (telemetry/clock.py): per-peer RTT lands in
  the ``dist_heartbeat_rtt_ms`` gauge and the offset estimate is what
  rank 0 re-bases merged timelines with. A reply carrying only the
  magic (no stamp) still counts as alive.
* **failure classification** — ``classify_failure`` maps the exception
  soup a dead peer produces (gloo transport errors, typed
  ``CollectiveTimeout`` from resilience/faults.py) onto a single typed
  ``RankFailure``, confirmed against the prober's view so a transient
  blip is not mistaken for a death.
* **shrink** — ``shrink_after_failure`` tears down the dead process
  group in-process and degrades to single-host: reset the bootstrap
  cache, drop the gloo collectives flag, clear backends and every jax
  cache that interns old Device objects, then detach the coordination
  client/service from jax's global state so no destructor or atexit
  hook ever touches the half-dead sockets (the OS reclaims them at
  exit). After it returns, ``jax.devices()`` is the local single-host
  topology and training can resume from the last rank-0 checkpoint.

Supervision is strictly opt-in (``dist_heartbeat_ms > 0``) and lives
entirely off the hot path: the float training loop never touches this
module except for one attribute read per iteration, so the single-host
byte path is identical with supervision off.

The coordination service itself is made inert by the supervised
bootstrap (distributed/bootstrap.py): its own heartbeat knobs are set
effectively infinite so it acts as a pure bootstrap KV store and never
races this layer by killing survivors first.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..telemetry import bundle as telem_bundle
from ..telemetry import clock as telem_clock
from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..utils import log

__all__ = ["RankFailure", "RejoinSignal", "Supervisor", "classify_failure",
           "shrink_after_failure", "start_supervision", "active",
           "stop_supervision", "derive_regroup", "expand_after_rejoin",
           "rejoin_as_replacement", "rendezvous_pending_rejoin",
           "await_rejoin_request", "poll_rejoin_window"]

# request: the 12-byte magic. response: magic + struct.pack("<d",
# time.time()) — liveness is "the event loop answered"; the stamp makes
# every probe a free clock-offset sample (telemetry/clock.py)
_MAGIC = b"lgbm-tpu-hb1"
_STAMP_LEN = 8
# rejoin request: same 12-byte slot so one listener serves both wires.
# Body is a 4-byte-length-prefixed pickle dict; the reply is a length-
# prefixed pickle ack naming the coordinator the re-formed group will
# rendezvous on (see rejoin_as_replacement / expand_after_rejoin).
_REJOIN_MAGIC = b"lgbm-tpu-rj1"


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    """Read exactly n bytes (short on EOF — callers length-check)."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf

# error-text signatures a dead gloo peer produces in the survivor; all
# are catchable XlaRuntimeError / RuntimeError, measured on the probed
# jaxlib (detection ~13 ms after the peer's sockets close)
_PEER_DEATH_SIGNATURES = (
    "gloo all-reduce failed",
    "connection reset by peer",
    "connection closed by peer",
    "connection refused",
    "read error",
    "socket closed",
)


class RankFailure(RuntimeError):
    """One or more peer ranks are confirmed dead or unreachable.

    ``ranks`` is the tuple of dead ranks when the supervisor could
    attribute the failure (empty when only the transport error is
    available); ``reason`` is the triggering evidence.
    """

    def __init__(self, ranks, reason: str):
        self.ranks: Tuple[int, ...] = tuple(sorted(set(int(r)
                                                       for r in ranks)))
        self.reason = str(reason)
        who = list(self.ranks) if self.ranks else "peer"
        super().__init__(f"rank failure ({who}): {self.reason}")


class RejoinSignal(Exception):
    """A replacement process is waiting to join and the group just made
    a checkpoint durable — the one boundary re-forming at N+1 is safe.
    Raised SYMMETRICALLY on every member (the rendezvous that produces
    it is itself a collective when distributed); ``info`` is the ack the
    newcomer already holds: coordinator address, new world size, the
    newcomer's rank, heartbeat period. Not an error — control flow the
    training loops catch to run ``expand_after_rejoin`` and resume from
    the checkpoint just written."""

    def __init__(self, info: dict):
        self.info = dict(info)
        super().__init__(
            f"elastic rejoin pending: world -> {self.info.get('world')} "
            f"via {self.info.get('coordinator')}")


class Supervisor:
    """Per-rank heartbeat responder + peer prober.

    Constructed with an explicit ``rank`` and ``peers`` map
    (``{rank: (host, port)}``) so unit tests can run several instances
    in one process; production wiring goes through ``for_group``, which
    exchanges listener endpoints over the collective lane at start-up
    (the one moment it is known-healthy).
    """

    def __init__(self, rank: int, peers: Dict[int, Tuple[str, int]],
                 heartbeat_ms: float = 500.0, max_misses: int = 3):
        self.rank = int(rank)
        self.heartbeat_ms = float(heartbeat_ms)
        self.max_misses = int(max_misses)
        self._peers: Dict[int, Tuple[str, int]] = dict(peers)
        self._misses: Dict[int, int] = {r: 0 for r in self._peers}
        self._dead: Dict[int, str] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self.port: int = 0
        # acks already issued to replacement processes, waiting for the
        # group to reach a safe re-form boundary (one at a time)
        self._pending_rejoin: List[dict] = []

    # -- lifecycle ------------------------------------------------------
    def start_listener(self, port: int = 0) -> int:
        """Bind + serve the heartbeat responder; returns the bound port
        (ephemeral when ``port`` is 0, so co-located ranks never
        collide)."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("", int(port)))
        srv.listen(8)
        self._listener = srv
        self.port = srv.getsockname()[1]
        t = threading.Thread(target=self._serve_loop, daemon=True,
                             name=f"lgbm-tpu-hb-serve-r{self.rank}")
        t.start()
        self._threads.append(t)
        return self.port

    def _serve_loop(self) -> None:
        # accept, read a 12-byte magic, dispatch: heartbeat probes get
        # the magic echoed back with a wall-clock stamp; rejoin requests
        # get a length-prefixed pickle ack. Any failure on a single
        # connection is the dialer's problem, not ours.
        while not self._stop.is_set():
            srv = self._listener
            if srv is None:
                return
            try:
                conn, _ = srv.accept()
            except OSError:
                return      # listener closed by stop()
            try:
                with conn:
                    conn.settimeout(self._timeout_s)
                    buf = _recv_exact(conn, len(_MAGIC))
                    if buf == _MAGIC:
                        conn.sendall(_MAGIC
                                     + struct.pack("<d", time.time()))
                    elif buf == _REJOIN_MAGIC:
                        self._answer_rejoin(conn)
            except OSError:
                continue

    def _answer_rejoin(self, conn: socket.socket) -> None:
        """Serve one rejoin request: record the pending ack (one at a
        time — a second request while one is pending is refused) and
        reply with the rendezvous the re-formed group will meet at."""
        conn.settimeout(5.0)
        ln = _recv_exact(conn, 4)
        if len(ln) < 4:
            return
        try:
            req = pickle.loads(_recv_exact(conn, struct.unpack("<I", ln)[0]))
        except Exception:   # noqa: BLE001 — garbage on the wire
            return
        with self._lock:
            busy = bool(self._pending_rejoin)
        if busy:
            ack = {"error": "a rejoin is already pending"}
        else:
            try:
                ack = _build_rejoin_ack(req, self.heartbeat_ms)
            except Exception as exc:   # noqa: BLE001 — refusal, not crash
                ack = {"error": str(exc)}
        if "error" not in ack:
            with self._lock:
                self._pending_rejoin.append(ack)
            telem_events.emit("rejoin_request",
                              host=str(req.get("host", "")),
                              coordinator=ack["coordinator"],
                              new_world=ack["world"])
            log.warning("rejoin request from %s: group will re-form at "
                        "world %d via %s at the next safe boundary",
                        req.get("host", "?"), ack["world"],
                        ack["coordinator"])
        payload = pickle.dumps(ack, protocol=4)
        conn.sendall(struct.pack("<I", len(payload)) + payload)

    def drain_pending_rejoin(self) -> List[dict]:
        with self._lock:
            out = list(self._pending_rejoin)
            self._pending_rejoin = []
        return out

    def has_pending_rejoin(self) -> bool:
        with self._lock:
            return bool(self._pending_rejoin)

    def start_prober(self) -> None:
        t = threading.Thread(target=self._probe_loop, daemon=True,
                             name=f"lgbm-tpu-hb-probe-r{self.rank}")
        t.start()
        self._threads.append(t)

    def start(self, port: int = 0) -> None:
        self.start_listener(port)
        self.start_prober()

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            # shutdown() before close(): on Linux close() does not wake
            # a thread blocked in accept() and the socket keeps
            # accepting until that syscall returns — shutdown() forces
            # it out immediately so the port actually goes dark here
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:  # pragma: no cover - already closed
                pass
            self._listener = None
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    def set_peers(self, peers: Dict[int, Tuple[str, int]]) -> None:
        with self._lock:
            self._peers = dict(peers)
            self._misses = {r: 0 for r in self._peers}

    @classmethod
    def for_group(cls, heartbeat_ms: float = 500.0, max_misses: int = 3
                  ) -> "Supervisor":
        """Production bring-up: start the local responder, exchange
        ``(rank, host, port)`` endpoints over the collective lane, then
        start probing. Must run while the group is healthy (right after
        bootstrap) — it is itself a collective."""
        from ..io.distributed import _allgather_host_bytes
        from . import bootstrap
        sup = cls(bootstrap.rank(), {}, heartbeat_ms, max_misses)
        # LGBM_TPU_REJOIN_PORT pins THIS rank's listener so a future
        # replacement process has a known address to dial (the heartbeat
        # listener doubles as the rejoin endpoint); ephemeral otherwise.
        # Fall back to ephemeral on a bind collision so co-located ranks
        # sharing an environment never fail bring-up.
        try:
            sup.start_listener(
                int(os.environ.get("LGBM_TPU_REJOIN_PORT", "0") or 0))
        except OSError:
            sup.start_listener()
        me = (sup.rank, _advertise_host(), sup.port)
        entries = [pickle.loads(c) for c in _allgather_host_bytes(
            pickle.dumps(me, protocol=4))]
        sup.set_peers({int(r): (str(h), int(p)) for r, h, p in entries
                       if int(r) != sup.rank})
        sup.start_prober()
        log.info("supervisor up: rank %d probing %d peer(s) every %.0f ms",
                 sup.rank, len(sup._peers), sup.heartbeat_ms)
        return sup

    # -- probing --------------------------------------------------------
    @property
    def _timeout_s(self) -> float:
        # a probe gets one heartbeat period to complete, floor 50 ms so
        # aggressive periods still survive scheduler jitter
        return max(self.heartbeat_ms / 1e3, 0.05)

    def _probe_once(self, peer_rank: int) -> bool:
        with self._lock:
            addr = self._peers.get(peer_rank)
        if addr is None:
            return True
        try:
            t0 = time.time()
            with socket.create_connection(addr,
                                          timeout=self._timeout_s) as s:
                s.settimeout(self._timeout_s)
                s.sendall(_MAGIC)
                want = len(_MAGIC) + _STAMP_LEN
                buf = b""
                while len(buf) < want:
                    chunk = s.recv(want - len(buf))
                    if not chunk:
                        break
                    buf += chunk
                t1 = time.time()
                if buf[:len(_MAGIC)] != _MAGIC:
                    return False
                if len(buf) == want:
                    # full reply: fold the round trip into the clock
                    # estimate (offset error bounded by rtt/2)
                    t_peer = struct.unpack("<d", buf[len(_MAGIC):])[0]
                    telem_clock.observe(peer_rank, t0, t1, t_peer)
                return True
        except OSError:
            return False

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_ms / 1e3):
            with self._lock:
                targets = [r for r in self._peers if r not in self._dead]
            for r in targets:
                if self._stop.is_set():
                    return
                telem_counters.incr("heartbeat_probes")
                if self._probe_once(r):
                    with self._lock:
                        self._misses[r] = 0
                    continue
                telem_counters.incr("heartbeat_misses")
                with self._lock:
                    self._misses[r] = self._misses.get(r, 0) + 1
                    n = self._misses[r]
                if n >= self.max_misses:
                    self._mark_dead(r, f"{n} consecutive heartbeat misses")

    def _mark_dead(self, peer_rank: int, reason: str) -> None:
        with self._lock:
            if peer_rank in self._dead:
                return
            self._dead[peer_rank] = reason
        telem_counters.incr("rank_failures")
        telem_events.emit("rank_dead", rank=peer_rank, reason=reason,
                          heartbeat_ms=self.heartbeat_ms)
        log.warning("supervisor: rank %d declared dead (%s)", peer_rank,
                    reason)

    # -- queries --------------------------------------------------------
    def dead_ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    def check(self) -> None:
        """Raise RankFailure if the prober has declared any peer dead.
        One lock acquire — cheap enough for a per-iteration poll."""
        with self._lock:
            if not self._dead:
                return
            dead = dict(self._dead)
        raise RankFailure(dead, "; ".join(
            f"rank {r}: {why}" for r, why in sorted(dead.items())))

    def confirm_dead(self, suspects: Optional[List[int]] = None,
                     rounds: int = 3) -> List[int]:
        """Active confirmation: probe each suspect ``rounds`` times
        back-to-back; a rank is confirmed dead only if EVERY round
        fails. Used when a collective error arrives before the passive
        prober has accumulated enough misses."""
        with self._lock:
            targets = (list(suspects) if suspects is not None
                       else list(self._peers))
        confirmed = []
        for r in targets:
            with self._lock:
                if r in self._dead:
                    confirmed.append(r)
                    continue
            alive = False
            for _ in range(max(1, int(rounds))):
                if self._probe_once(r):
                    alive = True
                    break
                time.sleep(0.01)
            if not alive:
                self._mark_dead(r, f"failed {rounds} confirmation probes")
                confirmed.append(r)
        return sorted(set(confirmed))


def _advertise_host() -> str:
    """The address peers should probe for THIS rank's responder.
    Override with LGBM_TPU_ADVERTISE_HOST; loopback coordinator implies
    a co-located test topology, so loopback back; else best-effort
    resolved hostname."""
    host = os.environ.get("LGBM_TPU_ADVERTISE_HOST", "").strip()
    if host:
        return host
    try:
        from jax._src import distributed as _jd
        coord = str(getattr(_jd.global_state, "coordinator_address", "")
                    or "")
    except Exception:  # pragma: no cover - jax internals moved
        coord = ""
    chost = coord.rsplit(":", 1)[0] if coord else ""
    if chost in ("", "localhost", "127.0.0.1", "::1", "[::1]", "[::]"):
        return "127.0.0.1"
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:  # pragma: no cover - resolver-less container
        return "127.0.0.1"


# -- module singleton ---------------------------------------------------
_active: Optional[Supervisor] = None
_last_hb_ms: float = 0.0      # last armed heartbeat period (rejoin acks)
_rejoin_gen: int = 0          # completed rejoins (coordinator-port salt)


def active() -> Optional[Supervisor]:
    return _active


def start_supervision(heartbeat_ms: float, collective_timeout_ms: float = 0
                      ) -> Optional[Supervisor]:
    """Wire the full supervision stack for a live process group: install
    the collective deadline (resilience/faults.py) and start the
    heartbeat supervisor. No-ops single-process or when
    ``heartbeat_ms <= 0`` — the opt-in that keeps the single-host path
    byte-identical."""
    global _active, _last_hb_ms
    from ..resilience import faults
    from . import bootstrap
    if not bootstrap.is_distributed():
        return None
    if collective_timeout_ms and collective_timeout_ms > 0:
        faults.set_collective_timeout_ms(collective_timeout_ms)
    if not heartbeat_ms or heartbeat_ms <= 0:
        return None
    _last_hb_ms = float(heartbeat_ms)
    if _active is not None:
        return _active
    _active = Supervisor.for_group(heartbeat_ms=heartbeat_ms)
    return _active


def stop_supervision() -> None:
    global _active
    if _active is not None:
        _active.stop()
        _active = None


# -- failure classification ---------------------------------------------
def classify_failure(exc: BaseException,
                     sup: Optional[Supervisor] = None
                     ) -> Optional[RankFailure]:
    """Map an exception from the collective lane onto a RankFailure, or
    None when it is not peer-death shaped. When a supervisor is
    available the suspicion is confirmed with active probes so a
    transient transport blip does not trigger a shrink."""
    from ..resilience.faults import CollectiveTimeout
    if isinstance(exc, RankFailure):
        return exc
    text = f"{type(exc).__name__}: {exc}".lower()
    suspicious = isinstance(exc, CollectiveTimeout) or any(
        sig in text for sig in _PEER_DEATH_SIGNATURES)
    if not suspicious:
        return None
    sup = sup if sup is not None else _active
    if sup is not None:
        dead = sup.confirm_dead()
        if not dead:
            # lane error but every peer answers its heartbeat: treat as
            # non-fatal so the caller's normal error path runs
            log.warning("collective error without a dead peer "
                        "(all heartbeats answered): %s", text[:200])
            return None
        return RankFailure(dead, f"collective lane failure: {text[:200]}")
    # no supervisor: the transport evidence is all we have
    return RankFailure((), f"collective lane failure: {text[:200]}")


# -- shrink-and-resume ---------------------------------------------------
def derive_regroup(world: int, dead, old_rank: int, old_coord: str,
                   peer_hosts: Dict[int, Tuple[str, int]], my_host: str
                   ) -> Tuple[int, int, str]:
    """Pure derivation of the re-formed group's shape after a failure:
    ``(survivors, new_rank, new_coordinator)`` (coordinator "" when the
    group degrades to single-host). New rank = index in the sorted
    survivor list, new coordinator = FIRST survivor's heartbeat host —
    which is how a dead rank 0 hands coordination (and thereby rank-0
    checkpoint-write duty) to the lowest surviving rank — and new port
    = old coordinator port + number of dead ranks (the immortalized old
    service keeps the old port bound, so the offset also avoids a bind
    collision). No cross-host agreement protocol is needed: every input
    here is already identical on every survivor when the shrink
    starts."""
    dead = sorted(set(int(r) for r in dead))
    surviving = [r for r in range(world) if r not in set(dead)]
    survivors = world - len(dead) if dead else 1
    if survivors <= 1:
        return 1, 0, ""
    lead = surviving[0]
    if lead == old_rank:
        lead_host = my_host
    elif lead in peer_hosts:
        lead_host = peer_hosts[lead][0]
    elif lead == 0 and old_coord:
        lead_host = old_coord.rsplit(":", 1)[0]
    else:
        log.fatal(
            "cannot re-form a %d-survivor group: no dialable "
            "address for the new coordinator (rank %d) — heartbeat "
            "supervision (dist_heartbeat_ms > 0) is required for "
            "multi-survivor shrink", survivors, lead)
    if not old_coord:
        log.fatal("cannot re-form: old coordinator address unknown")
    new_port = int(old_coord.rsplit(":", 1)[1]) + len(dead)
    return survivors, surviving.index(old_rank), f"{lead_host}:{new_port}"


def _teardown_backend() -> None:
    """Validated in-process teardown of a live jax process group (order
    matters — shared by shrink and elastic-rejoin expansion):

    1. forget the cached mesh/identity so nothing re-dispatches onto
       the dead topology through the bootstrap cache;
    2. next backend must come up WITHOUT gloo first (re-forming paths
       re-select gloo right before rejoining);
    3. drop the dead runtime client/backend;
    4. purge every cache that interns old Device objects (the Mesh
       intern dict is global and never evicted);
    5. detach the coordination client/service (and the preemption sync
       manager — jax.distributed.initialize refuses to run again while
       one is attached) from jax's global state WITHOUT destroying
       them: their destructors (and jax's atexit shutdown) join
       heartbeat/error-polling threads blocked on dead peer sockets and
       abort the process. Immortalize via an extra refcount and let the
       OS reclaim the sockets at exit."""
    import ctypes
    import gc

    import jax
    from jax._src import distributed as _jd

    from . import bootstrap

    bootstrap._state.update({"initialized": False, "num_processes": 1,
                             "rank": 0, "mesh": None, "mesh_axis": None})
    try:
        jax.config.update("jax_cpu_collectives_implementation", "none")
    except Exception:  # pragma: no cover - flag absent on this backend
        pass
    from jax.extend import backend as jeb
    jeb.clear_backends()
    try:
        from jax._src import mesh as _mesh_mod
        _mesh_mod._mesh_object_dict.clear()
    except Exception:  # pragma: no cover - jax internals moved
        pass
    jax.clear_caches()
    for obj in (getattr(_jd.global_state, "client", None),
                getattr(_jd.global_state, "service", None),
                getattr(_jd.global_state, "preemption_sync_manager",
                        None)):
        if obj is not None:
            ctypes.pythonapi.Py_IncRef(ctypes.py_object(obj))
    _jd.global_state.client = None
    _jd.global_state.service = None
    try:
        _jd.global_state.preemption_sync_manager = None
    except Exception:  # pragma: no cover - field absent on this jax
        pass
    _jd.global_state.num_processes = 1
    _jd.global_state.process_id = 0
    _jd.global_state.coordinator_address = None
    gc.collect()


def shrink_after_failure(failure: Optional[RankFailure] = None) -> int:
    """Tear down the dead process group and continue with the survivors.

    One survivor degrades to single-host (the common 2-host topology).
    With N > 1 survivors the group RE-FORMS in-process: every survivor
    runs the identical teardown, then rejoins a fresh coordination
    service on a deterministically derived address — new rank = index
    in the sorted survivor list, new coordinator = first survivor's
    heartbeat host, new port = old port + number of dead ranks (the old
    immortalized service keeps the old port bound, so the offset also
    avoids a bind collision). No cross-host agreement protocol is
    needed because every input to that derivation (old world, dead set,
    old coordinator, peer hosts) is already identical on every survivor
    when the shrink starts.

    Returns the new world size. The caller must drop its own references
    to boosters/datasets built on the old backend before dispatching
    new work; ``failure.__traceback__`` is cleared here so the dead
    iteration's frames do not pin them. Callers re-entering training
    re-arm supervision and the collective deadline themselves
    (engine.train does); this function leaves the deadline off so the
    rendezvous cannot be killed by a stale timeout.
    """
    import jax
    from jax._src import distributed as _jd

    from ..resilience import faults
    from . import bootstrap

    world = int(getattr(_jd.global_state, "num_processes", 1) or 1)
    if world <= 1:
        return 1
    dead = list(failure.ranks) if failure is not None else []
    # capture everything the re-bootstrap derives its addresses from
    # BEFORE teardown wipes jax's global state and the supervisor
    old_rank = int(getattr(_jd.global_state, "process_id", 0) or 0)
    old_coord = str(getattr(_jd.global_state, "coordinator_address", "")
                    or "")
    sup = _active
    peer_hosts = dict(sup._peers) if sup is not None else {}
    survivors, new_rank, new_coord = derive_regroup(
        world, dead, old_rank, old_coord, peer_hosts, _advertise_host())

    # freeze the dying world's evidence BEFORE any teardown: after
    # stop_supervision/clear_backends the prober state, ring and
    # timeline describe a group that no longer exists
    telem_bundle.maybe_capture(
        "rank_failure", dead_ranks=dead, old_world=world,
        failure=failure.reason if failure is not None else "requested")

    stop_supervision()
    telem_counters.incr("shrinks")
    # wall-clock mark for detection-latency measurement (chaos_bench
    # dist_kill subtracts the victim's observed exit time)
    telem_counters.set_gauge("last_shrink_unix", time.time())
    telem_events.emit("shrink", dead_ranks=dead, old_world=world,
                      new_world=survivors,
                      reason=failure.reason if failure else "requested")
    log.warning("shrinking process group %d -> %d (dead ranks: %s)",
                world, survivors, dead or "unknown")
    if failure is not None:
        failure.__traceback__ = None

    # validated teardown recipe (order matters — see _teardown_backend)
    _teardown_backend()

    # deadline off either way: single-host needs none, and the
    # multi-survivor rendezvous must not be killed by a stale timeout
    # (train() re-arms it from config on re-entry)
    faults.set_collective_timeout_ms(0)

    if survivors <= 1:
        telem_counters.set_gauge("dist_process_count", 1)
        telem_counters.set_gauge("dist_rank", 0)
        log.warning("shrink complete: continuing single-host on %d "
                    "device(s)", len(jax.devices()))
        # a replacement must still find an open door after the
        # supervisor died with the group (elastic rejoin, opt-in)
        _restart_rejoin_listener()
        return 1

    # --- multi-survivor: re-form the group on a fresh port -------------
    log.warning("re-forming process group: rank %d -> rank %d of %d "
                "(coordinator %s)", old_rank, new_rank, survivors,
                new_coord)
    bootstrap.initialize(new_coord, survivors, new_rank, supervise=True)
    telem_events.emit("regroup", old_rank=old_rank, new_rank=new_rank,
                      new_world=survivors, coordinator=new_coord)
    log.warning("shrink complete: continuing with %d process(es) on %d "
                "device(s)", survivors, len(jax.devices()))
    return survivors


# -- elastic rejoin ------------------------------------------------------
# The grow half of the survival story (ROADMAP "survive"): a replacement
# process started with LGBM_TPU_REJOIN=1 dials a survivor's heartbeat
# endpoint (LGBM_TPU_REJOIN_CONTACT=host:port), receives an ack naming
# the coordinator the re-formed group will meet at, and blocks in
# bootstrap until the existing members reach a safe boundary — either
# the post-shrink grace window (poll_rejoin_window) or the next durable
# checkpoint (DistributedCheckpointManager.save -> RejoinSignal). The
# whole lane is opt-in via LGBM_TPU_ELASTIC_REJOIN=1, set on EVERY
# member (the rendezvous is a collective).

def _rank0_host() -> str:
    """Dialable host of the CURRENT rank 0 (the rank that will own the
    re-formed group's coordination service and checkpoint writes)."""
    from . import bootstrap
    if bootstrap.rank() == 0:
        return _advertise_host()
    try:
        from jax._src import distributed as _jd
        coord = str(getattr(_jd.global_state, "coordinator_address", "")
                    or "")
        if coord:
            return coord.rsplit(":", 1)[0]
    except Exception:  # pragma: no cover - jax internals moved
        pass
    return _advertise_host()


def _build_rejoin_ack(req: dict, heartbeat_ms: float) -> dict:
    """The rendezvous a replacement process should bootstrap toward.
    Coordinator = current rank 0's host on a deterministic port derived
    from LGBM_TPU_REJOIN_PORT (+1 per completed rejoin, so repeated
    grow/shrink cycles never collide with an immortalized old service);
    the newcomer takes rank = old world (existing members keep their
    ranks, so scores/shards restored from the checkpoint stay put).
    ``_rejoin_gen`` is kept uniform across the group — survivors bump it
    in expand_after_rejoin and a replacement adopts it from this ack in
    rejoin_as_replacement — so ANY member can answer the next knock with
    a port no previous generation ever bound."""
    port_env = os.environ.get("LGBM_TPU_REJOIN_PORT", "").strip()
    if not port_env:
        raise RuntimeError(
            "rejoin needs LGBM_TPU_REJOIN_PORT set on the survivor to "
            "derive a deterministic coordinator port")
    from . import bootstrap
    world = bootstrap.process_count()
    return {"coordinator": f"{_rank0_host()}:"
                           f"{int(port_env) + 1 + _rejoin_gen}",
            "world": world + 1, "rank": world,
            "heartbeat_ms": float(heartbeat_ms), "gen": _rejoin_gen,
            "peer_host": str(req.get("host", ""))}


def _restart_rejoin_listener() -> None:
    """After a shrink to single-host the supervisor died with the group
    — but a replacement must still be able to dial something. With
    elastic rejoin armed and LGBM_TPU_REJOIN_PORT set, bring up a
    listener-only Supervisor on that fixed port (no peers, no prober)
    and make it the active one so `check()` keeps working."""
    global _active
    port = os.environ.get("LGBM_TPU_REJOIN_PORT", "").strip()
    if not port or os.environ.get("LGBM_TPU_ELASTIC_REJOIN", "") != "1":
        return
    if _active is not None:
        return
    sup = Supervisor(0, {}, heartbeat_ms=_last_hb_ms or 500.0)
    try:
        sup.start_listener(int(port))
    except OSError as exc:  # pragma: no cover - port raced away
        log.warning("could not re-arm rejoin listener on port %s: %s",
                    port, exc)
        return
    _active = sup
    log.warning("rejoin listener re-armed on port %s", port)


def rendezvous_pending_rejoin() -> Optional[dict]:
    """The one pending rejoin ack every member agrees on, or None.

    Distributed, each member contributes its locally-received acks over
    the all-gather lane so EVERY rank returns the same answer (the
    newcomer only ever dialed one of them); single-host it is a plain
    local drain. Gated on LGBM_TPU_ELASTIC_REJOIN=1 — the gather is a
    real collective, so the flag must be set symmetrically."""
    if os.environ.get("LGBM_TPU_ELASTIC_REJOIN", "") != "1":
        return None
    sup = _active
    local: List[dict] = sup.drain_pending_rejoin() if sup is not None \
        else []
    from . import bootstrap
    if bootstrap.is_distributed():
        from ..io.distributed import _allgather_host_bytes
        chunks = _allgather_host_bytes(pickle.dumps(local, protocol=4))
        merged = [a for c in chunks for a in pickle.loads(c)]
    else:
        merged = local
    if not merged:
        return None
    merged.sort(key=lambda a: (int(a.get("gen", 0)),
                               str(a.get("coordinator", ""))))
    return merged[0]


def await_rejoin_request(timeout_s: float) -> bool:
    """Block (poll) until a rejoin request is pending on THIS process's
    listener, or the window closes. Does not drain — the rendezvous
    does."""
    deadline = time.time() + max(0.0, float(timeout_s))
    while True:
        sup = _active
        if sup is not None and sup.has_pending_rejoin():
            return True
        if time.time() >= deadline:
            return False
        time.sleep(0.02)


def poll_rejoin_window() -> Optional[dict]:
    """Post-shrink grace window: give an already-launched replacement a
    bounded chance (LGBM_TPU_REJOIN_WAIT_MS) to rejoin BEFORE any
    shrunken-world iteration runs. Expanding here keeps every trained
    iteration at the original world size — which is exactly what makes
    kill -> rejoin parity-exact against the never-killed run. Returns
    the agreed ack or None (continue shrunken)."""
    if os.environ.get("LGBM_TPU_ELASTIC_REJOIN", "") != "1":
        return None
    wait_ms = float(os.environ.get("LGBM_TPU_REJOIN_WAIT_MS", "0") or 0)
    if wait_ms <= 0:
        return None
    have = await_rejoin_request(wait_ms / 1e3)
    from . import bootstrap
    if not have and not bootstrap.is_distributed():
        log.warning("no replacement dialed in within the %g ms rejoin "
                    "window; continuing shrunken", wait_ms)
        return None
    # distributed survivors must ALL enter the rendezvous collective,
    # pending or not — only one of them took the newcomer's call
    return rendezvous_pending_rejoin()


def expand_after_rejoin(info: dict) -> int:
    """Existing-member half of the re-form at N+1: tear down whatever
    backend is live (single-host after a shrink, or the N-member group
    at a checkpoint boundary), re-bootstrap at the ack's coordinator
    with our EXISTING rank, and re-arm supervision. The caller resumes
    training from the last durable checkpoint (the resume broadcast is
    the newcomer's state transfer)."""
    global _rejoin_gen
    from ..resilience import faults
    from . import bootstrap
    my_rank = bootstrap.rank()
    new_world = int(info["world"])
    hb_ms = float(info.get("heartbeat_ms", 0.0) or _last_hb_ms)
    log.warning("elastic rejoin: re-forming %d -> %d (coordinator %s, "
                "keeping rank %d)", new_world - 1, new_world,
                info["coordinator"], my_rank)
    stop_supervision()
    _teardown_backend()
    faults.set_collective_timeout_ms(0)
    bootstrap.initialize(info["coordinator"], new_world, my_rank,
                         supervise=True)
    _rejoin_gen = max(_rejoin_gen, int(info.get("gen", 0))) + 1
    telem_counters.incr("rejoins")
    telem_events.emit("rejoin", role="member", rank=my_rank,
                      new_world=new_world,
                      coordinator=info["coordinator"])
    if hb_ms > 0:
        start_supervision(hb_ms)
    log.warning("rejoin complete: world %d, rank %d", new_world, my_rank)
    return new_world


def rejoin_as_replacement(contact: str, timeout_s: float = 60.0) -> dict:
    """Newcomer half: dial a survivor's heartbeat endpoint (retrying
    while the survivor is still tearing down), send the length-prefixed
    rejoin request, then bootstrap into the re-formed group at the
    ack's coordinator/world/rank. The bootstrap blocks until the
    existing members reach their re-form boundary (bounded by
    LGBM_TPU_INIT_TIMEOUT_S). State arrives via the ordinary resume
    broadcast, so the caller just enters train(resume_from=...)."""
    host, _, port = str(contact).rpartition(":")
    req = pickle.dumps({"host": _advertise_host(), "pid": os.getpid()},
                       protocol=4)
    deadline = time.time() + max(1.0, float(timeout_s))
    while True:
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=2.0) as s:
                s.settimeout(5.0)
                s.sendall(_REJOIN_MAGIC + struct.pack("<I", len(req))
                          + req)
                ln = _recv_exact(s, 4)
                if len(ln) < 4:
                    raise OSError("short rejoin ack")
                ack = pickle.loads(
                    _recv_exact(s, struct.unpack("<I", ln)[0]))
            break
        except (OSError, pickle.UnpicklingError, EOFError) as exc:
            if time.time() >= deadline:
                raise RankFailure(
                    (), f"rejoin contact {contact} unreachable: {exc}")
            time.sleep(0.1)
    if not isinstance(ack, dict) or "error" in ack:
        raise RuntimeError(f"rejoin refused by {contact}: {ack}")
    log.warning("rejoining as rank %d of %d via %s", ack["rank"],
                ack["world"], ack["coordinator"])
    global _rejoin_gen
    from ..resilience import faults
    from . import bootstrap
    faults.set_collective_timeout_ms(0)
    bootstrap.initialize(ack["coordinator"], int(ack["world"]),
                         int(ack["rank"]), supervise=True)
    # adopt the group's rejoin generation: every member (survivors via
    # expand_after_rejoin, this newcomer via the ack) lands on gen+1, so
    # a FUTURE ack built by any member — including this one — derives
    # the same fresh coordinator port instead of re-offering one bound
    # by an immortalized old coordination service
    _rejoin_gen = max(_rejoin_gen, int(ack.get("gen", 0))) + 1
    telem_counters.incr("rejoins")
    telem_events.emit("rejoin", role="replacement", rank=int(ack["rank"]),
                      new_world=int(ack["world"]),
                      coordinator=ack["coordinator"])
    hb = float(ack.get("heartbeat_ms", 0.0))
    if hb > 0:
        start_supervision(hb)
    return ack
