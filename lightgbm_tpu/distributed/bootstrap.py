"""Process-group bootstrap: config surface -> jax.distributed -> Mesh.

Maps the reference's cluster bring-up (reference:
src/network/linkers_socket.cpp:80 — rank = index of the local address
in the ``machines`` list, full-mesh TCP handshake) onto
``jax.distributed.initialize``: entry 0 of the machine list is the
coordinator, every process dials it, and the platform runtime owns the
transport from there. Collectives never run in userspace — they are XLA
ops inside the jitted tree programs — so the only host-side state this
module keeps is the process identity and the global `Mesh`.

Env-var overrides (launchers like SLURM/k8s indexed jobs set these
instead of editing configs):

* ``LGBM_TPU_COORDINATOR``   — ``host:port`` of process 0
* ``LGBM_TPU_NUM_PROCESSES`` — world size
* ``LGBM_TPU_PROCESS_ID``    — this process's rank

On the CPU backend, cross-process collectives need an explicit
implementation (gloo); `_enable_cpu_collectives` flips the jax config
flag BEFORE the first backend touch — after the CPU client exists the
flag is ignored and every multi-process computation fails with
"Multiprocess computations aren't implemented on the CPU backend".
TPU/GPU need nothing: the fabric is the implementation.
"""
from __future__ import annotations

import os
from typing import Optional

from ..utils import log

_state = {"initialized": False, "num_processes": 1, "rank": 0,
          "mesh": None, "mesh_axis": None}


def _enable_cpu_collectives() -> None:
    """Select gloo for CPU cross-process collectives. Must run before
    jax creates the CPU client; harmless (and skipped) elsewhere."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        # jaxlib without the flag (or a backend that doesn't need it):
        # leave the default; TPU/GPU transports are built in
        pass


def resolve_rank(entries, explicit_rank: int = -1) -> Optional[int]:
    """Rank of this host in the machine list. ``machine_rank >= 0``
    short-circuits hostname detection (containers often don't resolve
    their external address; the reference has the same escape via
    ``local_listen_port`` disambiguation, linkers_socket.cpp:80)."""
    if explicit_rank >= 0:
        return explicit_rank
    import socket
    my_names = {socket.gethostname(), "localhost", "127.0.0.1"}
    try:
        my_names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    for i, e in enumerate(entries):
        if e.split(":")[0] in my_names:
            return i
    return None


def _initialize_supervised(coordinator_address: str, num_processes: int,
                           process_id: int) -> None:
    """Join the group with the platform coordination service made INERT.

    The stock ``jax.distributed.initialize`` arms the coordination
    service's own heartbeat: when a rank dies, the service tears down
    every *survivor* (hard process abort from a C++ polling thread) —
    the opposite of elastic recovery, and its Python
    missed-heartbeat callback path aborts with std::bad_cast on this
    jaxlib. So the supervised path builds the same service/client pair
    manually with effectively-infinite heartbeat knobs: the service
    degenerates to the bootstrap KV store the backends need, while
    OUR supervision (distributed/supervisor.py) owns liveness with a
    clean Python-side failure path. ``shutdown_on_destruction=False``
    keeps the client destructor from joining threads blocked on dead
    peers during shrink."""
    from jax._src import distributed as _jd
    from jaxlib import xla_extension as xe

    # seconds; the service only declares death after
    # heartbeat_interval * max_missing_heartbeats — push it past any
    # plausible job length
    inert_s = 1_000_000
    if int(process_id) == 0 and _jd.global_state.service is None:
        port = coordinator_address.rsplit(":", 1)[1]
        _jd.global_state.service = xe.get_distributed_runtime_service(
            f"[::]:{port}", int(num_processes),
            heartbeat_interval=inert_s, max_missing_heartbeats=10)
    # init_timeout doubles as the elastic-rejoin wait: a replacement
    # process blocks here until the existing members reach their
    # re-form boundary and rank 0 starts the new service
    init_timeout = int(os.environ.get("LGBM_TPU_INIT_TIMEOUT_S", 60))
    client = xe.get_distributed_runtime_client(
        coordinator_address, int(process_id), init_timeout=init_timeout,
        heartbeat_interval=inert_s, max_missing_heartbeats=10,
        shutdown_on_destruction=False, use_compression=True)
    client.connect()
    _jd.global_state.client = client
    _jd.global_state.num_processes = int(num_processes)
    _jd.global_state.process_id = int(process_id)
    _jd.global_state.coordinator_address = coordinator_address


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, supervise: bool = False) -> None:
    """Join the process group (idempotent). Bootstrap is a host
    collective boundary: joining retries transient failures with the
    same bounded backoff as in-training collectives
    (resilience/faults.py). ``supervise=True`` (or env
    ``LGBM_TPU_SUPERVISE=1``) routes through the supervised bring-up so
    rank death is OUR layer's to detect, not the platform's to abort
    on."""
    if _state["initialized"]:
        return
    import jax
    from ..resilience import faults
    from ..telemetry import counters
    _enable_cpu_collectives()
    if supervise or os.environ.get("LGBM_TPU_SUPERVISE", "") == "1":
        join = lambda: _initialize_supervised(  # noqa: E731
            coordinator_address, num_processes, process_id)
    else:
        join = lambda: jax.distributed.initialize(  # noqa: E731
            coordinator_address=coordinator_address,
            num_processes=int(num_processes),
            process_id=int(process_id))
    faults.run_collective(join, site="bootstrap")
    _state["initialized"] = True
    _state["num_processes"] = int(num_processes)
    _state["rank"] = int(process_id)
    counters.set_gauge("dist_process_count", int(num_processes))
    counters.set_gauge("dist_rank", int(process_id))
    # trace events carry pid=rank from here on, so per-rank dumps load
    # side-by-side in Perfetto and rank 0 can merge them
    from ..telemetry import spans
    spans.set_pid(int(process_id))
    log.info("jax.distributed initialized: rank %d of %d (coordinator %s)",
             process_id, num_processes, coordinator_address)


def initialize_from_env() -> bool:
    """Bring-up purely from LGBM_TPU_* env vars. Returns True if the
    trio was present and the group was joined."""
    coord = os.environ.get("LGBM_TPU_COORDINATOR", "").strip()
    nproc = os.environ.get("LGBM_TPU_NUM_PROCESSES", "").strip()
    pid = os.environ.get("LGBM_TPU_PROCESS_ID", "").strip()
    if not (coord and nproc and pid):
        return False
    initialize(coord, int(nproc), int(pid))
    return True


def initialize_from_config(machines: str = "", local_listen_port: int = 12400,
                           num_machines: int = 1, machine_rank: int = -1,
                           coordinator: str = "",
                           supervise: bool = False) -> None:
    """The reference's config surface -> process group. Precedence:
    env-var trio > explicit ``coordinator`` + ``machine_rank`` >
    ``machines`` list with hostname rank detection. ``supervise``
    (set from ``dist_heartbeat_ms > 0``) selects the supervised
    bring-up."""
    if _state["initialized"]:
        return
    if initialize_from_env():
        return
    if coordinator and num_machines > 1:
        if machine_rank < 0:
            log.fatal("coordinator=%s requires machine_rank>=0 "
                      "(hostname detection needs the machines list)",
                      coordinator)
        initialize(coordinator, num_machines, machine_rank,
                   supervise=supervise)
        return
    if isinstance(machines, (list, tuple)):
        machines = ",".join(machines)
    entries = [m.strip() for m in str(machines).split(",") if m.strip()]
    if len(entries) <= 1:
        return                       # single machine: nothing to join
    rank_ = resolve_rank(entries, machine_rank)
    if rank_ is None:
        log.fatal("Could not find local machine in machine list: %s "
                  "(set machine_rank=<idx> to override)", machines)
    initialize(entries[0], len(entries), rank_, supervise=supervise)


def _external_group():
    """(num_processes, rank) of a process group brought up OUTSIDE this
    module (a harness calling jax.distributed.initialize directly), or
    None. Inspects jax.distributed's own state object rather than
    calling jax.process_count(), which would instantiate the backend —
    and freeze the CPU client before gloo could be selected."""
    import sys
    if "jax" not in sys.modules:
        return None
    try:
        from jax._src import distributed as _jd
        st = _jd.global_state
        if getattr(st, "client", None) is None:
            return None
        return int(st.num_processes), int(st.process_id)
    except Exception:  # pragma: no cover - jax internals moved
        return None


def is_distributed() -> bool:
    """True once a REAL multi-process group is up (the virtual
    single-process mesh never counts)."""
    return process_count() > 1


def process_count() -> int:
    if _state["initialized"]:
        return _state["num_processes"]
    ext = _external_group()
    return ext[0] if ext else 1


def rank() -> int:
    if _state["initialized"]:
        return _state["rank"]
    ext = _external_group()
    return ext[1] if ext else 0


def global_mesh(axis_name: str = "data"):
    """The one mesh the learners consume: 1-D over ALL devices in the
    process group (jax.devices() is global under jax.distributed, so
    the same code serves the virtual and the real topology). Cached —
    learners, ingest, and checkpoints must agree on the axis."""
    if _state["mesh"] is not None and _state["mesh_axis"] == axis_name:
        return _state["mesh"]
    import jax
    import numpy as np
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()), (axis_name,))
    _state["mesh"] = mesh
    _state["mesh_axis"] = axis_name
    return mesh


def barrier(name: str = "lgbm_tpu_barrier") -> None:
    """Cross-host rendezvous (checkpoint durability, resume gating).
    No-op single-process; a real collective dispatch otherwise, counted
    and retried like every other host collective."""
    if not is_distributed():
        return
    from jax.experimental import multihost_utils
    from ..resilience import faults
    faults.run_collective(
        lambda: multihost_utils.sync_global_devices(name),
        site=f"barrier:{name}")


def shutdown() -> None:
    if _state["initialized"]:
        import jax
        try:
            # teardown must not retry or respect the collective deadline:
            # by here peers may already be gone, and the bare except is
            # the whole failure policy. lint: disable=collective-discipline
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover - already torn down
            pass
    _state["initialized"] = False
    _state["num_processes"] = 1
    _state["rank"] = 0
    _state["mesh"] = None
    _state["mesh_axis"] = None
