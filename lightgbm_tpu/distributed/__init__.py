"""Multi-host distributed training layer.

The reference builds its cluster trainer from a userspace transport
(reference: src/network/ — linkers_socket.cpp full-mesh TCP,
network.cpp Bruck/recursive-halving collectives) plus per-subsystem
protocols layered on it (distributed bin finding, histogram
ReduceScatter, global best-split sync, rank-0 model output). On TPU the
transport IS the platform: `jax.distributed.initialize` joins the
multi-host ICI/DCN domain and every in-training collective is an XLA op
emitted inside the jitted tree programs (parallel/learners.py). What
remains host-side — and what this package owns — is the *topology*:

* `bootstrap`  — process-group bring-up from the reference's
  ``machines``/``num_machines``/``machine_rank``/``local_listen_port``
  config surface (env-var overrides for launchers), the global `Mesh`
  the learners consume, and a named cross-host barrier.
* `ingest`     — rank-partitioned dataset loading: each host samples
  and bins its own row shard against cooperatively-found bin mappers
  (io/distributed.py protocol), then all-gathers the compact binned
  blocks so every host holds the identical `Dataset` (the float matrix
  never crosses the wire; codes are ~8x smaller).
* `checkpoint` — rank-0 checkpoint writes with a post-save barrier and
  a broadcast-restore so resume works even when only the coordinator
  has the checkpoint on disk.

Single-process runs pass through every entry point unchanged — the
virtual mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=N``)
stays the default and is bit-identical to a real multi-process run of
the same mesh shape (asserted by tests/test_distributed_multihost.py).
"""
from __future__ import annotations

from . import bootstrap, checkpoint, ingest
from .bootstrap import (barrier, global_mesh, initialize,
                        initialize_from_config, is_distributed,
                        process_count, rank, shutdown)
from .checkpoint import DistributedCheckpointManager, restore_for_resume
from .ingest import load_sharded, shard_row_block

__all__ = [
    "bootstrap", "checkpoint", "ingest",
    "barrier", "global_mesh", "initialize", "initialize_from_config",
    "is_distributed", "process_count", "rank", "shutdown",
    "DistributedCheckpointManager", "restore_for_resume",
    "load_sharded", "shard_row_block",
]
