"""lightgbm_tpu: TPU-native gradient boosting framework.

A from-scratch reimplementation of the LightGBM (v2.3.1) feature surface,
designed TPU-first: binned data as device arrays, histogram construction on
the MXU, split search as vectorized bin scans, distribution via
jax.sharding meshes + XLA collectives. Drop-in Python API:

    import lightgbm_tpu as lgb
    bst = lgb.train(params, lgb.Dataset(X, label=y))
"""
import os as _os

# Persistent XLA compilation cache: tree training launches a family of
# jitted programs per (bucket-size, config); caching makes reruns warm.
if not _os.environ.get("LGBM_TPU_NO_COMP_CACHE"):
    try:
        import jax as _jax
        _cache_dir = _os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            _os.path.join(_os.path.expanduser("~"), ".cache", "lightgbm_tpu_xla"))
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:  # pragma: no cover
        pass

from . import telemetry
from .basic import Booster, Dataset
from .callback import (EarlyStopException, early_stopping, print_evaluation,
                       record_evaluation, record_telemetry, reset_parameter)
from .engine import CVBooster, cv, train
from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor)
from .utils.log import LightGBMError

try:
    from .plotting import (plot_importance, plot_metric, plot_split_value_histogram,
                           plot_tree, create_tree_digraph)
except ImportError:  # matplotlib/graphviz absent
    pass

__version__ = "2.3.1.tpu1"

__all__ = [
    "Dataset", "Booster", "CVBooster",
    "train", "cv",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "early_stopping", "print_evaluation", "record_evaluation",
    "record_telemetry", "reset_parameter", "EarlyStopException",
    "LightGBMError", "telemetry",
    "plot_importance", "plot_split_value_histogram", "plot_metric",
    "plot_tree", "create_tree_digraph",
]
