"""Training callbacks (reference: python-package/lightgbm/callback.py).

Same protocol: callables taking a CallbackEnv namedtuple, ordered by a
`.order` attribute, raising EarlyStopException to halt training.
"""
from __future__ import annotations

import collections
from typing import Callable, List

from .utils import log


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv=True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dict")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to 'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are supported "
                                 "as a mapping from boosting round index to new parameter value.")
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def record_telemetry(period: int = 1) -> Callable:
    """Stream each iteration's telemetry phase summary to the logger
    (one line per `period` iterations). Needs ``telemetry=summary`` or
    ``trace`` — with telemetry off there is nothing recorded and the
    callback stays silent. See docs/Observability.md.

    Runs at order 15: after print_evaluation (10), before
    record_evaluation (20), so the phase line lands next to the metric
    line for the same iteration."""
    from .telemetry import recorder as _recorder

    def _callback(env: CallbackEnv) -> None:
        if period <= 0 or (env.iteration + 1) % period != 0:
            return
        info = _recorder.last_iteration()
        if info is None:
            return
        phases = " ".join(
            f"{name}={secs * 1e3:.1f}ms"
            for name, secs in sorted(info["phases"].items()))
        log.info("[%d]\ttelemetry wall=%.1fms %s", env.iteration + 1,
                 info["wall_s"] * 1e3, phases)
    _callback.order = 15
    return _callback


def checkpoint(directory: str, checkpoint_freq: int = 1, keep_last: int = 3,
               prefix: str = "ckpt") -> Callable:
    """Write a full training checkpoint every `checkpoint_freq`
    iterations (atomic file, checksum manifest, keep-last-`keep_last`
    rotation — see resilience/checkpoint.py). The callback accumulates
    the run's eval history so a resumed run (engine.train
    ``resume_from=``) restores `evals_result` and early-stopping state;
    on resume the engine re-seeds that history automatically.

    Runs at order 25: after record_evaluation (20) and the loss-spike
    guard (22), before early stopping (30), so the iteration that trips
    early stopping is still captured.
    """
    if checkpoint_freq <= 0:
        raise ValueError("checkpoint_freq must be positive")
    history: List = []
    state = {"mgr": None}

    def _callback(env: CallbackEnv) -> None:
        if env.evaluation_result_list:
            history.append([env.iteration,
                            [[r[0], r[1], float(r[2]), bool(r[3])]
                             for r in env.evaluation_result_list]])
        if (env.iteration + 1) % checkpoint_freq == 0:
            if state["mgr"] is None:
                # rank-0 writer + post-save barrier on a real process
                # group; single-process it IS the plain manager
                from .distributed.checkpoint import (
                    DistributedCheckpointManager)
                state["mgr"] = DistributedCheckpointManager(
                    directory, keep_last, prefix)
            # target_rounds rides every checkpoint so a preempted or
            # replacement process can resume with num_boost_round=None
            # and still finish the run's ORIGINAL budget
            path = state["mgr"].save(
                env.model, history=history,
                extra_meta={"target_rounds": int(env.end_iteration)})
            from .telemetry import events as telem_events
            telem_events.emit("checkpoint", iteration=env.iteration,
                              path=path)
            log.debug("checkpoint written: %s", path)
    _callback.order = 25
    _callback._ckpt_history = history
    # the engine's rank-failure recovery resumes from this directory
    # (engine._recover_after_rank_failure finds it by attribute)
    _callback._ckpt_dir = directory
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            log.info("Training until validation scores don't improve for %d rounds",
                     stopping_rounds)
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # higher better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _final_iteration_check(env, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if verbose:
                log.info("Did not meet early stopping. Best iteration is: [%d]\t%s",
                         best_iter[i] + 1,
                         "\t".join(_format_eval_result(x) for x in best_score_list[i]))
            raise EarlyStopException(best_iter[i], best_score_list[i])

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if not enabled[0]:
            return
        for i in range(len(env.evaluation_result_list)):
            score = env.evaluation_result_list[i][2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            eval_name_splitted = env.evaluation_result_list[i][1].split(" ")
            if first_metric_only and first_metric[0] != eval_name_splitted[-1]:
                continue
            if env.evaluation_result_list[i][0] == env.model._train_data_name:
                _final_iteration_check(env, eval_name_splitted, i)
                continue
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info("Early stopping, best iteration is: [%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            _final_iteration_check(env, eval_name_splitted, i)
    _callback.order = 30
    return _callback
