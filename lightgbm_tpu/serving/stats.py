"""Serving-side metrics: request counters + latency histograms.

The training side reports phase costs through utils/timer (accumulating
TIMETAG timers); online inference needs tail latency, not just totals, so
this module adds log-bucketed histograms with p50/p95/p99 readout. The
HTTP front end exposes a `snapshot()` of everything at `/stats`.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List

# log-spaced latency buckets: 1us .. ~137s, x2 per bucket (28 buckets).
_BUCKET_LO = 1e-6
_BUCKET_COUNT = 28


class LatencyHistogram:
    """Fixed log2 buckets over seconds; cheap record, percentile readout.

    Percentiles are bucket upper-bound estimates (standard Prometheus
    histogram semantics), good to within one x2 bucket — plenty for
    p50/p95/p99 serving dashboards.
    """

    def __init__(self):
        self._counts = [0] * (_BUCKET_COUNT + 1)   # +1 overflow bucket
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        if seconds < 0:
            seconds = 0.0
        idx = 0
        if seconds > _BUCKET_LO:
            idx = min(int(math.log2(seconds / _BUCKET_LO)) + 1, _BUCKET_COUNT)
        self._counts[idx] += 1
        self.count += 1
        self.sum += seconds
        self.max = max(self.max, seconds)

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-th percentile."""
        if self.count == 0:
            return 0.0
        target = math.ceil(self.count * p / 100.0)
        seen = 0
        for idx, c in enumerate(self._counts):
            seen += c
            if seen >= target:
                return _BUCKET_LO * (2.0 ** idx)
        return self.max

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_ms": (self.sum / self.count * 1e3) if self.count else 0.0,
            "p50_ms": self.percentile(50) * 1e3,
            "p95_ms": self.percentile(95) * 1e3,
            "p99_ms": self.percentile(99) * 1e3,
            "max_ms": self.max * 1e3,
        }


class ServingStats:
    """Thread-safe counter + histogram registry for one serving stack.

    Besides the flat counters/histograms, per-model-version series
    (`observe_version`) track request count, error count, and a latency
    histogram keyed by the version tag that answered (or was asked for,
    on errors) — the observability half of canary/shadow traffic
    splitting: `/stats` exposes them under `"versions"`, `/metrics`
    renders them as `{version="..."}`-labeled series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._hists: Dict[str, LatencyHistogram] = {}
        self._versions: Dict[str, Dict[str, int]] = {}
        self._vhists: Dict[str, LatencyHistogram] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = LatencyHistogram()
            hist.record(seconds)

    def observe_version(self, version: str, seconds: float = None,
                        error: bool = False) -> None:
        """Count one request against a model version; `seconds` records
        into the version's latency histogram (None on error paths where
        no answer was produced)."""
        version = str(version)
        with self._lock:
            ent = self._versions.setdefault(
                version, {"requests": 0, "errors": 0})
            ent["requests"] += 1
            if error:
                ent["errors"] += 1
            if seconds is not None:
                hist = self._vhists.get(version)
                if hist is None:
                    hist = self._vhists[version] = LatencyHistogram()
                hist.record(seconds)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "latency": {k: h.snapshot() for k, h in self._hists.items()},
                "versions": {
                    v: {"requests": ent["requests"],
                        "errors": ent["errors"],
                        "latency": (self._vhists[v].snapshot()
                                    if v in self._vhists else None)}
                    for v, ent in self._versions.items()},
            }
