"""Brownout load shedding: priority classes over the batcher queue.

Overload is a first-class scenario, not an error path: when a replica
saturates, *which* traffic gets dropped decides whether the SLO
survives. Three priority classes, in strictly decreasing worth:

* ``pinned``    — un-versioned routed traffic, the SLO class; shed
  only when the queue is hard-full.
* ``versioned`` — explicit-version requests (debug, replay, batch
  backfill); shed under acute burn and at reduced queue headroom.
* ``shadow``    — mirrored canary traffic; measurement-only, first to
  go the moment anything burns.

Two mechanisms compose inside `MicroBatcher.submit_async` (the
batcher's existing admission-control point):

* **Headroom** — each class may only fill its fraction of
  ``max_queue_rows`` (defaults 1.0 / 0.8 / 0.5), so a rising queue
  rejects shadow before versioned before pinned with no coordination.
* **Brownout levels** driven by the PR 13 SLO burn-rate monitor:
  level 0 (clear) admits per headroom; level 1 (slow-window burn —
  the "ticket" signal) sheds shadow outright; level 2 (fast-window
  burn — the "page" signal) sheds shadow + versioned, keeping pinned
  SLO traffic as the only queue tenant so its deadline flush holds.

Level transitions are logged through the canary router's audit channel
(one bounded decision log for everything that reroutes traffic),
edge-triggered into the flight recorder (``shed_level`` event +
``shed_level`` gauge), and every rejection counts into
``shed_requests`` plus a per-class ServingStats counter
(``serve_shed_<class>``) for ``/stats``.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..utils import log

__all__ = ["LoadShedder", "PRIORITIES", "DEFAULT_PRIORITY"]

PRIORITIES = ("pinned", "versioned", "shadow")
DEFAULT_PRIORITY = "pinned"
_RANK = {"pinned": 0, "versioned": 1, "shadow": 2}
DEFAULT_HEADROOM = {"pinned": 1.0, "versioned": 0.8, "shadow": 0.5}


class LoadShedder:
    """Priority-class admission policy, consulted by the batcher under
    its queue lock (every decision must be O(dict reads) — the SLO
    window scan behind `level()` is cached for `refresh_s`)."""

    def __init__(self, slo=None, headroom: Optional[Dict[str, float]] = None,
                 refresh_s: float = 0.25,
                 audit: Optional[Callable] = None):
        self.slo = slo                      # serving.slo.SloMonitor | None
        self.headroom = dict(DEFAULT_HEADROOM)
        if headroom:
            self.headroom.update(headroom)
        self.refresh_s = float(refresh_s)
        # audit(action, version=None, **detail): the router's audit
        # channel (CanaryRouter.audit_note) once the app binds it
        self.audit = audit
        self._lock = threading.Lock()
        self._level = 0
        self._manual: Optional[int] = None
        self._last_eval = 0.0
        self._shed: Dict[str, int] = {p: 0 for p in PRIORITIES}

    # -- brownout level --------------------------------------------------
    def set_level(self, level: Optional[int], reason: str = "manual") -> None:
        """Operator/test override (None returns control to the SLO)."""
        with self._lock:
            self._manual = None if level is None else int(level)
        self._publish(self.level(), reason)

    def level(self) -> int:
        """Current brownout level (0 clear / 1 slow burn / 2 fast
        burn). SLO-driven unless a manual override is set."""
        with self._lock:
            manual = self._manual
            if manual is not None:
                return manual
            has_slo = self.slo is not None and self.slo.configured
            if has_slo:
                now = time.monotonic()
                if now - self._last_eval < self.refresh_s:
                    return self._level
                self._last_eval = now
        if not has_slo:
            # no signal source: a cleared manual override means clear,
            # not "whatever level was last published"
            if self._level != 0:
                self._publish(0, "manual_cleared")
            return 0
        fast = self.slo._window_stats(self.slo.fast_window_s)
        slow = self.slo._window_stats(self.slo.slow_window_s)
        level = 2 if fast["burning"] else 1 if slow["burning"] else 0
        reason = (fast.get("violation") or slow.get("violation")
                  or "slo_clear")
        self._publish(level, reason)
        return level

    def _publish(self, level: int, reason: str) -> None:
        with self._lock:
            previous, self._level = self._level, level
        if level == previous:
            return
        telem_counters.set_gauge("shed_level", level)
        telem_events.emit("shed_level", level=level, previous=previous,
                          reason=reason)
        if self.audit is not None:
            try:
                self.audit("shed_level", None, level=level,
                           previous=previous, reason=reason)
            except Exception as exc:   # noqa: BLE001 — audit is advisory
                log.debug("shed: audit hook failed: %s", exc)
        (log.warning if level > previous else log.info)(
            "shed: brownout level %d -> %d (%s)", previous, level, reason)

    # -- admission -------------------------------------------------------
    def admit(self, priority: str, queued_rows: int, incoming_rows: int,
              cap: int) -> Optional[str]:
        """None to admit, else the rejection reason. Called with the
        batcher queue lock held."""
        rank = _RANK.get(priority, 0)
        level = self.level()
        if level >= 1 and rank >= _RANK["shadow"]:
            return self._reject(priority, f"brownout level {level} "
                                          "sheds shadow traffic")
        if level >= 2 and rank >= _RANK["versioned"]:
            return self._reject(priority, f"brownout level {level} "
                                          "sheds versioned traffic")
        limit = cap * self.headroom.get(priority, 1.0)
        if queued_rows + incoming_rows > limit:
            return self._reject(
                priority, f"queue {queued_rows}+{incoming_rows} rows over "
                          f"{priority} headroom {limit:g}/{cap}")
        return None

    def _reject(self, priority: str, reason: str) -> str:
        with self._lock:
            self._shed[priority] = self._shed.get(priority, 0) + 1
        telem_counters.incr("shed_requests")
        return reason

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self._level,
                    "manual": self._manual,
                    "headroom": dict(self.headroom),
                    "shed": dict(self._shed)}
