"""Micro-batching scheduler: coalesce small requests into device batches.

Single-row traffic is the worst case for an accelerator predictor — each
dispatch pays host->device transfer and kernel launch for one row. The
batcher amortizes that: concurrent requests queue up and a background
worker flushes them as one padded batch when either (a) `max_batch` rows
have accumulated or (b) the oldest request has waited `max_delay_ms`.

Operational guarantees:

* Admission control — a full queue (`max_queue_rows`) fast-fails new
  requests with OverloadedError instead of building unbounded latency.
  With a `serving.shed.LoadShedder` attached, admission is priority-
  aware: each request carries a class (pinned / versioned / shadow)
  and the shedder's headroom fractions + brownout level decide who is
  rejected first (shadow, then versioned, pinned last).
* Per-request timeout — requests that exceed their deadline while queued
  are failed at flush time, and waiters give up on their own clock.
* Version consistency — the model version is resolved ONCE per request
  (before any splitting) and once per flush group, so every row of a
  response comes from a single model even while a hot swap lands
  mid-flight; the version used is returned with the result.
* Oversize requests — inputs larger than `max_batch` are split into
  batch-sized chunks pinned to one resolved version and reassembled.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from ..resilience import faults
from ..telemetry import spans as telem_spans
from ..utils import log
from .stats import ServingStats


class OverloadedError(RuntimeError):
    """Queue depth cap hit: shed load instead of queueing."""


class RequestTimeout(TimeoutError):
    """Request exceeded its deadline before a result was produced."""


class _Pending:
    """One queued request; waiters block on `event`."""

    __slots__ = ("x", "n", "version", "raw_score", "t_enqueue", "deadline",
                 "event", "result", "result_version", "error", "trace")

    def __init__(self, x, version, raw_score, timeout_s, trace=None):
        now = time.monotonic()
        self.x = x
        self.n = x.shape[0]
        self.version = version           # concrete version tag
        self.raw_score = raw_score
        self.t_enqueue = now
        self.deadline = now + timeout_s if timeout_s else None
        self.event = threading.Event()
        self.result = None
        self.result_version = None
        self.error = None
        # sampled request timeline (serving.trace.Trace | None): rides
        # the item because the flush worker emits the batcher/predictor
        # spans from its own thread
        self.trace = trace

    def finish(self, result=None, version=None, error=None):
        self.result = result
        self.result_version = version
        self.error = error
        self.event.set()

    def wait(self, timeout_s: Optional[float]):
        if not self.event.wait(timeout_s):
            raise RequestTimeout("request timed out waiting for batch")
        if self.error is not None:
            raise self.error
        return self.result, self.result_version


class MicroBatcher:
    """Request queue + flush worker in front of a PredictorCache.

    `start=False` skips the worker thread: nothing flushes until
    `flush()` is called, which makes batching behavior deterministic for
    tests and embedders with their own event loop.
    """

    def __init__(self, registry, max_batch: int = 256,
                 max_delay_ms: float = 2.0, max_queue_rows: int = 4096,
                 default_timeout_ms: float = 5000.0,
                 stats: Optional[ServingStats] = None, start: bool = True,
                 shed=None):
        self.registry = registry
        # optional serving.shed.LoadShedder: priority-class admission
        # (None keeps the single flat queue cap)
        self.shed = shed
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.default_timeout_s = float(default_timeout_ms) / 1e3
        self.stats = stats or ServingStats()
        self._queue: deque = deque()
        self._queued_rows = 0
        self._cv = threading.Condition()
        self._closed = False
        self._draining = False
        self._worker = None
        if start:
            self._worker = threading.Thread(
                target=self._run, name="lgbm-tpu-batcher", daemon=True)
            self._worker.start()

    # -- client side ----------------------------------------------------
    def submit(self, rows, version: Optional[str] = None,
               raw_score: bool = False,
               timeout_ms: Optional[float] = None,
               trace=None, priority: str = "pinned") -> Tuple[np.ndarray, str]:
        """Blocking predict through the batch queue. Returns
        (scores (N, num_class), model version used)."""
        handles = self.submit_async(rows, version, raw_score, timeout_ms,
                                    trace=trace, priority=priority)
        timeout_s = (self.default_timeout_s if timeout_ms is None
                     else timeout_ms / 1e3)
        # grace on top of the request deadline: expiry is reported by the
        # flusher; the waiter clock is only a backstop against a dead worker
        parts, ver = [], None
        for h in handles:
            out, ver = h.wait(timeout_s + 1.0)
            parts.append(out)
        return (parts[0] if len(parts) == 1
                else np.concatenate(parts, axis=0)), ver

    def submit_async(self, rows, version: Optional[str] = None,
                     raw_score: bool = False,
                     timeout_ms: Optional[float] = None,
                     trace=None, priority: str = "pinned") -> List[_Pending]:
        """Enqueue without blocking for the result; returns the pending
        handles (one per <=max_batch chunk, in row order)."""
        x = np.ascontiguousarray(np.asarray(rows, dtype=np.float32))
        if x.ndim == 1:
            x = x.reshape(1, -1)
        timeout_s = (self.default_timeout_s if timeout_ms is None
                     else timeout_ms / 1e3)
        # pin the version before splitting: every chunk of one request
        # must be served by the same model even across a hot swap
        concrete = self.registry.get(version).version
        chunks = ([x] if x.shape[0] <= self.max_batch else
                  [x[i:i + self.max_batch]
                   for i in range(0, x.shape[0], self.max_batch)])
        if len(chunks) > 1:
            self.stats.incr("serve_requests_split")
        handles = []
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._draining:
                # graceful shutdown: stop admitting, keep flushing what
                # is already queued (run_http_server drains on exit)
                self.stats.incr("serve_rejected_draining")
                raise OverloadedError("batcher is draining")
            if self.shed is not None:
                # priority-aware admission: brownout level + per-class
                # queue headroom (shadow rejected first, pinned last)
                reason = self.shed.admit(priority, self._queued_rows,
                                         x.shape[0], self.max_queue_rows)
                if reason is not None:
                    self.stats.incr("serve_shed_" + priority)
                    raise OverloadedError(f"shed [{priority}]: {reason}")
            if self._queued_rows + x.shape[0] > self.max_queue_rows:
                self.stats.incr("serve_rejected_overload")
                raise OverloadedError(
                    f"queue full ({self._queued_rows} rows queued, "
                    f"cap {self.max_queue_rows})")
            for chunk in chunks:
                item = _Pending(chunk, concrete, raw_score, timeout_s,
                                trace=trace)
                self._queue.append(item)
                self._queued_rows += chunk.shape[0]
                handles.append(item)
            self.stats.incr("serve_requests")
            self._cv.notify_all()
        return handles

    # -- flush side -----------------------------------------------------
    def flush(self) -> int:
        """Drain and execute one batch group synchronously; returns rows
        flushed (0 on an empty queue — a no-op)."""
        batch = self._pop_batch()
        if not batch:
            return 0
        return self._execute(batch)

    def _pop_batch(self) -> List[_Pending]:
        """Pop a FIFO prefix of compatible requests (same version +
        raw_score) totalling <= max_batch rows."""
        with self._cv:
            if not self._queue:
                return []
            first = self._queue[0]
            group_key = (first.version, first.raw_score)
            batch, rows = [], 0
            while self._queue:
                item = self._queue[0]
                if (item.version, item.raw_score) != group_key:
                    break
                if batch and rows + item.n > self.max_batch:
                    break
                batch.append(self._queue.popleft())
                rows += item.n
            self._queued_rows -= rows
            return batch

    def _execute(self, batch: List[_Pending]) -> int:
        with telem_spans.span("serve_flush", requests=len(batch)):
            return self._execute_inner(batch)

    def _execute_inner(self, batch: List[_Pending]) -> int:
        # fault site: an injected delay here models a stalled device /
        # slow predictor, driving requests past their deadlines so the
        # timeout path below is deterministically testable
        faults.sleep_point("serve_flush")
        now = time.monotonic()
        live: List[_Pending] = []
        for item in batch:
            # queue wait = enqueue -> flush, expired requests included:
            # the tail of this histogram is exactly what admission
            # control and max_delay_ms tuning need to see
            self.stats.observe("serve_queue_wait", now - item.t_enqueue)
            if item.deadline is not None and now > item.deadline:
                self.stats.incr("serve_timeouts")
                item.finish(error=RequestTimeout(
                    "request expired in queue before flush"))
            else:
                live.append(item)
        if not live:
            return 0
        version, raw_score = live[0].version, live[0].raw_score
        x = (live[0].x if len(live) == 1
             else np.concatenate([i.x for i in live], axis=0))
        try:
            t0 = time.monotonic()
            # fault site: fail_request@version= clauses raise here — the
            # injected per-version error spike the canary router demotes on
            faults.request_point(version)
            model = self.registry.get(version)
            out = self.registry.predictor.predict(model, x, raw_score)
            exec_s = time.monotonic() - t0
            self.stats.observe("serve_batch_exec", exec_s)
            self.stats.incr("serve_batches")
            self.stats.incr("serve_rows", x.shape[0])
        except Exception as exc:   # noqa: BLE001 — propagate to waiters
            log.warning("serving: batch of %d rows failed: %s",
                        x.shape[0], exc)
            self.stats.incr("serve_batch_errors")
            for item in live:
                item.finish(error=exc)
            return x.shape[0]
        off = 0
        for item in live:
            if item.trace is not None:
                # batcher span = queue wait; predictor span = this
                # item's share of the device execute (whole-batch time,
                # batch context attached so amortization is visible)
                item.trace.span("batcher", now - item.t_enqueue,
                                rows=item.n, batch_requests=len(live),
                                version=version)
                item.trace.span("predictor", exec_s,
                                rows=item.n, batch_rows=x.shape[0],
                                version=version)
            item.finish(result=out[off:off + item.n], version=version)
            off += item.n
        return x.shape[0]

    # -- worker ---------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                first = self._queue[0]
                flush_at = first.t_enqueue + self.max_delay_s
                # linger for more rows until the batch fills or the
                # oldest request's coalescing deadline passes
                while (self._queued_rows < self.max_batch
                       and not self._closed):
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
            batch = self._pop_batch()
            if batch:
                self._execute(batch)

    # -- liveness / shutdown --------------------------------------------
    def alive(self) -> bool:
        """Liveness for /healthz: open for business and (when a worker
        was started) the worker thread still running. Inline mode
        (start=False) has no worker to die, so open == alive."""
        if self._closed or self._draining:
            return False
        return self._worker is None or self._worker.is_alive()

    @property
    def queued_rows(self) -> int:
        with self._cv:
            return self._queued_rows

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout_s: float = 5.0) -> None:
        """Graceful shutdown: stop admitting new requests, flush every
        batch already in the queue (the worker keeps flushing; inline
        mode flushes here), then close. In-flight waiters get real
        results — only requests arriving after the drain started are
        rejected."""
        with self._cv:
            if self._closed:
                return
            self._draining = True
            self._cv.notify_all()
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while time.monotonic() < deadline:
            if self._worker is None or not self._worker.is_alive():
                # no worker to flush for us: do it inline
                if self.flush() == 0 and self.queued_rows == 0:
                    break
            else:
                if self.queued_rows == 0:
                    break
                time.sleep(0.005)
        self.close()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
        while True:
            batch = self._pop_batch()
            if not batch:
                break
            for item in batch:
                item.finish(error=RuntimeError("batcher closed"))
