"""In-process serving API + stdlib HTTP front end (JSON, no deps).

The app layer (`ServingApp`) is plain dict-in/dict-out so embedders and
tests drive it without sockets; the HTTP layer is a thin
ThreadingHTTPServer adapter over it.

Endpoints:

* ``POST /predict``  {"rows": [[...], ...], "raw_score": false,
  "version": "v1" | "latest", "timeout_ms": 100} ->
  {"predictions": [...], "version": "v1", "num_rows": N}; an incoming
  ``X-Request-Id`` header is honored (else generated) and always
  echoed back — sampled requests additionally emit a linked
  trace_span timeline (serving/trace.py)
* ``GET  /stats``    counters + latency histograms (p50/p95/p99) +
  compiled-predictor cache info
* ``GET  /metrics``  the same counters in Prometheus text format, plus
  the process-wide telemetry counters (XLA compile events/seconds,
  transfer bytes, collective retries, peak RSS) — scrape-ready
* ``GET  /models``   loaded versions
* ``POST /models``   {"model_file": path} | {"model_str": text}
  [, "version": tag] — load + warm + hot-swap to latest
* ``GET  /healthz``  registry + batcher liveness: 200 with
  ``status=ok`` when routable, 503 with ``status=draining``/
  ``degraded`` during graceful shutdown or after a dead batcher worker
* ``GET  /router``   canary router state (stable/canary/weight/history)
* ``GET  /router/audit``  the router decision log: every transition
  with the exact gate snapshot that justified it
* ``POST /router``   {"action": "stable"|"deploy"|"promote"|"demote"
  [, "version", "weight", "shadow"]} — drive the canary state machine
* ``POST /drain``    graceful drain for rolling restarts: stop
  admitting, flush the queue, reply with the final health snapshot
* ``POST /feedback`` {"version": "v1", "labels": [...],
  "scores": [...]} — record ground-truth labels against the version
  that answered (the /predict response carries it); feeds the router's
  labeled-feedback AUC promotion gate (serving/feedback.py)
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..fleet.router import CanaryRouter
from ..utils import log
from . import trace as serve_trace
from .batcher import MicroBatcher, OverloadedError, RequestTimeout
from .registry import ModelNotFound, ModelRegistry
from .shed import PRIORITIES
from .stats import ServingStats


class BadRequest(ValueError):
    pass


class ServingApp:
    """Transport-agnostic serving facade: registry + batcher + stats +
    canary router. The router is idle (pass-through to `latest`) until a
    stable version is installed via `POST /router {"action":
    "stable"}` or `app.router.set_stable`.

    Optional observability attachments: `slo` (serving.slo.SloMonitor —
    folds into /healthz, /metrics and the router's demotion gate),
    `drift` (serving.drift.DriftMonitor — windows served traffic
    against the model's training baseline) and `shed`
    (serving.shed.LoadShedder — priority-class brownout admission in
    the batcher, level changes logged to the router audit channel)."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 batcher: Optional[MicroBatcher] = None,
                 stats: Optional[ServingStats] = None,
                 router: Optional[CanaryRouter] = None,
                 slo=None, drift=None, shed=None, feedback=None,
                 **batcher_kwargs):
        from .feedback import FeedbackStore
        self.registry = registry or ModelRegistry()
        self.stats = stats or ServingStats()
        self.shed = shed
        self.batcher = batcher or MicroBatcher(
            self.registry, stats=self.stats, shed=shed, **batcher_kwargs)
        if shed is not None and self.batcher.shed is None:
            self.batcher.shed = shed
        self.slo = slo
        self.drift = drift
        self.feedback = feedback or FeedbackStore()
        self.router = router or CanaryRouter(self.registry, self.stats,
                                             slo=slo,
                                             feedback=self.feedback)
        if slo is not None and getattr(self.router, "slo", None) is None:
            self.router.slo = slo
        if getattr(self.router, "feedback", None) is None:
            self.router.feedback = self.feedback
        if shed is not None and shed.audit is None:
            # brownout level changes land in the same bounded decision
            # log as canary transitions (GET /router/audit)
            shed.audit = self.router.audit_note

    # ------------------------------------------------------------------
    def predict(self, payload: dict,
                request_id: Optional[str] = None) -> dict:
        rows = payload.get("rows")
        if rows is None:
            raise BadRequest("missing 'rows'")
        raw_score = bool(payload.get("raw_score", False))
        version = payload.get("version")
        # priority class for shed admission: explicit tag wins, else
        # routed traffic is "pinned" (the SLO class) and explicit-
        # version requests are "versioned" (replay/debug traffic)
        priority = payload.get("priority") or (
            "versioned" if version else "pinned")
        if priority not in PRIORITIES:
            raise BadRequest(f"unknown priority {priority!r} "
                             f"(one of {', '.join(PRIORITIES)})")
        # sampled per-request timeline (None when sampled out / tracing
        # off); the request id itself is handled by the HTTP layer so
        # the response header exists whether or not this is sampled
        trace = serve_trace.start(request_id or payload.get("request_id"))
        # an explicit version tag bypasses the router (debugging, shadow
        # replay); everything else is routed stable/canary per weight
        routed = version is None and self.router.active
        if routed:
            t_route = time.monotonic()
            version = self.router.route()
            if trace is not None:
                trace.span("router", time.monotonic() - t_route,
                           version=version)
        t0 = time.monotonic()
        try:
            out, version_used = self.batcher.submit(
                rows, version=version, raw_score=raw_score,
                timeout_ms=payload.get("timeout_ms"), trace=trace,
                priority=priority)
        except Exception as exc:
            # error series keyed by the *requested* tag — no answer
            # resolved one, and "which version is erroring" is exactly
            # the canary question these labels exist to answer
            requested = version or self.registry.latest or "latest"
            dt = time.monotonic() - t0
            self.stats.observe_version(requested, error=True)
            if self.slo is not None:
                self.slo.observe(requested, dt, error=True)
            if trace is not None:
                trace.span("server", dt, version=requested,
                           status="error", error=type(exc).__name__)
            if routed:
                # errors drive the demotion gate — evaluate before the
                # error propagates so a bleeding canary is cut promptly
                self.router.evaluate()
            raise
        dt = time.monotonic() - t0
        self.stats.observe("serve_request", dt)
        self.stats.observe_version(version_used, dt)
        if self.slo is not None:
            self.slo.observe(version_used, dt)
        if self.drift is not None:
            self.drift.observe(rows, out, version=version_used)
        if routed:
            shadow = self.router.shadow_target()
            if shadow is not None:
                self._mirror(rows, shadow, raw_score)
            self.router.evaluate()
        preds = (out[:, 0] if out.ndim == 2 and out.shape[1] == 1 else out)
        if trace is not None:
            trace.span("server", dt, version=version_used,
                       rows=int(out.shape[0]), status="ok")
        return {"predictions": preds.tolist(), "version": version_used,
                "num_rows": int(out.shape[0])}

    def _mirror(self, rows, version: str, raw_score: bool) -> None:
        """Shadow traffic: replay the request against `version` off the
        response path. The caller never waits; results are discarded but
        the canary's per-version counters accumulate, which is the whole
        point — measurement without user exposure."""
        self.stats.incr("serve_shadow_mirrored")

        def _run():
            t0 = time.monotonic()
            try:
                _, ver = self.batcher.submit(rows, version=version,
                                             raw_score=raw_score,
                                             priority="shadow")
                self.stats.observe_version(ver, time.monotonic() - t0)
            except Exception as exc:   # noqa: BLE001 — shadow never throws
                self.stats.observe_version(version, error=True)
                log.debug("serving: shadow mirror to %s failed: %s",
                          version, exc)
            self.router.evaluate()

        threading.Thread(target=_run, daemon=True,
                         name="lgbm-tpu-shadow").start()

    def feedback_record(self, payload: dict) -> dict:
        """POST /feedback: ground-truth labels for earlier predictions,
        keyed by the version that answered them. Labels accumulate in
        the bounded per-version store the router's AUC promotion gate
        reads."""
        version = payload.get("version")
        if not version:
            raise BadRequest("feedback needs 'version' (echo the one "
                             "the /predict response carried)")
        labels = payload.get("labels")
        scores = payload.get("scores", payload.get("predictions"))
        if labels is None or scores is None:
            raise BadRequest("feedback needs 'labels' and 'scores'")
        try:
            count = self.feedback.record(version, labels, scores)
        except ValueError as exc:
            raise BadRequest(str(exc)) from exc
        self.stats.incr("serve_feedback_batches")
        # fresh labels are gate evidence — re-judge the canary now
        # rather than waiting for the next predict
        self.router.evaluate()
        return {"version": version, "recorded": len(labels),
                "total_labels": count}

    def load_model(self, payload: dict) -> dict:
        if "model_file" in payload:
            source = payload["model_file"]
        elif "model_str" in payload:
            source = payload["model_str"]
        else:
            raise BadRequest("need 'model_file' or 'model_str'")
        version = self.registry.load(source, version=payload.get("version"))
        self.stats.incr("serve_model_loads")
        return {"version": version, "latest": True}

    def models(self) -> dict:
        return {"models": self.registry.versions(),
                "latest": self.registry.latest}

    def stats_snapshot(self) -> dict:
        snap = self.stats.snapshot()
        snap["predictor_cache"] = self.registry.predictor.cache_info()
        snap["models"] = self.registry.versions()
        snap["router"] = self.router.snapshot()
        snap["feedback"] = self.feedback.snapshot()
        if self.registry.export_cache is not None:
            snap["export_cache"] = self.registry.export_cache.info()
        if self.slo is not None:
            snap["slo"] = self.slo.snapshot()
        if self.drift is not None:
            snap["drift"] = self.drift.snapshot()
        if self.shed is not None:
            snap["shed"] = self.shed.snapshot()
        return snap

    # -- fleet control ---------------------------------------------------
    def router_action(self, payload: dict) -> dict:
        """POST /router — the canary state machine's control surface:
        {"action": "stable"|"deploy"|"promote"|"demote", ...}."""
        action = payload.get("action")
        if action == "stable":
            version = payload.get("version") or self.registry.latest
            if version is None:
                raise BadRequest("no version to make stable")
            self.router.set_stable(version)
        elif action == "deploy":
            version = payload.get("version")
            if not version:
                raise BadRequest("deploy needs 'version'")
            self.router.deploy(version,
                               weight=float(payload.get("weight", 0.10)),
                               shadow=bool(payload.get("shadow", False)))
        elif action == "promote":
            self.router.promote()
        elif action == "demote":
            self.router.demote(payload.get("reason", "manual"))
        else:
            raise BadRequest(f"unknown router action {action!r}")
        return self.router.snapshot()

    def metrics_text(self) -> str:
        """Prometheus text format: serving counters/latency + process
        telemetry counters (served at GET /metrics, next to /stats)."""
        from .. import telemetry
        return telemetry.prometheus_text(
            self.stats.snapshot(), self.registry.predictor.cache_info(),
            slo=self.slo.snapshot() if self.slo is not None else None,
            drift=self.drift.snapshot() if self.drift is not None
            else None)

    def health(self) -> dict:
        """Liveness for load balancers: registry + batcher state, plus
        the SLO fast window when a monitor is attached. ``status`` is
        ``ok`` (routable), ``draining`` (shutdown in progress — stop
        routing, in-flight work still completes) or ``degraded``
        (batcher worker dead/closed, or the fast SLO window is burning
        — servable but violating its objectives). The HTTP layer maps
        non-``ok`` to 503. Degradation is *explained*: ``reason`` names
        which SLO window is burning (with the violation string) or that
        the batcher died, and ``shed_level`` reports the current
        brownout level — one curl tells an operator (or the fleet
        gateway, which records it per ejection) exactly why a replica
        left rotation."""
        batcher_alive = self.batcher.alive()
        draining = self.batcher.draining
        status = ("draining" if draining
                  else "ok" if batcher_alive else "degraded")
        reasons = []
        if not draining and not batcher_alive:
            reasons.append("batcher_dead")
        body = {"status": status,
                "model_loaded": self.registry.latest is not None,
                "batcher_alive": batcher_alive,
                "draining": draining,
                "queued_rows": self.batcher.queued_rows}
        if self.slo is not None:
            snap = self.slo.snapshot()
            body["slo"] = snap
            if snap["fast"].get("burning"):
                if body["status"] == "ok":
                    body["status"] = "degraded"
                reasons.append("slo_fast_burn: "
                               + str(snap["fast"].get("violation")))
            elif snap["slow"].get("burning"):
                # slow burn doesn't degrade routability, but the reason
                # is surfaced so the shed level below is explainable
                reasons.append("slo_slow_burn: "
                               + str(snap["slow"].get("violation")))
        body["shed_level"] = (self.shed.level()
                              if self.shed is not None else 0)
        body["reason"] = "; ".join(reasons) if reasons else None
        return body

    def drain(self, timeout_s: float = 5.0) -> None:
        """Graceful shutdown: stop admitting, flush in-flight batches,
        then close the batcher."""
        self.batcher.drain(timeout_s)

    def close(self) -> None:
        self.batcher.close()
        if self.drift is not None:
            self.drift.close()


class _Handler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-serve/1.0"
    protocol_version = "HTTP/1.1"

    @property
    def app(self) -> ServingApp:
        return self.server.app

    def log_message(self, fmt, *args):   # route to our logger, not stderr
        log.debug("http: " + fmt, *args)

    def _reply(self, code: int, body: dict,
               headers: Optional[dict] = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(data)

    def _reply_text(self, code: int, text: str,
                    content_type: str = "text/plain; version=0.0.4") -> None:
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _payload(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            return {}
        try:
            return json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError as exc:
            raise BadRequest(f"invalid JSON body: {exc}") from exc

    def _dispatch(self, fn, headers: Optional[dict] = None) -> None:
        try:
            self._reply(200, fn(), headers)
        except BadRequest as exc:
            self._reply(400, {"error": str(exc)}, headers)
        except ModelNotFound as exc:
            self._reply(404, {"error": str(exc)}, headers)
        except OverloadedError as exc:
            self._reply(429, {"error": str(exc)}, headers)
        except RequestTimeout as exc:
            self._reply(504, {"error": str(exc)}, headers)
        except ValueError as exc:
            self._reply(400, {"error": str(exc)}, headers)
        except Exception as exc:   # noqa: BLE001 — JSON 500, keep serving
            log.warning("serving: internal error: %s", exc)
            self._reply(500, {"error": str(exc)}, headers)

    def do_GET(self):
        if self.path == "/stats":
            self._dispatch(self.app.stats_snapshot)
        elif self.path == "/metrics":
            try:
                self._reply_text(200, self.app.metrics_text())
            except Exception as exc:   # noqa: BLE001 — keep serving
                log.warning("serving: /metrics failed: %s", exc)
                self._reply(500, {"error": str(exc)})
        elif self.path == "/models":
            self._dispatch(self.app.models)
        elif self.path == "/router":
            self._dispatch(lambda: self.app.router.snapshot())
        elif self.path == "/router/audit":
            # the decision log: every stable/deploy/promote/demote with
            # the gate snapshot (counter deltas + thresholds) it was
            # decided on, plus the latest "hold" evaluation
            self._dispatch(lambda: self.app.router.audit_snapshot())
        elif self.path in ("/healthz", "/health"):
            # non-ok health is a 503 so load balancers stop routing
            # while drain/degradation is in progress
            try:
                body = self.app.health()
                self._reply(200 if body.get("status") == "ok" else 503,
                            body)
            except Exception as exc:   # noqa: BLE001 — keep serving
                self._reply(500, {"error": str(exc)})
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        if self.path == "/predict":
            # every request gets an id (incoming X-Request-Id honored)
            # and the id always comes back in the response header —
            # whether or not this request was sampled for a full trace
            rid = ((self.headers.get("X-Request-Id") or "").strip()
                   or serve_trace.new_request_id())
            self._dispatch(
                lambda: self.app.predict(self._payload(), request_id=rid),
                headers={"X-Request-Id": rid})
        elif self.path == "/models":
            self._dispatch(lambda: self.app.load_model(self._payload()))
        elif self.path == "/router":
            self._dispatch(lambda: self.app.router_action(self._payload()))
        elif self.path == "/drain":
            # rollout tooling: stop admitting, flush in-flight work,
            # answer when the queue is empty — the caller then restarts
            # this process knowing zero requests were dropped
            def _drain():
                payload = self._payload()
                self.app.drain(float(payload.get("timeout_s", 5.0)))
                return self.app.health()
            self._dispatch(_drain)
        elif self.path == "/feedback":
            self._dispatch(
                lambda: self.app.feedback_record(self._payload()))
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})


def make_http_server(app: ServingApp, host: str = "127.0.0.1",
                     port: int = 8080) -> ThreadingHTTPServer:
    """Bind (port=0 for ephemeral) and return the server; caller runs
    serve_forever(), typically via `run_http_server`."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.app = app
    httpd.daemon_threads = True
    return httpd


def run_http_server(app: ServingApp, host: str = "127.0.0.1",
                    port: int = 8080, background: bool = False):
    httpd = make_http_server(app, host, port)
    log.info("serving: listening on http://%s:%d (POST /predict, "
             "GET /stats)", *httpd.server_address[:2])
    if background:
        t = threading.Thread(target=httpd.serve_forever,
                             name="lgbm-tpu-http", daemon=True)
        t.start()
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:   # pragma: no cover
        pass
    finally:
        # graceful exit: stop admitting, flush what is queued, then
        # close — in-flight requests get answers, not connection resets
        app.drain()
        httpd.server_close()
        app.close()
    return httpd
