"""Per-request trace propagation through the serving path.

Every HTTP request gets a request id (an incoming ``X-Request-Id``
header is honored, else one is generated) and the id is always returned
in the response header — correlation is free. A *sampled* subset of
requests additionally gets a full timeline: linked ``trace_span``
events in the flight-recorder stream, one per hop of the serving path
(``router`` version selection, ``batcher`` queue wait, ``predictor``
device execute, ``server`` end-to-end), all carrying the same
``trace`` id so `tools/run_report.py` and ad-hoc greps can reassemble
a single request's journey.

Sampling is deterministic error-diffusion (an accumulator adds the
rate per request and emits when it crosses 1.0), so `rate=0.25` traces
exactly every 4th request — no RNG, reproducible in tests. The rate
comes from ``LGBM_TPU_TRACE_SAMPLE`` (or `configure(rate)`, which the
CLI wires to the ``serve_trace_sample`` param); the default is 0.0 and
tracing also requires the event stream to be enabled, so the untraced
hot path costs one module-global read plus one float add — the same
no-op discipline as spans/events.

The Trace object travels *explicitly* with the request (a slot on the
batcher's `_Pending`), not via thread-locals: the flush worker emits
the batcher/predictor spans from its own thread.
"""
from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Optional

from ..telemetry import events

__all__ = ["configure", "sample_rate", "new_request_id", "start",
           "Trace", "reset"]

_lock = threading.Lock()
_rate: Optional[float] = None      # None = parse env on first use
_accum = 0.0                       # error-diffusion sampling accumulator


def configure(rate: Optional[float] = None) -> float:
    """Install a sampling rate in [0, 1] (None re-reads
    ``LGBM_TPU_TRACE_SAMPLE``). Returns the active rate."""
    global _rate, _accum
    if rate is None:
        raw = os.environ.get("LGBM_TPU_TRACE_SAMPLE", "").strip()
        try:
            rate = float(raw) if raw else 0.0
        except ValueError:
            rate = 0.0
    with _lock:
        _rate = min(1.0, max(0.0, float(rate)))
        _accum = 0.0
        return _rate


def sample_rate() -> float:
    if _rate is None:
        configure()
    return _rate


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def start(request_id: Optional[str] = None) -> Optional["Trace"]:
    """Begin a trace for one request if it is sampled. Returns None
    (sampled out / tracing off / events disabled) or a Trace whose id
    is `request_id` when given."""
    if not events.enabled():
        return None
    rate = sample_rate()
    if rate <= 0.0:
        return None
    global _accum
    with _lock:
        _accum += rate
        if _accum < 1.0:
            return None
        _accum -= 1.0
    return Trace(request_id or new_request_id())


class Trace:
    """One sampled request's timeline. `span(name, dur_s, **fields)`
    records a linked ``trace_span`` event; `t_offset_ms` is the span's
    start relative to trace start, so spans reassemble into a timeline
    regardless of emission order across threads."""

    __slots__ = ("trace_id", "t0")

    def __init__(self, trace_id: str):
        self.trace_id = str(trace_id)
        self.t0 = time.monotonic()

    def span(self, span: str, dur_s: float, **fields) -> None:
        start_s = max(0.0, time.monotonic() - self.t0 - dur_s)
        events.emit("trace_span", trace=self.trace_id, span=span,
                    t_offset_ms=round(start_s * 1e3, 3),
                    dur_ms=round(float(dur_s) * 1e3, 3), **fields)


def reset() -> None:
    """Forget the cached rate/accumulator (tests that monkeypatch
    LGBM_TPU_TRACE_SAMPLE re-parse on next use)."""
    global _rate, _accum
    with _lock:
        _rate = None
        _accum = 0.0
