"""Online inference subsystem.

Layers (each usable on its own):

* `registry` — versioned, hot-swappable PreparedModels with warm-up,
  optional persistent export cache + device placement (fleet hooks)
* `predictor` — AOT-compiled, shape-bucketed predictor cache with
  LRU eviction, router pins, and donated/staged batch buffers
* `batcher` — micro-batching scheduler with admission control
* `server` — in-process API + stdlib JSON-over-HTTP front end, with
  the fleet canary router on the un-versioned request path
* `stats` — request counters and latency histograms
* `trace` — sampled per-request span traces (X-Request-Id propagation)
* `slo` — dual-window p99/error-rate burn-rate monitor
* `drift` — training-baseline vs served-traffic PSI drift monitor
* `shed` — brownout load shedding: priority classes (pinned /
  versioned / shadow) over the batcher queue, levels driven by `slo`
* `transforms` — edge feature transforms: raw CSV/JSON rows binned by
  the model's training-time mappers (gateway side)

The fleet control plane (persistent compiled-predictor cache,
multi-model placement, canary/shadow router) lives in
`lightgbm_tpu.fleet` and plugs in through ModelRegistry/ServingApp.

Quick start::

    from lightgbm_tpu.serving import ModelRegistry, MicroBatcher, ServingApp
    app = ServingApp()
    app.registry.load(booster)            # tensorize + pre-compile buckets
    out, version = app.batcher.submit([[...row...]])

or over HTTP: ``python -m lightgbm_tpu task=serve input_model=model.txt``.
"""
from .batcher import MicroBatcher, OverloadedError, RequestTimeout
from .drift import DriftMonitor
from .predictor import PredictorCache, PreparedModel
from .registry import ModelNotFound, ModelRegistry
from .server import ServingApp, make_http_server, run_http_server
from .shed import LoadShedder
from .slo import SloMonitor
from .stats import LatencyHistogram, ServingStats
from .transforms import EdgeTransform

__all__ = [
    "MicroBatcher", "OverloadedError", "RequestTimeout",
    "DriftMonitor", "SloMonitor", "LoadShedder", "EdgeTransform",
    "PredictorCache", "PreparedModel",
    "ModelNotFound", "ModelRegistry",
    "ServingApp", "make_http_server", "run_http_server",
    "LatencyHistogram", "ServingStats",
]
