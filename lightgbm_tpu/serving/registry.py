"""Versioned model registry with warm-up and hot swap.

The serving unit of deployment is a PreparedModel: tensorized once
(through the GBDT ensemble-arrays cache), warmed by pre-compiling the
scoring executable for the configured batch buckets, then published
atomically. Readers never see a half-loaded model: `get()` resolves
against an immutable snapshot, and swapping is one dict+pointer update
under the lock. Old versions stay queryable until `unload()`.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..utils import log
from ..utils.timer import timer
from .predictor import PredictorCache, PreparedModel

DEFAULT_WARM_BUCKETS = (1, 16, 256)


class ModelNotFound(KeyError):
    pass


class ModelRegistry:
    """Holds live model versions and the shared compiled-predictor cache."""

    def __init__(self, predictor: Optional[PredictorCache] = None,
                 warm_buckets: Sequence[int] = DEFAULT_WARM_BUCKETS,
                 warm_raw_score: Sequence[bool] = (False,),
                 export_cache=None, placement=None):
        self.predictor = predictor or PredictorCache()
        self.warm_buckets = tuple(warm_buckets)
        self.warm_raw_score = tuple(warm_raw_score)
        # fleet hooks: a fleet.ExportCache persists warm executables
        # across process restarts; a fleet.PlacementPlan pins versions
        # to distinct devices. Both optional — None keeps the
        # single-model single-device behavior.
        self.export_cache = export_cache
        self.placement = placement
        self._lock = threading.RLock()
        self._models: Dict[str, PreparedModel] = {}
        self._latest: Optional[str] = None
        self._pinned_versions: Dict[str, tuple] = {}
        self._version_counter = itertools.count(1)
        # version -> training-time drift baseline (serving.drift),
        # auto-discovered from a <model>.drift.json sidecar or the
        # booster's cached baseline at load()
        self.drift_baselines: Dict[str, dict] = {}

    # ------------------------------------------------------------------
    def load(self, source, version: Optional[str] = None,
             num_iteration: Optional[int] = None,
             warm: bool = True) -> str:
        """Prepare `source` (Booster, GBDT, model string, or model file
        path) for serving and publish it as `version` (auto 'v<N>' when
        None). Warm-up happens BEFORE publication, so a hot swap never
        exposes a cold model to traffic. Returns the version id."""
        gbdt = self._to_gbdt(source)
        if num_iteration is None:
            # parity with Booster.predict: an early-stopped booster
            # serves its best iteration unless told otherwise
            best = getattr(source, "best_iteration", -1)
            if isinstance(best, int) and best > 0:
                num_iteration = best
        with self._lock:
            ver = version or f"v{next(self._version_counter)}"
            if ver in self._models:
                raise ValueError(f"model version {ver!r} already loaded")
        from ..telemetry import events as telem_events
        with timer("serve_model_load"):
            t0 = time.monotonic()
            device = (self.placement.assign(ver)
                      if self.placement is not None else None)
            prepared = PreparedModel(gbdt, ver, num_iteration,
                                     device=device)
            restored = {}
            if self.export_cache is not None:
                # restore serialized executables BEFORE warm-up: a full
                # restore turns the warm loop below into pure cache hits
                # (zero compiles) — the fleet restart property
                restored = self.export_cache.restore(
                    prepared, self.predictor, self.warm_buckets,
                    self.warm_raw_score)
            if warm:
                for raw in self.warm_raw_score:
                    for b in self.warm_buckets:
                        self.predictor.warm(prepared, b, raw_score=raw)
                telem_events.emit(
                    "serve_warmup", version=ver,
                    buckets=list(self.warm_buckets),
                    restored=restored.get("restored", 0),
                    warm_s=round(time.monotonic() - t0, 6))
            if self.export_cache is not None:
                self.export_cache.save(prepared, self.predictor)
        baseline = self._discover_drift_baseline(source)
        with self._lock:
            previous = self._latest
            self._models[ver] = prepared
            self._latest = ver
            if baseline is not None:
                self.drift_baselines[ver] = baseline
        telem_events.emit("serve_swap", version=ver, previous=previous)
        log.info("serving: loaded model %s (%d trees, %d features)",
                 ver, prepared.n_trees, prepared.num_features)
        return ver

    def _discover_drift_baseline(self, source) -> Optional[dict]:
        """Find the training-time drift baseline that rode along with
        `source`: a ``<path>.drift.json`` sidecar when loading from a
        model file, or the baseline cached on a live Booster/GBDT."""
        import os
        from . import drift as serve_drift
        if isinstance(source, str) and "\n" not in source \
                and "Tree=" not in source and os.path.exists(
                    source + ".drift.json"):
            return serve_drift.load_baseline(source + ".drift.json")
        gbdt = (source._gbdt if hasattr(source, "_gbdt") else source)
        cached = getattr(gbdt, "_drift_baseline", None)
        return cached if isinstance(cached, dict) else None

    def _to_gbdt(self, source):
        if hasattr(source, "_gbdt"):           # Booster
            return source._gbdt
        if hasattr(source, "ensemble_arrays"):  # GBDT
            return source
        from ..models.gbdt import GBDT
        if isinstance(source, str):
            if "\n" in source or "Tree=" in source:
                return GBDT.load_model_from_string(source)
            return GBDT.load_model(source)
        raise TypeError(f"cannot load model from {type(source).__name__}")

    # ------------------------------------------------------------------
    def get(self, version: Optional[str] = None) -> PreparedModel:
        """Resolve a version tag (None/'latest' -> newest) to its model."""
        with self._lock:
            if version in (None, "latest"):
                version = self._latest
            if version is None:
                raise ModelNotFound("no model loaded")
            model = self._models.get(version)
            if model is None:
                raise ModelNotFound(f"unknown model version {version!r}")
            return model

    def unload(self, version: str) -> None:
        with self._lock:
            if version not in self._models:
                raise ModelNotFound(f"unknown model version {version!r}")
            del self._models[version]
            self.drift_baselines.pop(version, None)
            if self._latest == version:
                self._latest = (max(self._models) if self._models else None)
        self.unpin_version(version)
        if self.placement is not None:
            self.placement.release(version)

    # -- eviction pins (fleet router) -----------------------------------
    def pin_version(self, version: str) -> None:
        """Protect a routed version's executables from LRU eviction. Pins
        are refcounted by shape signature: two same-shape versions (the
        periodic-retrain case) share executables, so the signature stays
        pinned until the LAST pinned version releases it."""
        model = self.get(version)
        with self._lock:
            self._pinned_versions[version] = model.shape_sig
        self.predictor.pin(model.shape_sig)

    def unpin_version(self, version: str) -> None:
        with self._lock:
            sig = self._pinned_versions.pop(version, None)
            if sig is None:
                return
            still_pinned = sig in self._pinned_versions.values()
        if not still_pinned:
            self.predictor.unpin(sig)

    def pinned_versions(self) -> List[str]:
        with self._lock:
            return sorted(self._pinned_versions)

    def versions(self) -> List[dict]:
        with self._lock:
            return [{"version": v,
                     "latest": v == self._latest,
                     "pinned": v in self._pinned_versions,
                     "device": m.device_key or None,
                     "num_trees": m.n_trees,
                     "num_features": m.num_features,
                     "num_class": m.num_class}
                    for v, m in sorted(self._models.items())]

    @property
    def latest(self) -> Optional[str]:
        with self._lock:
            return self._latest
