"""Drift monitors: training-time baselines vs served-traffic windows.

The continual-learning loop (ROADMAP 4c) needs a trigger: "the traffic
this model serves no longer looks like the data it trained on". This
module supplies it in two halves:

* **Baseline capture** (training side) — `compute_baseline(dataset,
  scores)` records, per used numerical feature, the binning edges the
  model actually trained with (BinMapper.bin_upper_bound, merged down
  to at most DRIFT_BINS quantile-shaped groups so a finite serving
  window's sampling noise stays far below the PSI threshold) and the
  bin occupancy over the train set (one `np.bincount` per feature over
  `Dataset.binned` — the codes already exist, capture is cheap), plus a
  decile histogram of the *converted* train scores (the same
  objective transform serving applies by default, so served
  predictions are comparable). The baseline is a small JSON-able dict:
  the CLI writes it to a ``<model>.drift.json`` sidecar next to the
  model (model text stays bit-identical) and `GBDT.capture_state`
  carries it in checkpoints once computed.

* **DriftMonitor** (serving side, numpy-only) — keeps a sliding window
  of served rows/scores binned by the *baseline's* edges and computes
  PSI (population stability index) per feature and for the score
  distribution:  ``psi = sum((p - q) * ln(p / q))`` with epsilon
  smoothing. Above threshold it fires the ``drift_psi`` watchdog
  (telemetry/watchdogs.fire_drift → watchdog_fires counter + watchdog
  event — which the canary router's existing watchdog gate turns into
  a demotion input) and emits a ``drift`` event with the full PSI
  snapshot for run reports. Checks are throttled (every `check_every`
  rows once `min_rows` are windowed) and a fire arms a one-window
  cooldown, so a drifted stream alarms once per window, not per row.

The conventional PSI folklore thresholds: < 0.1 stable, 0.1–0.25
moderate shift, > 0.25 action; the default threshold (0.2, the
``drift_psi_threshold`` param / watchdogs `drift_psi` knob) sits in
that band. Same-distribution windows land well under 0.05 with the
epsilon smoothing, which is the false-positive guard the tests pin.
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import events, watchdogs

__all__ = ["compute_baseline", "save_baseline", "load_baseline",
           "psi", "DriftMonitor", "BASELINE_FORMAT"]

BASELINE_FORMAT = "lgbm_tpu_drift_baseline"
SCORE_BINS = 10
DRIFT_BINS = 16
_EPS = 1e-4


def psi(expected, observed) -> float:
    """Population stability index between two occupancy vectors
    (epsilon-smoothed + renormalized, so empty bins don't blow up)."""
    p = np.asarray(expected, dtype=np.float64) + _EPS
    q = np.asarray(observed, dtype=np.float64) + _EPS
    p /= p.sum()
    q /= q.sum()
    return float(np.sum((p - q) * np.log(p / q)))


def _coarsen(edges: List[float], occ: np.ndarray,
             has_nan: bool) -> tuple:
    """Merge fine training bins into at most DRIFT_BINS roughly
    equal-occupancy groups (the trailing missing bin stays its own
    group). PSI over a finite window carries ~(bins-1)/window of pure
    sampling noise, so judging a 512-row serving window against 255
    training bins would fire on noise alone; 16 merged bins keep the
    noise floor well under the 0.2 threshold while quantile-shaped
    groups stay sensitive to real shift."""
    nan_occ = occ[-1] if has_nan else None
    core = occ[:-1] if has_nan else occ       # aligned with edges+1
    if core.size <= DRIFT_BINS:
        return edges, occ
    target = core.sum() / DRIFT_BINS
    new_edges: List[float] = []
    new_occ: List[float] = []
    acc = 0.0
    for i, v in enumerate(core):
        acc += float(v)
        if (acc >= target and i < core.size - 1
                and len(new_edges) < DRIFT_BINS - 1):
            new_edges.append(edges[i])        # group's upper bound
            new_occ.append(acc)
            acc = 0.0
    new_occ.append(acc)
    if nan_occ is not None:
        new_occ.append(float(nan_occ))
    return new_edges, np.asarray(new_occ, dtype=np.float64)


def compute_baseline(dataset, scores=None) -> dict:
    """Capture the drift baseline from a binned training Dataset (+
    optionally the converted train scores). Only numerical features
    carry edges a standalone monitor can re-apply; categorical features
    are skipped."""
    from ..io.binning import BIN_NUMERICAL
    features: List[dict] = []
    n = int(dataset.binned.shape[0]) if dataset.binned is not None else 0
    for j, f in enumerate(getattr(dataset, "used_features", [])):
        mapper = dataset.bin_mappers[f]
        if mapper.bin_type != BIN_NUMERICAL:
            continue
        edges = [float(b) for b in mapper.bin_upper_bound
                 if math.isfinite(b)]
        has_nan = bool(mapper.bin_upper_bound
                       and isinstance(mapper.bin_upper_bound[-1], float)
                       and math.isnan(mapper.bin_upper_bound[-1]))
        num_bins = len(edges) + 1 + (1 if has_nan else 0)
        codes = np.asarray(dataset.binned[:, j]).astype(np.int64)
        occ = np.bincount(codes, minlength=num_bins).astype(np.float64)
        total = occ.sum()
        if total <= 0:
            continue
        edges, occ = _coarsen(edges, occ, has_nan)
        features.append({"index": int(f), "edges": edges,
                         "has_nan": has_nan,
                         "occupancy": [round(float(v), 8)
                                       for v in occ / occ.sum()]})
    baseline = {"format": BASELINE_FORMAT, "version": 1,
                "n_rows": n, "features": features}
    if scores is not None:
        s = np.asarray(scores, dtype=np.float64).ravel()
        if s.size:
            qs = [i / SCORE_BINS for i in range(1, SCORE_BINS)]
            edges = np.quantile(s, qs)
            codes = np.searchsorted(edges, s, side="left")
            occ = np.bincount(codes,
                              minlength=SCORE_BINS).astype(np.float64)
            baseline["score"] = {
                "edges": [float(e) for e in edges],
                "occupancy": [round(float(v), 8)
                              for v in occ / occ.sum()]}
    return baseline


def save_baseline(baseline: dict, path: str) -> str:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(baseline, fh, sort_keys=True)
    return path


def load_baseline(path: str) -> Optional[dict]:
    """Read a baseline sidecar; None when missing/unreadable (serving
    without drift monitoring beats not serving)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            baseline = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if baseline.get("format") != BASELINE_FORMAT:
        return None
    return baseline


class _Window:
    """Fixed-size ring of bin codes. Pushes are vectorized block
    writes; occupancy is one bincount over the valid region at check
    time (the ring is small — recount beats bookkeeping)."""

    def __init__(self, num_bins: int, window: int):
        self.codes = np.zeros(window, dtype=np.int32)
        self.num_bins = num_bins
        self.window = window
        self.idx = 0
        self.size = 0

    def push(self, codes: np.ndarray) -> None:
        codes = np.asarray(codes, dtype=np.int32).ravel()
        if codes.size > self.window:
            codes = codes[-self.window:]
        k = codes.size
        end = self.idx + k
        if end <= self.window:
            self.codes[self.idx:end] = codes
        else:
            split = self.window - self.idx
            self.codes[self.idx:] = codes[:split]
            self.codes[:end - self.window] = codes[split:]
        self.idx = end % self.window
        self.size = min(self.window, self.size + k)

    def occupancy(self) -> np.ndarray:
        return np.bincount(self.codes[:self.size],
                           minlength=self.num_bins)


class DriftMonitor:
    """Sliding-window PSI monitor over served traffic, judged against a
    training-time baseline (see module docstring)."""

    def __init__(self, baseline: dict, threshold: Optional[float] = None,
                 window: int = 512, min_rows: int = 256,
                 check_every: int = 64, min_interval_s: float = 1.0):
        self.threshold = (float(threshold) if threshold is not None
                          else watchdogs.drift_threshold())
        self.window = int(window)
        self.min_rows = int(min_rows)
        self.check_every = max(1, int(check_every))
        # rate limit on top of the row throttle: under large-batch
        # traffic every request crosses the row boundary, and on a
        # small host a busy evaluation worker steals cycles from the
        # request path. Drift is a minutes-scale phenomenon; 1 Hz
        # evaluation of a 512-row window is plenty. 0 disables (tests).
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._features: List[dict] = []
        for feat in baseline.get("features", []):
            num_bins = (len(feat["edges"]) + 1
                        + (1 if feat.get("has_nan") else 0))
            self._features.append({
                "index": int(feat["index"]),
                "edges": np.asarray(feat["edges"], dtype=np.float64),
                "has_nan": bool(feat.get("has_nan")),
                "expected": np.asarray(feat["occupancy"],
                                       dtype=np.float64),
                "win": _Window(num_bins, self.window)})
        score = baseline.get("score")
        self._score = None
        if score and score.get("edges"):
            self._score = {
                "edges": np.asarray(score["edges"], dtype=np.float64),
                "expected": np.asarray(score["occupancy"],
                                       dtype=np.float64),
                "win": _Window(SCORE_BINS, self.window)}
        self._pending: List[tuple] = []
        self._pending_rows = 0
        self._rows = 0
        self._next_check = self.min_rows
        self._cooldown_until = 0
        self._fires = 0
        self._last_psi: Dict[str, float] = {}
        self._version: Optional[str] = None
        self._last_check_t = 0.0
        # serializes evaluations; distinct from _lock (the pending
        # buffer) so a running check never blocks the request path
        self._eval_lock = threading.Lock()
        self._wake = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # -- request path ----------------------------------------------------
    def observe(self, rows, preds=None,
                version: Optional[str] = None) -> None:
        """Buffer one request's rows (+ served predictions). The
        request path never bins or computes PSI — crossing the check
        boundary just wakes the evaluation worker, so the per-request
        cost is a lock + list append (the <2% serving overhead guard
        covers this path; the check itself runs off-thread)."""
        if not self._features and self._score is None:
            return
        # no dtype conversion here — copying a float32 batch on the
        # request path costs more than everything else in this method;
        # the worker converts when it bins
        x = np.asarray(rows)
        if x.ndim == 1:
            x = x.reshape(1, -1)
        with self._lock:
            self._pending.append((x, preds))
            self._pending_rows += x.shape[0]
            self._rows += x.shape[0]
            if version is not None:
                self._version = version
            # only the newest `window` rows can survive in the ring —
            # drop whole buffered blocks the next check would overwrite
            # anyway, so the worker's bill stays O(window) no matter
            # how much traffic arrived since the last check
            while (self._pending_rows - self._pending[0][0].shape[0]
                   >= self.window):
                self._pending_rows -= self._pending.pop(0)[0].shape[0]
            if self._rows < self._next_check:
                return
            now = time.monotonic()
            if now - self._last_check_t < self.min_interval_s:
                return               # retry on a later request
            self._last_check_t = now
            self._next_check = self._rows + self.check_every
            if self._worker is None and not self._closed:
                self._worker = threading.Thread(
                    target=self._loop, name="drift-monitor", daemon=True)
                self._worker.start()
        self._wake.set()

    # -- evaluation (worker thread / explicit) ---------------------------
    def _loop(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            if self._closed:
                return
            self.check_now()

    def check_now(self) -> Dict[str, float]:
        """Bin buffered rows and run one PSI judgment synchronously
        (the worker's body; also the deterministic hook for tests).
        Only the pending-buffer swap holds the request-path lock; the
        windows and PSI math are worker-only state."""
        with self._eval_lock:
            with self._lock:
                pending, self._pending = self._pending, []
                self._pending_rows = 0
                version = self._version
            self._bin_pending(pending)
            psis = self._psi()
        self._judge(psis, version)
        return psis

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        worker = self._worker
        if worker is not None:
            worker.join(timeout=2.0)

    def _bin_pending(self, pending: List[tuple]) -> None:
        """Bin the buffered rows through the baseline's edges into the
        sliding windows (vectorized over the whole buffered block)."""
        if not pending:
            return
        x = (pending[0][0] if len(pending) == 1
             else np.concatenate([p[0] for p in pending], axis=0))
        x = np.asarray(x, dtype=np.float64)
        for feat in self._features:
            f = feat["index"]
            if f >= x.shape[1]:
                continue
            v = x[:, f]
            codes = np.searchsorted(feat["edges"], v, side="left")
            nan_mask = np.isnan(v)
            if nan_mask.any():
                # nan rides the trailing missing bin when the model
                # trained with one, else the overflow bin
                codes = np.where(nan_mask,
                                 feat["win"].num_bins - 1, codes)
            feat["win"].push(codes)
        if self._score is not None:
            preds = [p[1] for p in pending if p[1] is not None]
            if preds:
                s = np.concatenate(
                    [np.asarray(p, dtype=np.float64).ravel()
                     for p in preds])
                codes = np.searchsorted(self._score["edges"], s,
                                        side="left")
                self._score["win"].push(codes)

    def _psi(self) -> Dict[str, float]:
        psis: Dict[str, float] = {}
        for feat in self._features:
            win = feat["win"]
            if win.size < self.min_rows:
                continue
            psis[f"feature_{feat['index']}"] = round(
                psi(feat["expected"], win.occupancy()), 6)
        if self._score is not None \
                and self._score["win"].size >= self.min_rows:
            psis["score"] = round(
                psi(self._score["expected"],
                    self._score["win"].occupancy()), 6)
        with self._lock:
            self._last_psi = psis
        return psis

    def _judge(self, psis: Dict[str, float],
               version: Optional[str]) -> None:
        if not psis:
            return
        worst = max(psis, key=psis.get)
        worst_psi = psis[worst]
        if worst_psi <= self.threshold:
            return
        with self._lock:
            if self._rows < self._cooldown_until:
                return
            self._cooldown_until = self._rows + self.window
            self._fires += 1
        fired = watchdogs.fire_drift(worst, worst_psi, self.threshold,
                                     version=version)
        if fired:
            events.emit("drift", version=version, worst=worst,
                        psi=worst_psi, threshold=self.threshold,
                        rows=self._rows, window=self.window, psis=psis)

    def snapshot(self) -> dict:
        with self._lock:
            return {"rows": self._rows, "window": self.window,
                    "threshold": self.threshold, "fires": self._fires,
                    "psi": dict(self._last_psi)}
