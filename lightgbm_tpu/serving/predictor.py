"""Compiled-predictor cache for online inference.

Batch prediction (`Booster.predict`) tolerates a trace + XLA compile per
new input shape; an online server cannot. This module compiles the full
scoring function — ensemble traversal, average-output division, objective
link — ahead of time per (ensemble shape signature, batch bucket,
raw_score) and then dispatches straight to the cached executable.

Two properties fall out of the key design:

* Batch shapes are power-of-two bucketed with the same `_bucket_up` rule
  as ops/predict.py, so arbitrary request sizes hit O(log max_batch)
  programs, pre-compilable at model load.
* The key is the ensemble's SHAPE signature, not the model version: a
  hot-swap to a retrained model of the same padded shape (the common
  periodic-retrain case) reuses every compiled executable and serves its
  first request with zero compile stalls.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import predict as predict_ops
from ..ops.predict import _bucket_up
from ..telemetry import counters as telem_counters
from ..telemetry import spans as telem_spans
from ..utils import log
from ..utils.timer import timer


class PreparedModel:
    """A Booster/GBDT tensorized once for serving.

    Holds the bucketed EnsembleArrays on device plus everything the
    compiled scoring function needs as static context. Immutable after
    construction — hot swaps publish a new PreparedModel.
    """

    def __init__(self, gbdt, version: str,
                 num_iteration: Optional[int] = None):
        arrays, tree_class, n_models = gbdt.ensemble_arrays(
            num_iteration, 0, bucket=True)
        if not n_models:
            raise ValueError("cannot serve a model with no trees")
        self.version = version
        self.arrays = arrays
        self.tree_class = tree_class
        self.n_trees = n_models
        self.num_class = gbdt.num_class
        self.max_depth = arrays.max_depth
        self.num_features = gbdt.max_feature_idx + 1
        self.objective = gbdt.objective
        denom = (max(1, n_models // max(gbdt.num_tree_per_iteration, 1))
                 if gbdt.average_output else 1)
        self.denom = jnp.float32(denom)
        # identifies the output transform for executable sharing: two
        # models convert identically iff the objective serializes the same
        self.convert_key = (gbdt.objective.to_string()
                            if gbdt.objective is not None else "")
        self.shape_sig = tuple(
            (tuple(a.shape), str(a.dtype))
            for a in arrays if hasattr(a, "shape"))

    @classmethod
    def from_booster(cls, booster, version: str,
                     num_iteration: Optional[int] = None) -> "PreparedModel":
        gbdt = getattr(booster, "_gbdt", booster)
        return cls(gbdt, version, num_iteration)


class PredictorCache:
    """(shape signature, batch bucket, raw_score) -> AOT-compiled executable.

    `compile_count` is the ground-truth XLA compile counter the
    no-recompile tests assert on: every lowering/compile in the serving
    hot path goes through `_compile` below.
    """

    def __init__(self, max_batch_rows: int = 4096):
        self.max_batch_rows = max_batch_rows
        self._exec: Dict[Tuple, object] = {}
        # family key (everything but the bucket) -> sorted compiled
        # buckets: lets a small request ride an already-warm larger
        # bucket instead of paying a compile for its exact power of two
        self._buckets: Dict[Tuple, list] = {}
        self._lock = threading.Lock()
        self.compile_count = 0
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------------
    def _make_fn(self, model: PreparedModel, raw_score: bool):
        max_depth, num_class = model.max_depth, model.num_class
        objective = model.objective

        def fn(x, arrays, tree_class, denom):
            out = predict_ops.predict_raw_ensemble(
                x, arrays, tree_class,
                max_depth=max_depth, num_class=num_class)
            out = out / denom
            if not raw_score and objective is not None:
                out = objective.convert_output(out.T).T
            return out
        return fn

    def _family(self, model: PreparedModel, n_features: int,
                raw_score: bool) -> Tuple:
        return (model.shape_sig, n_features, model.max_depth,
                model.num_class, bool(raw_score),
                "" if raw_score else model.convert_key)

    def _pick_bucket(self, family: Tuple, n: int) -> int:
        """Smallest already-compiled bucket that fits n rows, else n's own
        power-of-two bucket (which will compile)."""
        with self._lock:
            for b in self._buckets.get(family, ()):
                if b >= n:
                    return b
        return _bucket_up(n)

    def _compile(self, family, bucket, model: PreparedModel,
                 x_dev, raw_score: bool) -> object:
        key = family + (bucket,)
        with self._lock:
            compiled = self._exec.get(key)
            if compiled is not None:
                return compiled
            t0 = time.perf_counter()
            with timer("serve_compile"), \
                    telem_spans.span("serve_compile", bucket=bucket):
                fn = self._make_fn(model, raw_score)
                compiled = jax.jit(fn).lower(
                    x_dev, model.arrays, model.tree_class,
                    model.denom).compile()
            # compiles are rare and expensive: count unconditionally so
            # the /metrics compile counters exist even with telemetry off
            telem_counters.incr("serve_compiles")
            telem_counters.add_seconds("serve_compile_seconds",
                                       time.perf_counter() - t0)
            self._exec[key] = compiled
            self._buckets.setdefault(family, []).append(bucket)
            self._buckets[family].sort()
            self.compile_count += 1
            log.debug("serving: compiled predictor bucket=%d", bucket)
            return compiled

    # ------------------------------------------------------------------
    def predict(self, model: PreparedModel, x: np.ndarray,
                raw_score: bool = False) -> np.ndarray:
        """(N, num_class) scores; pads N up to its power-of-two bucket and
        slices back, so any N <= max_batch_rows reuses a warm program."""
        x = np.ascontiguousarray(np.asarray(x, dtype=np.float32))
        if x.ndim == 1:
            x = x.reshape(1, -1)
        n = x.shape[0]
        if n == 0:
            return np.zeros((0, model.num_class), dtype=np.float64)
        if x.shape[1] < model.num_features:
            raise ValueError(
                f"request has {x.shape[1]} features, model "
                f"{model.version} needs {model.num_features}")
        if n > self.max_batch_rows:
            parts = [self.predict(model, x[i:i + self.max_batch_rows],
                                  raw_score)
                     for i in range(0, n, self.max_batch_rows)]
            return np.concatenate(parts, axis=0)
        family = self._family(model, x.shape[1], raw_score)
        bucket = self._pick_bucket(family, n)
        if bucket != n:
            x = np.concatenate(
                [x, np.zeros((bucket - n, x.shape[1]), dtype=x.dtype)],
                axis=0)
        if telem_counters.is_active():
            telem_counters.incr("transfer_h2d_bytes", x.nbytes)
        x_dev = jnp.asarray(x)
        compiled = self._exec.get(family + (bucket,))
        if compiled is None:
            self.misses += 1
            compiled = self._compile(family, bucket, model, x_dev, raw_score)
        else:
            self.hits += 1
        with timer("serve_execute"), \
                telem_spans.span("serve_execute", rows=n, bucket=bucket):
            out = compiled(x_dev, model.arrays, model.tree_class,
                           model.denom)
            out = np.asarray(jax.device_get(out), dtype=np.float64)
        if telem_counters.is_active():
            telem_counters.incr("transfer_d2h_bytes", out.nbytes)
        return out[:n]

    def warm(self, model: PreparedModel, bucket_rows: int,
             raw_score: bool = False) -> None:
        """Compile + execute one dummy batch so the first real request in
        this bucket is a pure cache hit."""
        bucket = min(_bucket_up(max(1, bucket_rows)), self.max_batch_rows)
        dummy = np.zeros((bucket, model.num_features), dtype=np.float32)
        self.predict(model, dummy, raw_score=raw_score)

    def cache_info(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._exec),
                    "compiles": self.compile_count,
                    "hits": self.hits, "misses": self.misses}
