"""Compiled-predictor cache for online inference.

Batch prediction (`Booster.predict`) tolerates a trace + XLA compile per
new input shape; an online server cannot. This module compiles the full
scoring function — ensemble traversal, average-output division, objective
link — ahead of time per (ensemble shape signature, batch bucket,
raw_score) and then dispatches straight to the cached executable.

Two properties fall out of the key design:

* Batch shapes are power-of-two bucketed with the same `_bucket_up` rule
  as ops/predict.py, so arbitrary request sizes hit O(log max_batch)
  programs, pre-compilable at model load.
* The key is the ensemble's SHAPE signature, not the model version: a
  hot-swap to a retrained model of the same padded shape (the common
  periodic-retrain case) reuses every compiled executable and serves its
  first request with zero compile stalls.

Fleet extensions (PR 11):

* **LRU eviction with router pins** — `max_entries` bounds the
  executable count under multi-model load; eviction walks least-recently
  -used first but NEVER drops an executable whose ensemble shape
  signature is pinned (`pin`/`unpin`, driven by the canary router and
  the placement plan through `ModelRegistry.pin_version`).
* **Donated device batch buffers** — on backends that support input
  aliasing (donation is a no-op-with-warning on CPU) the batch operand
  is donated so XLA reuses its memory for the output instead of
  allocating per flush (`donate="auto"`).
* **Staging buffer pool** — padding a request up to its bucket reuses a
  pooled host buffer instead of allocating + concatenating per call,
  cutting two allocations out of the flush latency path
  (`LGBM_TPU_SERVE_NO_STAGING=1` restores the old path for A/B).
* **Placement-aware keys** — a PreparedModel pinned to a mesh device
  carries that device in its executable family, so two versions placed
  on different devices never collide in the cache.
* **install()/entries()** — the persistent export cache
  (fleet/export_cache.py) enumerates warm executables for serialization
  and installs deserialized ones without counting a compile.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import predict as predict_ops
from ..ops.predict import _bucket_up
from ..telemetry import counters as telem_counters
from ..telemetry import spans as telem_spans
from ..utils import log
from ..utils.timer import timer


def _device_key(device) -> str:
    """Stable string identity of a placement device ('' = default)."""
    if device is None:
        return ""
    return f"{getattr(device, 'platform', 'dev')}:{getattr(device, 'id', 0)}"


class PreparedModel:
    """A Booster/GBDT tensorized once for serving.

    Holds the bucketed EnsembleArrays on device plus everything the
    compiled scoring function needs as static context. Immutable after
    construction — hot swaps publish a new PreparedModel. An optional
    `device` pins every tensor (and, through the executable family key,
    every compiled program) to one mesh device — the placement unit of
    fleet/placement.py.
    """

    def __init__(self, gbdt, version: str,
                 num_iteration: Optional[int] = None, device=None):
        arrays, tree_class, n_models = gbdt.ensemble_arrays(
            num_iteration, 0, bucket=True)
        if not n_models:
            raise ValueError("cannot serve a model with no trees")
        self.version = version
        self.device = device
        self.device_key = _device_key(device)
        if device is not None:
            # per-field put: the NamedTuple carries a plain-int max_depth
            # that a pytree-wide device_put would wrongly tensorize
            arrays = arrays._replace(**{
                f: jax.device_put(getattr(arrays, f), device)
                for f in arrays._fields
                if hasattr(getattr(arrays, f), "shape")})
            tree_class = jax.device_put(tree_class, device)
        self.arrays = arrays
        self.tree_class = tree_class
        self.n_trees = n_models
        # the host-side model the tensors came from: continual-loop
        # retrains start from the SERVED version's model text, which
        # only the gbdt can produce (save_model_to_string)
        self.gbdt = gbdt
        self.num_class = gbdt.num_class
        self.max_depth = arrays.max_depth
        self.num_features = gbdt.max_feature_idx + 1
        self.objective = gbdt.objective
        denom = (max(1, n_models // max(gbdt.num_tree_per_iteration, 1))
                 if gbdt.average_output else 1)
        self.denom = (jax.device_put(jnp.float32(denom), device)
                      if device is not None else jnp.float32(denom))
        # identifies the output transform for executable sharing: two
        # models convert identically iff the objective serializes the same
        self.convert_key = (gbdt.objective.to_string()
                            if gbdt.objective is not None else "")
        self.shape_sig = tuple(
            (tuple(a.shape), str(a.dtype))
            for a in arrays if hasattr(a, "shape"))

    @classmethod
    def from_booster(cls, booster, version: str,
                     num_iteration: Optional[int] = None,
                     device=None) -> "PreparedModel":
        gbdt = getattr(booster, "_gbdt", booster)
        return cls(gbdt, version, num_iteration, device=device)


def resolve_donate(donate="auto") -> bool:
    """'auto' donates the batch operand wherever XLA can actually alias
    it (every accelerator backend); CPU ignores donation and warns, so
    auto stays off there."""
    if donate == "auto":
        return jax.default_backend() != "cpu"
    return bool(donate)


class PredictorCache:
    """(shape signature, batch bucket, raw_score, device) -> AOT-compiled
    executable, LRU-bounded with pin protection.

    `compile_count` is the ground-truth XLA compile counter the
    no-recompile tests assert on: every lowering/compile in the serving
    hot path goes through `_compile` below — executables restored from
    the persistent export cache arrive via `install()` and count as
    neither compiles nor misses.
    """

    def __init__(self, max_batch_rows: int = 4096,
                 max_entries: Optional[int] = None, donate="auto"):
        self.max_batch_rows = max_batch_rows
        self.max_entries = (int(max_entries) if max_entries else None)
        self.donate_input = resolve_donate(donate)
        self._exec: "OrderedDict[Tuple, object]" = OrderedDict()
        # family key (everything but the bucket) -> sorted compiled
        # buckets: lets a small request ride an already-warm larger
        # bucket instead of paying a compile for its exact power of two
        self._buckets: Dict[Tuple, list] = {}
        self._pinned_sigs: set = set()
        self._lock = threading.Lock()
        # key -> Event for a compile in flight; lets _compile run XLA
        # outside _lock (seconds-long) while duplicate requests for the
        # SAME key wait instead of compiling twice
        self._inflight: Dict[Tuple, threading.Event] = {}
        self._staging: Dict[Tuple[int, int], list] = {}
        self._staging_off = bool(os.environ.get("LGBM_TPU_SERVE_NO_STAGING"))
        self.compile_count = 0
        self.install_count = 0
        self.evictions = 0
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------------
    def _make_fn(self, model: PreparedModel, raw_score: bool):
        max_depth, num_class = model.max_depth, model.num_class
        objective = model.objective

        def fn(x, arrays, tree_class, denom):
            out = predict_ops.predict_raw_ensemble(
                x, arrays, tree_class,
                max_depth=max_depth, num_class=num_class)
            out = out / denom
            if not raw_score and objective is not None:
                out = objective.convert_output(out.T).T
            return out
        return fn

    def family(self, model: PreparedModel, n_features: int,
               raw_score: bool) -> Tuple:
        return (model.shape_sig, n_features, model.max_depth,
                model.num_class, bool(raw_score),
                "" if raw_score else model.convert_key,
                model.device_key)

    _family = family          # internal alias kept for older callers

    def _pick_bucket(self, family: Tuple, n: int) -> int:
        """Smallest already-compiled bucket that fits n rows, else n's own
        power-of-two bucket (which will compile)."""
        with self._lock:
            for b in self._buckets.get(family, ()):
                if b >= n:
                    return b
        return _bucket_up(n)

    # -- pinning / eviction ---------------------------------------------
    def pin(self, shape_sig) -> None:
        """Protect every executable of this ensemble shape signature from
        LRU eviction (the router pins its stable + canary versions)."""
        with self._lock:
            self._pinned_sigs.add(shape_sig)

    def unpin(self, shape_sig) -> None:
        with self._lock:
            self._pinned_sigs.discard(shape_sig)

    def pinned(self) -> set:
        with self._lock:
            return set(self._pinned_sigs)

    def _evict_locked(self) -> None:
        """Drop least-recently-used unpinned executables until the cache
        fits max_entries (caller holds the lock). Pinned families are
        never dropped, even if that leaves the cache over budget — a
        routed version must stay servable without a compile stall."""
        if self.max_entries is None:
            return
        while len(self._exec) > self.max_entries:
            victim = None
            for key in self._exec:          # OrderedDict: LRU first
                if key[0][0] not in self._pinned_sigs:
                    victim = key
                    break
            if victim is None:
                log.warning(
                    "serving: predictor cache over budget (%d > %d) but "
                    "every entry is pinned; not evicting",
                    len(self._exec), self.max_entries)
                return
            del self._exec[victim]
            fam, bucket = victim[0], victim[1][-1]
            if bucket in self._buckets.get(fam, ()):
                self._buckets[fam].remove(bucket)
            self.evictions += 1
            telem_counters.incr("serve_cache_evictions")

    # -- compile / install ----------------------------------------------
    @staticmethod
    def _key(family: Tuple, bucket: int) -> Tuple:
        return (family, (bucket,))

    def _compile(self, family, bucket, model: PreparedModel,
                 x_dev, raw_score: bool) -> object:
        """XLA lowering+compile takes seconds; holding the cache lock
        across it would stall every cache-hit request behind a cold
        bucket. So: claim the key under the lock, compile UNLOCKED,
        install under the lock. A second thread asking for the same key
        waits on the claimant's event; threads asking for other keys
        sail through."""
        key = self._key(family, bucket)
        while True:
            with self._lock:
                compiled = self._exec.get(key)
                if compiled is not None:
                    return compiled
                waiter = self._inflight.get(key)
                if waiter is None:
                    self._inflight[key] = threading.Event()
                    break
            waiter.wait()

        try:
            t0 = time.perf_counter()
            with timer("serve_compile"), \
                    telem_spans.span("serve_compile", bucket=bucket):
                fn = self._make_fn(model, raw_score)
                donate = (0,) if self.donate_input else ()
                compiled = jax.jit(fn, donate_argnums=donate).lower(
                    x_dev, model.arrays, model.tree_class,
                    model.denom).compile()
            # compiles are rare and expensive: count unconditionally so
            # the /metrics compile counters exist even with telemetry off
            telem_counters.incr("serve_compiles")
            telem_counters.add_seconds("serve_compile_seconds",
                                       time.perf_counter() - t0)
            with self._lock:
                self._exec[key] = compiled
                self._buckets.setdefault(family, []).append(bucket)
                self._buckets[family].sort()
                self.compile_count += 1
                self._evict_locked()
            log.debug("serving: compiled predictor bucket=%d", bucket)
            return compiled
        finally:
            with self._lock:
                ev = self._inflight.pop(key, None)
            if ev is not None:
                ev.set()

    def install(self, family: Tuple, bucket: int, compiled) -> None:
        """Register an executable that did NOT come from `_compile` —
        deserialized from the persistent export cache. Counts neither a
        compile nor a miss; the zero-compile restart property rests on
        this seam."""
        key = self._key(family, int(bucket))
        with self._lock:
            if key in self._exec:
                return
            self._exec[key] = compiled
            if bucket not in self._buckets.setdefault(family, []):
                self._buckets[family].append(int(bucket))
                self._buckets[family].sort()
            self.install_count += 1
            self._evict_locked()

    def entries(self) -> List[Tuple[Tuple, int, object]]:
        """Snapshot of (family, bucket, executable) — the export cache's
        serialization feed."""
        with self._lock:
            return [(key[0], key[1][-1], compiled)
                    for key, compiled in self._exec.items()]

    # -- staging ---------------------------------------------------------
    def _stage(self, x: np.ndarray, bucket: int):
        """Pad x up to `bucket` rows. Returns (padded array, pool token);
        the token goes back to the pool after the device copy so the
        buffer is reused by the next flush instead of reallocated."""
        if self._staging_off:
            return np.concatenate(
                [x, np.zeros((bucket - x.shape[0], x.shape[1]),
                             dtype=x.dtype)], axis=0), None
        pkey = (bucket, x.shape[1])
        with self._lock:
            pool = self._staging.setdefault(pkey, [])
            buf = pool.pop() if pool else None
        if buf is None:
            buf = np.empty((bucket, x.shape[1]), dtype=np.float32)
        n = x.shape[0]
        buf[:n] = x
        buf[n:] = 0.0
        return buf, pkey

    def _unstage(self, buf, pkey) -> None:
        if pkey is None:
            return
        with self._lock:
            pool = self._staging.setdefault(pkey, [])
            if len(pool) < 4:       # bound the pool per shape
                pool.append(buf)

    # ------------------------------------------------------------------
    def predict(self, model: PreparedModel, x: np.ndarray,
                raw_score: bool = False) -> np.ndarray:
        """(N, num_class) scores; pads N up to its power-of-two bucket and
        slices back, so any N <= max_batch_rows reuses a warm program."""
        x = np.ascontiguousarray(np.asarray(x, dtype=np.float32))
        if x.ndim == 1:
            x = x.reshape(1, -1)
        n = x.shape[0]
        if n == 0:
            return np.zeros((0, model.num_class), dtype=np.float64)
        if x.shape[1] < model.num_features:
            raise ValueError(
                f"request has {x.shape[1]} features, model "
                f"{model.version} needs {model.num_features}")
        if n > self.max_batch_rows:
            parts = [self.predict(model, x[i:i + self.max_batch_rows],
                                  raw_score)
                     for i in range(0, n, self.max_batch_rows)]
            return np.concatenate(parts, axis=0)
        family = self.family(model, x.shape[1], raw_score)
        bucket = self._pick_bucket(family, n)
        token = None
        if bucket != n:
            x, token = self._stage(x, bucket)
        if telem_counters.is_active():
            telem_counters.incr("transfer_h2d_bytes", x.nbytes)
        x_dev = (jax.device_put(x, model.device)
                 if model.device is not None else jnp.asarray(x))
        if token is not None:
            jax.block_until_ready(x_dev)      # host buffer copied out
            self._unstage(x, token)
        key = self._key(family, bucket)
        with self._lock:
            compiled = self._exec.get(key)
            if compiled is not None:
                self._exec.move_to_end(key)   # LRU touch
        if compiled is None:
            self.misses += 1
            compiled = self._compile(family, bucket, model, x_dev, raw_score)
        else:
            self.hits += 1
        with timer("serve_execute"), \
                telem_spans.span("serve_execute", rows=n, bucket=bucket):
            out = compiled(x_dev, model.arrays, model.tree_class,
                           model.denom)
            out = np.asarray(jax.device_get(out), dtype=np.float64)
        if telem_counters.is_active():
            telem_counters.incr("transfer_d2h_bytes", out.nbytes)
        return out[:n]

    def warm(self, model: PreparedModel, bucket_rows: int,
             raw_score: bool = False) -> None:
        """Compile + execute one dummy batch so the first real request in
        this bucket is a pure cache hit."""
        bucket = min(_bucket_up(max(1, bucket_rows)), self.max_batch_rows)
        dummy = np.zeros((bucket, model.num_features), dtype=np.float32)
        self.predict(model, dummy, raw_score=raw_score)

    def cache_info(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._exec),
                    "compiles": self.compile_count,
                    "installs": self.install_count,
                    "evictions": self.evictions,
                    "pinned_sigs": len(self._pinned_sigs),
                    "max_entries": self.max_entries or 0,
                    "donate": int(self.donate_input),
                    "hits": self.hits, "misses": self.misses}
