"""Labeled-feedback store: ground truth per answering version.

`POST /feedback` lands here: clients that later learn the true label
of a prediction post it back together with the score and the version
that answered (the predict response carries `version` for exactly this
round trip). The store keeps a bounded per-version window of
(label, score) pairs and computes AUC on demand — the quality half of
the canary promotion gate (`CanaryRouter` holds until the canary has
`feedback_min_labels` labels and demotes/holds when its AUC trails the
stable's by more than `feedback_auc_epsilon`).

AUC is the tie-corrected Mann-Whitney statistic (average ranks), so it
is exact for quantized/duplicate scores. Binary labels only — a label
is "positive" iff > 0.5; regression feedback would gate on a different
statistic and is out of scope here.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events

__all__ = ["FeedbackStore", "binary_auc"]


def binary_auc(labels: np.ndarray, scores: np.ndarray) -> Optional[float]:
    """Tie-corrected Mann-Whitney AUC; None while only one class has
    been observed (the statistic is undefined there — callers treat
    None as "not enough evidence", never as 0)."""
    labels = np.asarray(labels, dtype=np.float64).reshape(-1)
    scores = np.asarray(scores, dtype=np.float64).reshape(-1)
    pos = labels > 0.5
    npos = int(pos.sum())
    nneg = int(labels.size - npos)
    if npos == 0 or nneg == 0:
        return None
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(scores.size, dtype=np.float64)
    s_sorted = scores[order]
    i = 0
    while i < s_sorted.size:
        j = i
        while j + 1 < s_sorted.size and s_sorted[j + 1] == s_sorted[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0   # average 1-based rank
        i = j + 1
    return float((ranks[pos].sum() - npos * (npos + 1) / 2.0)
                 / (npos * nneg))


class FeedbackStore:
    """Bounded per-version (label, score) windows, thread-safe."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._labels: Dict[str, List[float]] = {}
        self._scores: Dict[str, List[float]] = {}

    def record(self, version: str, labels, scores) -> int:
        """Append one feedback batch against `version`; returns the
        number of labels now held for it."""
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        scores = np.asarray(scores, dtype=np.float64).reshape(-1)
        if labels.size != scores.size:
            raise ValueError(
                f"feedback labels ({labels.size}) and scores "
                f"({scores.size}) must align")
        with self._lock:
            ls = self._labels.setdefault(version, [])
            ss = self._scores.setdefault(version, [])
            ls.extend(float(v) for v in labels)
            ss.extend(float(v) for v in scores)
            if len(ls) > self.capacity:
                del ls[:len(ls) - self.capacity]
                del ss[:len(ss) - self.capacity]
            count = len(ls)
        telem_counters.incr("serve_feedback_labels", float(labels.size))
        telem_events.emit("serve_feedback", version=version,
                          labels=int(labels.size), total=count)
        return count

    def auc(self, version: Optional[str]) -> Tuple[Optional[float], int]:
        """(AUC or None, label count) for one version's window."""
        if version is None:
            return None, 0
        with self._lock:
            ls = list(self._labels.get(version) or [])
            ss = list(self._scores.get(version) or [])
        if not ls:
            return None, 0
        return binary_auc(np.asarray(ls), np.asarray(ss)), len(ls)

    def labels(self, version: str) -> int:
        with self._lock:
            return len(self._labels.get(version) or [])

    def reset(self, version: Optional[str] = None) -> None:
        with self._lock:
            if version is None:
                self._labels.clear()
                self._scores.clear()
            else:
                self._labels.pop(version, None)
                self._scores.pop(version, None)

    def snapshot(self) -> dict:
        with self._lock:
            versions = sorted(self._labels)
            counts = {v: len(self._labels[v]) for v in versions}
        out = {}
        for v in versions:
            auc, n = self.auc(v)
            out[v] = {"labels": counts[v],
                      "auc": (round(auc, 6) if auc is not None else None),
                      "window": n}
        return {"capacity": self.capacity, "versions": out}
