"""SLO burn-rate alerting over fast/slow dual windows.

Two targets, both optional (0 disables): ``serve_slo_p99_ms`` (p99
latency objective) and ``serve_slo_error_rate`` (error-rate
objective). Each request's latency/error lands in a bounded sample
deque; the monitor evaluates the objectives over two trailing *time*
windows — a **fast** window (default 60s) that catches acute burns
quickly, and a **slow** window (default 600s) that catches slow leaks
a fast window averages away. This is the standard multi-window
burn-rate shape: page on the fast window, ticket on the slow one.

Consumers:

* ``/healthz`` — a fast-window burn flips ``ok`` → ``degraded`` (the
  HTTP layer already maps non-ok to 503, so load balancers back off).
* ``/metrics`` — both windows' observed p99/error-rate and burn flags
  are exported as gauges next to the serving counters.
* the canary router — `version_violation(version)` answers "is THIS
  version burning its SLO in the fast window", the additional
  demotion input wired in fleet/router.py.

Burn transitions are edge-triggered into the flight recorder
(``slo_burn`` / ``slo_clear`` events + an ``slo_burns`` counter), so
run reports show when an incident started and ended, not one line per
request. Evaluation is O(window) and happens on read (health/metrics/
router), not per observe — the request path pays one deque append.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..telemetry import bundle as telem_bundle
from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events

__all__ = ["SloMonitor"]

_MAX_SAMPLES = 8192


class SloMonitor:
    """Sliding-window SLO evaluation over per-request observations."""

    def __init__(self, p99_ms: float = 0.0, error_rate: float = 0.0,
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 min_requests: int = 20):
        self.p99_ms = float(p99_ms)
        self.error_rate = float(error_rate)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.min_requests = int(min_requests)
        self._lock = threading.Lock()
        # (t_monotonic, latency_s | None, error, version)
        self._samples: deque = deque(maxlen=_MAX_SAMPLES)
        self._burning = False

    @property
    def configured(self) -> bool:
        return self.p99_ms > 0 or self.error_rate > 0

    # -- request path ----------------------------------------------------
    def observe(self, version: Optional[str], seconds: Optional[float],
                error: bool = False) -> None:
        """One request's outcome. O(1): evaluation is deferred to the
        readers (health/metrics/router)."""
        with self._lock:
            self._samples.append((time.monotonic(),
                                  None if seconds is None else
                                  float(seconds),
                                  bool(error), version))

    # -- evaluation ------------------------------------------------------
    def _window_stats(self, window_s: float,
                      version: Optional[str] = None) -> dict:
        cutoff = time.monotonic() - window_s
        lats = []
        requests = errors = 0
        with self._lock:
            for t, lat, err, ver in self._samples:
                if t < cutoff:
                    continue
                if version is not None and ver != version:
                    continue
                requests += 1
                if err:
                    errors += 1
                elif lat is not None:
                    lats.append(lat)
        p99 = 0.0
        if lats:
            lats.sort()
            p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] * 1e3
        rate = errors / requests if requests else 0.0
        violated = None
        if requests >= self.min_requests:
            if self.p99_ms > 0 and p99 > self.p99_ms:
                violated = (f"p99 {p99:.1f}ms > slo {self.p99_ms:g}ms "
                            f"({requests} reqs)")
            elif self.error_rate > 0 and rate > self.error_rate:
                violated = (f"error_rate {rate:.3f} > slo "
                            f"{self.error_rate:g} ({requests} reqs)")
        return {"requests": requests, "errors": errors,
                "error_rate": round(rate, 6), "p99_ms": round(p99, 3),
                "burning": violated is not None, "violation": violated}

    def version_violation(self, version: str) -> Optional[str]:
        """Fast-window SLO verdict for one version (the router's
        demotion input): a reason string while burning, else None."""
        if not self.configured:
            return None
        return self._window_stats(self.fast_window_s,
                                  version)["violation"]

    def burning(self) -> bool:
        """Aggregate fast-window burn (drives /healthz degradation).
        Edge-triggers slo_burn/slo_clear events on state change."""
        if not self.configured:
            return False
        fast = self._window_stats(self.fast_window_s)
        self._edge(fast)
        return fast["burning"]

    def _edge(self, fast: dict) -> None:
        with self._lock:
            was, now = self._burning, fast["burning"]
            self._burning = now
        if now and not was:
            telem_counters.incr("slo_burns")
            telem_events.emit("slo_burn", window="fast",
                              violation=fast["violation"],
                              p99_ms=fast["p99_ms"],
                              error_rate=fast["error_rate"],
                              requests=fast["requests"])
            # outside self._lock (released above): capture writes files
            telem_bundle.maybe_capture("slo_burn",
                                       violation=fast["violation"])
        elif was and not now:
            telem_events.emit("slo_clear", window="fast",
                              p99_ms=fast["p99_ms"],
                              error_rate=fast["error_rate"],
                              requests=fast["requests"])

    def snapshot(self) -> dict:
        """Both windows' stats + objectives (for /stats, /metrics and
        /healthz). Edge-triggers burn events like `burning()`."""
        fast = self._window_stats(self.fast_window_s)
        slow = self._window_stats(self.slow_window_s)
        if self.configured:
            self._edge(fast)
        return {"slo_p99_ms": self.p99_ms,
                "slo_error_rate": self.error_rate,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "configured": self.configured,
                "fast": fast, "slow": slow}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._burning = False
