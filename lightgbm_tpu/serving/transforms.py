"""Edge feature transforms: raw CSV/JSON rows -> model-ready features.

The reference CLI predicts straight from a raw data file — binning is
the model's problem, not the client's (reference: application.cpp
Predict + bin.h ValueToBin). The serving edge gets the same property
here: at train time the CLI captures the Dataset's fitted BinMappers
into a ``<model>.transform.json`` sidecar (the exact mechanism the
drift baseline uses), and the fleet gateway applies them so clients
send raw feature rows — CSV text or JSON with nulls — and never
pre-bin.

Why this is *bit-identical* to raw predict, not merely close: trained
trees store real-valued thresholds that are exactly bin upper bounds
(``Dataset.real_threshold`` -> ``BinMapper.bin_to_value``), so mapping
a raw value to its bin code and back to the bin's representative value
(``EdgeTransform.prebin_rows``) can never move it across any threshold
the model can test. A client that pre-bins with this sidecar and one
that sends raw floats get byte-for-byte the same predictions — the
acceptance property tests/test_fleet_gateway.py pins.

Sidecar lifecycle mirrors serving/drift.py: ``capture_transform``
(training side, rank-0 CLI write), ``save_transform`` /
``load_transform`` (format-tagged JSON; load returns None on
unreadable or foreign files), ``EdgeTransform`` (serving side,
numpy-only — no accelerator dependency at the gateway).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ..io.binning import BIN_NUMERICAL, BinMapper

__all__ = ["capture_transform", "save_transform", "load_transform",
           "EdgeTransform", "TRANSFORM_FORMAT"]

TRANSFORM_FORMAT = "lgbm_tpu_edge_transform"

# CSV tokens that mean "missing" (case-insensitive), matching the
# loose-parsing habits of the reference's text parser
_MISSING_TOKENS = {"", "na", "nan", "null", "none", "?"}


def capture_transform(dataset) -> dict:
    """Record the fitted bin mappers of a constructed Dataset, keyed by
    raw feature column. Unused/trivial columns carry no mapper — the
    transform passes them through untouched (no tree can test them).
    Accepts either the inner io.dataset.Dataset or the public
    basic.Dataset wrapper (the CLI holds the wrapper; its mappers live
    on the constructed ``_inner``)."""
    if hasattr(dataset, "construct"):
        dataset = dataset.construct()._inner
    mappers: Dict[str, dict] = {}
    for f in getattr(dataset, "used_features", []):
        mappers[str(int(f))] = dataset.bin_mappers[f].to_dict()
    return {"format": TRANSFORM_FORMAT, "version": 1,
            "num_features": int(dataset.num_total_features),
            "mappers": mappers}


def save_transform(spec: dict, path: str) -> str:
    # default json (allow_nan=True): bin_upper_bound legitimately holds
    # Infinity and, for MISSING_NAN features, a trailing NaN
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(spec, fh, sort_keys=True)
    return path


def load_transform(path: str) -> Optional[dict]:
    """Sidecar load: None (not an error) on missing/unreadable/foreign
    files, so discovery can probe paths freely."""
    try:
        with open(path, encoding="utf-8") as fh:
            spec = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(spec, dict) or spec.get("format") != TRANSFORM_FORMAT:
        return None
    return spec


class EdgeTransform:
    """Raw-row front end over a captured transform spec."""

    def __init__(self, spec: dict):
        if spec.get("format") != TRANSFORM_FORMAT:
            raise ValueError("not an edge-transform spec")
        self.num_features = int(spec["num_features"])
        self.mappers: Dict[int, BinMapper] = {
            int(f): BinMapper.from_dict(d)
            for f, d in (spec.get("mappers") or {}).items()}

    # -- ingestion ------------------------------------------------------
    def parse_rows(self, rows) -> np.ndarray:
        """JSON rows -> float32 matrix; None (JSON null) and missing
        tokens become NaN for the mappers' missing handling."""
        out = np.empty((len(rows), self.num_features), dtype=np.float32)
        for i, row in enumerate(rows):
            if len(row) != self.num_features:
                raise ValueError(
                    f"row {i} has {len(row)} values, model expects "
                    f"{self.num_features}")
            out[i] = [self._scalar(v) for v in row]
        return out

    def parse_csv(self, text: str, sep: Optional[str] = None) -> np.ndarray:
        """CSV text -> float32 matrix. Separator auto-detected
        (comma/tab/semicolon) from the first line when not given; blank
        lines are skipped; missing tokens become NaN."""
        lines = [ln for ln in text.splitlines() if ln.strip()]
        if not lines:
            raise ValueError("empty csv body")
        if sep is None:
            sep = max(",\t;", key=lines[0].count)
        rows: List[List[str]] = [ln.split(sep) for ln in lines]
        return self.parse_rows(rows)

    @staticmethod
    def _scalar(v) -> float:
        if v is None:
            return float("nan")
        if isinstance(v, str):
            if v.strip().lower() in _MISSING_TOKENS:
                return float("nan")
            return float(v)
        return float(v)

    # -- binning --------------------------------------------------------
    def bin_rows(self, x: np.ndarray) -> np.ndarray:
        """Raw matrix -> int32 bin codes (columns without a mapper code
        to 0 — they carry no signal the model can read)."""
        x = np.asarray(x, dtype=np.float64)
        codes = np.zeros(x.shape, dtype=np.int32)
        for f, mapper in self.mappers.items():
            codes[:, f] = mapper.values_to_bins(x[:, f])
        return codes

    def representative(self, codes: np.ndarray) -> np.ndarray:
        """Bin codes -> the representative raw value of each bin (the
        bin upper bound for numerical features, the category value for
        categorical) — the values `bin_to_value` would return, so every
        tree threshold comparison matches the raw value's."""
        out = np.zeros(codes.shape, dtype=np.float32)
        for f, mapper in self.mappers.items():
            if mapper.bin_type == BIN_NUMERICAL:
                table = np.asarray(mapper.bin_upper_bound,
                                   dtype=np.float64)
            else:
                table = np.asarray(
                    [float(c) for c in mapper.bin_2_categorical]
                    + [-1.0], dtype=np.float64)
            out[:, f] = table[np.clip(codes[:, f], 0, len(table) - 1)]
        return out

    def prebin_rows(self, x: np.ndarray) -> np.ndarray:
        """Raw matrix -> bin-representative matrix: what a pre-binning
        client would send. Unmapped columns pass through unchanged."""
        x = np.asarray(x, dtype=np.float32)
        pre = self.representative(self.bin_rows(x))
        for f in range(self.num_features):
            if f not in self.mappers:
                pre[:, f] = x[:, f]
        return pre

    def describe(self) -> dict:
        return {"num_features": self.num_features,
                "mapped_features": sorted(self.mappers)}
