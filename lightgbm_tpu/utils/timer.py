"""Phase timers + profiler integration.

Equivalent of the reference's TIMETAG accumulating timers (reference:
src/treelearner/serial_tree_learner.cpp:21-48, CMake USE_TIMETAG) printed at
teardown, plus a jax.profiler trace hook for TPU timeline capture.

Enable with env LGBM_TPU_TIMETAG=1 or config timetag=true; report via
`report()` or automatically at interpreter exit.
"""
from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict

from . import log

_acc: Dict[str, float] = defaultdict(float)
_cnt: Dict[str, int] = defaultdict(int)
_enabled = os.environ.get("LGBM_TPU_TIMETAG", "0") not in ("0", "", "false")


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def timer(name: str):
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _acc[name] += time.perf_counter() - t0
        _cnt[name] += 1


def add(name: str, seconds: float) -> None:
    if _enabled:
        _acc[name] += seconds
        _cnt[name] += 1


def report() -> Dict[str, float]:
    if _acc:
        log.info("cost summary:")
        for name in sorted(_acc):
            log.info("  %-24s %10.3fs  (%d calls)",
                     name, _acc[name], _cnt[name])
    return dict(_acc)


def reset() -> None:
    _acc.clear()
    _cnt.clear()


@atexit.register
def _report_at_exit():  # pragma: no cover
    if _enabled and _acc:
        report()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture an XLA/TPU timeline with jax.profiler (view in TensorBoard
    or xprof). The reference has no device tracing; this replaces its
    wall-clock logs for kernel-level analysis."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
