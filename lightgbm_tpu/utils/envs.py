"""Environment knobs shared across modules (single parse, single name)."""
from __future__ import annotations

import os

_TRUE = ("1", "true", "yes", "on")


def flag(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).strip().lower() in _TRUE


def use_pallas_env() -> bool:
    """Opt-in to the Pallas histogram kernel (both learners honor both
    spellings; the XLA one-hot path measured faster on v5e so default off)."""
    return flag("LGBM_TPU_PALLAS") or flag("LGBM_TPU_PALLAS_HIST")


def partition_mode_env(default: str = "sort") -> str:
    """LGBM_TPU_PARTITION selects the compact window-split formulation:
    'sort' (argsort+take — latency-bound on TPU: the sort's O(W log W)
    passes dominate small windows, the row gather runs at 3-10 GB/s),
    'scan' (destination = cumsum of the partition flags + one row
    scatter — two linear passes, no sort), or 'pallas' (the block-
    streaming one-hot-matmul kernel, ops/pallas/partition_kernel.py).
    LGBM_TPU_PALLAS_PART=1 is the round-2 spelling of 'pallas'.
    `default` carries the caller's measured backend/strategy-aware
    choice (device_learner: scan on TPU+compact, round-5 battery)."""
    mode = os.environ.get("LGBM_TPU_PARTITION", "").strip().lower()
    if mode in ("sort", "scan", "pallas"):
        return mode
    resolved = "pallas" if flag("LGBM_TPU_PALLAS_PART") else default
    if mode:
        from . import log
        log.warning("Unknown LGBM_TPU_PARTITION=%r; using %s", mode, resolved)
    return resolved


def pipeline_env() -> bool:
    """LGBM_TPU_PIPELINE: overlap the fused iteration's split-record
    D2H fetch + host tree replay with the NEXT iteration's device
    program (models materialize lazily through GBDT.models). Default on
    for TPU — the record fetch costs one ~70 ms tunnel round trip per
    iteration (tools/profile_fused.py, round 5) that the pipeline hides
    entirely — and off elsewhere (on CPU the fetch is free and the
    synchronous path keeps step-debugging simple)."""
    v = os.environ.get("LGBM_TPU_PIPELINE", "").strip().lower()
    if v:
        return v in _TRUE
    import jax
    return jax.default_backend() == "tpu"


def strategy_env(default: str = "auto") -> str:
    """LGBM_TPU_STRATEGY: auto | masked | compact | chunk — the ONE
    read shared by the device learner's resolve_strategy and the
    sharded learners' chunk opt-in."""
    return os.environ.get("LGBM_TPU_STRATEGY", default).strip().lower()


def dp_reduce_mode_env() -> str:
    """LGBM_TPU_DP_REDUCE: 'scatter' (reference comm pattern, default) or
    'psum' (replicated histograms) for the data-parallel device learner."""
    return os.environ.get("LGBM_TPU_DP_REDUCE", "scatter").strip().lower()
