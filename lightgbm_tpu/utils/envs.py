"""Environment knobs shared across modules (single parse, single name)."""
from __future__ import annotations

import os

_TRUE = ("1", "true", "yes", "on")


def flag(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).strip().lower() in _TRUE


def use_pallas_env() -> bool:
    """Opt-in to the Pallas histogram kernel (both learners honor both
    spellings; the XLA one-hot path measured faster on v5e so default off)."""
    return flag("LGBM_TPU_PALLAS") or flag("LGBM_TPU_PALLAS_HIST")


def partition_mode_env(default: str = "sort") -> str:
    """LGBM_TPU_PARTITION selects the compact window-split formulation:
    'sort' (argsort+take — latency-bound on TPU: the sort's O(W log W)
    passes dominate small windows, the row gather runs at 3-10 GB/s),
    'scan' (destination = cumsum of the partition flags + one row
    scatter — two linear passes, no sort), or 'pallas' (the block-
    streaming one-hot-matmul kernel, ops/pallas/partition_kernel.py).
    LGBM_TPU_PALLAS_PART=1 is the round-2 spelling of 'pallas'.
    `default` carries the caller's measured backend/strategy-aware
    choice (device_learner: scan on TPU+compact, round-5 battery)."""
    mode = os.environ.get("LGBM_TPU_PARTITION", "").strip().lower()
    if mode in ("sort", "scan", "pallas"):
        return mode
    resolved = "pallas" if flag("LGBM_TPU_PALLAS_PART") else default
    if mode:
        from . import log
        log.warning("Unknown LGBM_TPU_PARTITION=%r; using %s", mode, resolved)
    return resolved


def strategy_env(default: str = "auto") -> str:
    """LGBM_TPU_STRATEGY: auto | masked | compact | chunk — the ONE
    read shared by the device learner's resolve_strategy and the
    sharded learners' chunk opt-in."""
    return os.environ.get("LGBM_TPU_STRATEGY", default).strip().lower()


def dp_reduce_mode_env() -> str:
    """LGBM_TPU_DP_REDUCE: 'scatter' (reference comm pattern, default) or
    'psum' (replicated histograms) for the data-parallel device learner."""
    return os.environ.get("LGBM_TPU_DP_REDUCE", "scatter").strip().lower()
