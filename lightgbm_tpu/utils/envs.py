"""Environment knobs shared across modules (single parse, single name)."""
from __future__ import annotations

import os

_TRUE = ("1", "true", "yes", "on")


def flag(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).strip().lower() in _TRUE


def use_pallas_env() -> bool:
    """Opt-in to the Pallas histogram kernel (both learners honor both
    spellings; the XLA one-hot path measured faster on v5e so default off)."""
    return flag("LGBM_TPU_PALLAS") or flag("LGBM_TPU_PALLAS_HIST")


def use_pallas_partition_env() -> bool:
    """Opt-in to the Pallas stable-partition kernel for the compact
    growth loop's window split (replaces argsort+take, which is
    gather-latency-bound on TPU)."""
    return flag("LGBM_TPU_PALLAS_PART")


def dp_reduce_mode_env() -> str:
    """LGBM_TPU_DP_REDUCE: 'scatter' (reference comm pattern, default) or
    'psum' (replicated histograms) for the data-parallel device learner."""
    return os.environ.get("LGBM_TPU_DP_REDUCE", "scatter").strip().lower()
