"""Logging for lightgbm_tpu.

Mirrors the reference's four-level logger with Fatal-raises semantics
(reference: include/LightGBM/utils/log.h:27-108).
"""
import sys

_LEVELS = {"fatal": -1, "warning": 0, "info": 1, "debug": 2}
_current_level = 1


class LightGBMError(Exception):
    """Raised on fatal errors (the reference throws std::runtime_error)."""


def set_verbosity(verbosity: int) -> None:
    global _current_level
    _current_level = int(verbosity)


def get_verbosity() -> int:
    return _current_level


def debug(msg, *args):
    if _current_level >= 2:
        _emit("Debug", msg % args if args else msg)


def info(msg, *args):
    if _current_level >= 1:
        _emit("Info", msg % args if args else msg)


def warning(msg, *args):
    if _current_level >= 0:
        _emit("Warning", msg % args if args else msg)


def fatal(msg, *args):
    text = msg % args if args else msg
    raise LightGBMError(text)


def _emit(level, text):
    sys.stderr.write(f"[LightGBM-TPU] [{level}] {text}\n")
    sys.stderr.flush()


def check(cond, msg="check failed"):
    if not cond:
        fatal(msg)
