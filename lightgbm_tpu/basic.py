"""User-facing Dataset and Booster.

Signature-compatible with the reference Python package
(reference: python-package/lightgbm/basic.py:711 Dataset, :1658 Booster) so
existing LightGBM user code ports by changing the import. There is no ctypes
boundary — the "C API" role is played by the in-process engine
(models/gbdt.py); a C-ABI shim lives in capi/ for external bindings.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

import numpy as np

from .config import Config, parse_config_str
from .io.dataset import Dataset as _InnerDataset
from .models.gbdt import GBDT, create_boosting
from .ops.predict import _bucket_up
from .utils import log
from .utils.log import LightGBMError

__all__ = ["Dataset", "Booster", "LightGBMError"]

# row-batch size for sparse (CSR) prediction; module-level so tests can
# shrink it to exercise the multi-batch + ragged-tail path cheaply
_SPARSE_PREDICT_BATCH = 65536


def _load_data_from_file(path: str):
    """Parse CSV/TSV/LibSVM with auto-detection
    (reference: src/io/parser.cpp CreateParser)."""
    from .io.parser import parse_file
    return parse_file(path)


def _data_from_pandas(df, categorical_feature, pandas_categorical):
    """Convert a DataFrame to a float64 matrix, mapping category-dtype
    columns to their integer codes (reference: basic.py:312
    _data_from_pandas). For a training frame the category lists are
    captured; for valid/predict frames the stored lists re-align each
    column's categories so codes agree with training.

    Returns (matrix, feature_names, categorical_feature, pandas_categorical).
    """
    cat_cols = [c for c in df.columns if str(df[c].dtype) == "category"]
    realign = pandas_categorical is not None
    if not realign:                       # train frame: capture the lists
        pandas_categorical = [list(df[c].cat.categories) for c in cat_cols]
    elif len(cat_cols) != len(pandas_categorical):
        # also catches a frame whose categorical column LOST its dtype
        # (raw values would silently be compared against learned codes)
        raise ValueError(
            "train and valid dataset categorical_feature do not match")
    if categorical_feature == "auto":
        # positions, not labels: a column labeled with an int must not be
        # read as a feature index downstream
        categorical_feature = [int(df.columns.get_loc(c)) for c in cat_cols]
    feature_names = [str(c) for c in df.columns]
    if cat_cols:
        df = df.copy()
        if realign:
            for c, cats in zip(cat_cols, pandas_categorical):
                df[c] = df[c].cat.set_categories(cats)
        for c in cat_cols:
            codes = df[c].cat.codes.values.astype(np.float64)
            codes[codes == -1] = np.nan    # unseen/missing categories
            df[c] = codes
    x = df.astype(np.float64).values
    return x, feature_names, categorical_feature, pandas_categorical


_PANDAS_CAT_PREFIX = "\npandas_categorical:"


def _json_default_with_numpy(obj):
    """numpy scalars -> native JSON types; int categories must stay ints
    or predict-time set_categories() matches nothing (reference:
    basic.py json_default_with_numpy). Anything else fails loudly at
    save time — a stringified category would silently match nothing on
    reload."""
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    raise TypeError(
        f"pandas category values of type {type(obj).__name__} cannot be "
        "recorded in the model file; use str/int/float categories")


def _dump_pandas_categorical(pandas_categorical) -> str:
    """Model-file trailer recording the category lists (reference:
    basic.py:366)."""
    import json
    return _PANDAS_CAT_PREFIX + json.dumps(
        pandas_categorical, default=_json_default_with_numpy) + "\n"


def _split_pandas_categorical(model_str: str):
    """(model text without trailer, pandas_categorical or None)."""
    import json
    i = model_str.rfind(_PANDAS_CAT_PREFIX)
    if i < 0:
        return model_str, None
    line = model_str[i + len(_PANDAS_CAT_PREFIX):].strip()
    try:
        return model_str[:i] + "\n", json.loads(line)
    except ValueError:
        return model_str, None


class Dataset:
    """Lazily-constructed training data (reference: basic.py:711)."""

    def __init__(self, data, label=None, reference=None, weight=None,
                 group=None, init_score=None, silent=False,
                 feature_name="auto", categorical_feature="auto",
                 params=None, free_raw_data=True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) or {}
        self.free_raw_data = free_raw_data
        self._inner: Optional[_InnerDataset] = None
        self._label_from_file = None
        self.pandas_categorical = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._inner is not None:
            return self
        data = self.data
        label = self.label
        feature_names = None
        if isinstance(data, str) and Config(self.params).two_round \
                and self.reference is None:
            # out-of-core path: two streaming passes, no float matrix
            # (reference dataset_loader.cpp:168 two_round)
            from .io.two_round import load_two_round
            cfg2 = Config(self.params)
            cats = self.categorical_feature
            inner, y = load_two_round(
                data, cfg2,
                categorical_feature=(cats if isinstance(cats,
                                                        (list, tuple))
                                     else None))
            self._load_side_files(data)
            if self.label is not None:
                inner.metadata.set_label(self.label)
            if self.weight is not None:
                inner.metadata.set_weight(self.weight)
            if self.group is not None:
                inner.metadata.set_group(self.group)
            if self.init_score is not None:
                inner.metadata.set_init_score(self.init_score)
            if isinstance(self.feature_name, (list, tuple)):
                inner.feature_names = list(self.feature_name)
            self._inner = inner
            return self
        if isinstance(data, str):
            x, y, qb = _load_data_from_file(data)
            data = x
            if label is None and y is not None:
                label = y
            if self.group is None and qb is not None:
                self.group = np.diff(qb)
        cat_spec = self.categorical_feature
        if hasattr(data, "columns"):  # pandas: category dtypes -> codes
            ref_pc = None
            if self.reference is not None:
                # the template must be constructed first so its captured
                # category lists align this frame's codes
                self.reference.construct()
                ref_pc = self.reference.pandas_categorical
            data, feature_names, cat_spec, self.pandas_categorical = \
                _data_from_pandas(data, cat_spec, ref_pc)
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        cats = None
        if isinstance(cat_spec, (list, tuple)):
            # names -> column indices (pandas auto-detection yields names)
            cats = []
            for c in cat_spec:
                if isinstance(c, str):
                    if feature_names is None or c not in feature_names:
                        raise LightGBMError(
                            f"categorical_feature {c!r} not in features")
                    cats.append(feature_names.index(c))
                else:
                    cats.append(int(c))
        cfg = Config(self.params)
        ref_inner = None
        if self.reference is not None:
            self.reference.construct()
            ref_inner = self.reference._inner
        if isinstance(self.data, str):
            self._load_side_files(self.data)
        self._inner = _InnerDataset(
            data, config=cfg, label=label, weight=self.weight,
            group=self.group, init_score=self.init_score,
            feature_names=feature_names, categorical_feature=cats,
            reference=ref_inner)
        if self.free_raw_data and not isinstance(self.data, str):
            self.data = None
        return self

    def _load_side_files(self, path: str) -> None:
        """<data>.weight / <data>.query ride along with a file dataset
        (reference: Metadata::LoadWeights/LoadQueryBoundaries) — the ONE
        copy shared by the in-memory and two_round construct branches."""
        import os
        if self.weight is None and os.path.exists(path + ".weight"):
            self.weight = np.loadtxt(path + ".weight")
        if self.group is None and os.path.exists(path + ".query"):
            self.group = np.loadtxt(path + ".query").astype(np.int64)

    def _update_params(self, params: Dict[str, Any]) -> None:
        if self._inner is not None:
            return  # constructed; params frozen like the reference
        self.params.update(params or {})

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, silent=silent,
                       params=params or self.params)

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._inner is not None:
            self._inner.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.set_weight(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None:
            self._inner.metadata.set_group(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.set_init_score(init_score)
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name == "label":
            return self.set_label(data)
        if field_name == "weight":
            return self.set_weight(data)
        if field_name == "group":
            return self.set_group(data)
        if field_name == "init_score":
            return self.set_init_score(data)
        raise LightGBMError(f"Unknown field {field_name}")

    def get_field(self, field_name: str):
        self.construct()
        md = self._inner.metadata
        if field_name == "label":
            return md.label
        if field_name == "weight":
            return md.weight
        if field_name == "group":
            return (np.diff(md.query_boundaries)
                    if md.query_boundaries is not None else None)
        if field_name == "init_score":
            return md.init_score
        raise LightGBMError(f"Unknown field {field_name}")

    def get_label(self):
        return self.get_field("label")

    def get_data(self):
        """Raw data used for construction (reference: basic.py:1512);
        None once free_raw_data dropped it."""
        if self._inner is None:
            raise LightGBMError("Cannot get data before construct Dataset")
        return self.data

    def get_feature_penalty(self):
        """Per-feature gain penalty (feature_contri), None when unset
        (reference: basic.py:1476)."""
        contri = self.construct()._inner.config.feature_contri
        return np.asarray(contri, dtype=np.float64) if contri else None

    def get_monotone_constraints(self):
        """Per-feature monotone constraints, None when unset
        (reference: basic.py:1488)."""
        mono = self.construct()._inner.config.monotone_constraints
        return np.asarray(mono, dtype=np.int8) if mono else None

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        return self.get_field("group")

    def get_init_score(self):
        return self.get_field("init_score")

    def num_data(self) -> int:
        self.construct()
        return self._inner.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._inner.num_total_features

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._inner.feature_names)

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset (reference basic.py Dataset.subset)."""
        self.construct()
        # row order is normalized like the reference (basic.py subset
        # sorts); the group reconstruction below depends on it
        used_indices = np.sort(np.asarray(used_indices))
        sub = Dataset.__new__(Dataset)
        sub.params = params or self.params
        sub.free_raw_data = True
        sub.data = None
        sub.reference = self
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.pandas_categorical = self.pandas_categorical
        sub._label_from_file = None
        inner = copy.copy(self._inner)
        inner.binned = self._inner.binned[used_indices]
        if getattr(self._inner, "bundled", None) is not None:
            inner.bundled = self._inner.bundled[used_indices]
        inner.num_data = len(used_indices)
        from .io.dataset import Metadata
        md = Metadata(inner.num_data)
        src = self._inner.metadata
        if src.label is not None:
            md.label = src.label[used_indices]
        if src.weight is not None:
            md.weight = src.weight[used_indices]
        n_src = self._inner.num_data
        if src.init_score is not None:
            isc = np.asarray(src.init_score)
            if isc.size == n_src:
                md.init_score = isc[used_indices]
            else:
                # flat multiclass layout is class-major ((K, N) flattened,
                # see ScoreUpdater): slice every class's block
                k = isc.size // n_src
                md.init_score = isc.reshape(k, n_src)[:, used_indices] \
                    .reshape(-1)
        group_sizes = None
        if src.query_boundaries is not None:
            # per-query row counts among the kept rows (group-aware cv
            # folds keep whole queries; partial queries shrink)
            qb = np.asarray(src.query_boundaries)
            qidx = np.searchsorted(qb, used_indices, side="right") - 1
            counts = np.bincount(qidx, minlength=len(qb) - 1)
            group_sizes = counts[counts > 0]
            md.set_group(group_sizes)
        inner.metadata = md
        inner._device_cache = {}
        sub._inner = inner
        sub.label = md.label
        sub.weight = md.weight
        sub.group = group_sizes
        sub.init_score = md.init_score
        return sub

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        self._inner.save_binary(filename)
        return self

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Column-concatenate another dataset at the binned level
        (reference: Dataset::addFeaturesFrom, src/io/dataset.cpp merges
        feature groups without re-binning); EFB bundles are re-planned
        over the combined features."""
        self.construct()
        other.construct()
        if self.num_data() != other.num_data():
            raise ValueError("datasets must have the same number of rows")
        a, b = self._inner, other._inner
        offset = a.num_total_features
        # _bin_data pads an all-trivial dataset with one dummy zero column;
        # drop dummies so binned stays aligned with used_features
        a_cols = a.binned if a.used_features else a.binned[:, :0]
        b_cols = b.binned if b.used_features else b.binned[:, :0]
        a.bin_mappers = list(a.bin_mappers) + list(b.bin_mappers)
        a.used_features = list(a.used_features) + [
            offset + f for f in b.used_features]
        a.max_num_bins = max(a.max_num_bins, b.max_num_bins)
        dt = (np.uint16 if max(a_cols.dtype.itemsize,
                               b_cols.dtype.itemsize) == 2 else np.uint8)
        merged = np.hstack([a_cols.astype(dt), b_cols.astype(dt)])
        if merged.shape[1] == 0:
            merged = np.zeros((a.num_data, 1), dtype=dt)
        a.binned = merged
        a.num_total_features += b.num_total_features
        a.feature_names = list(a.feature_names) + list(b.feature_names)
        a.columns = a._plan_bundles()
        a.bundled = a._encode_bundles() if a.columns else None
        a._device_cache = {}
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        self.categorical_feature = categorical_feature
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        self.feature_name = feature_name
        return self

    def get_ref_chain(self, ref_limit: int = 100) -> set:
        """Walk the reference chain (reference: basic.py:1295)."""
        head = self
        ref_chain = set()
        while len(ref_chain) < ref_limit:
            if isinstance(head, Dataset):
                ref_chain.add(id(head))
                if head.reference is not None and \
                        id(head.reference) not in ref_chain:
                    head = head.reference
                else:
                    break
            else:
                break
        return ref_chain

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Use `reference`'s bin mappers as the template for this dataset
        (reference: basic.py:1319). Constructed state is dropped so the
        next construct() aligns to the new reference; requires the raw
        data to still be around (free_raw_data=False)."""
        if not isinstance(reference, Dataset):
            raise TypeError("Reference should be Dataset instance")
        self.set_categorical_feature(reference.categorical_feature) \
            .set_feature_name(reference.feature_name)
        if self.get_ref_chain().intersection(reference.get_ref_chain()):
            return self
        if self.data is not None:
            self.reference = reference
            self._inner = None     # re-construct against the new template
            return self
        raise LightGBMError(
            "Cannot set reference after freed raw data, set "
            "free_raw_data=False when construct Dataset to avoid this.")


_NO_DEFAULT = object()


class Booster:
    """Training/prediction handle (reference: basic.py:1658)."""

    def __init__(self, params=None, train_set: Optional[Dataset] = None,
                 model_file=None, model_str=None, silent=False):
        self.params = copy.deepcopy(params) or {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_data_name = "training"
        self.name_valid_sets: List[str] = []
        self._gbdt: Optional[GBDT] = None
        self._attr: Dict[str, str] = {}

        self.pandas_categorical = None
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            train_set._update_params(self.params)
            train_set.construct()
            self.pandas_categorical = train_set.pandas_categorical
            cfg = train_set._inner.config
            cfg.update(self.params)
            self._gbdt = create_boosting(cfg, train_set._inner)
            self.train_set = train_set
        elif model_file is not None:
            from .io.file_io import read_text
            text, self.pandas_categorical = _split_pandas_categorical(
                read_text(model_file))
            self._gbdt = GBDT.load_model_from_string(
                text, Config(self.params))
        elif model_str is not None:
            text, self.pandas_categorical = _split_pandas_categorical(
                model_str)
            self._gbdt = GBDT.load_model_from_string(text, Config(self.params))
        else:
            raise TypeError("need at least one of train_set, model_file, model_str")

    # ------------------------------------------------------------------
    # pickling / copying ride the model string (reference: basic.py
    # Booster.__getstate__/__deepcopy__): the engine holds jitted device
    # closures that cannot serialize; the reloaded booster predicts and
    # continues via init_model, but drops the live training state.
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_gbdt"] = None
        state["train_set"] = None
        state["_model_str"] = (self.model_to_string(num_iteration=-1)
                               if self._gbdt is not None else None)
        return state

    def __setstate__(self, state):
        model_str = state.pop("_model_str", None)
        self.__dict__.update(state)
        if model_str is not None:
            self.model_from_string(model_str, verbose=False)

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, _):
        return Booster(model_str=self.model_to_string(num_iteration=-1))

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self._gbdt.add_valid(data._inner, name)
        self.name_valid_sets.append(name)
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def update(self, train_set=None, fobj=None) -> bool:
        """One boosting iteration; returns True if stopped early
        (reference Booster.update -> LGBM_BoosterUpdateOneIter)."""
        if fobj is not None:
            grad, hess = fobj(self.__inner_predict_raw(), self.train_set)
            return self.__boost(grad, hess)
        return self._gbdt.train_one_iter()

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, dtype=np.float32)
        hess = np.asarray(hess, dtype=np.float32)
        return self._gbdt.train_one_iter(grad, hess)

    def __inner_predict_raw(self) -> np.ndarray:
        scores = self._gbdt.score_updater.host_scores()
        return scores[0] if self._gbdt.num_class == 1 else scores.reshape(-1)

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def save_checkpoint(self, directory: str, keep_last: int = 3,
                        history=None) -> str:
        """Write a full training checkpoint — model trees PLUS live
        training state (scores, bagging RNG, iteration counter) — into
        `directory` with keep-last-`keep_last` rotation; returns the
        path. Unlike save_model, a checkpoint resumes training
        bit-identically (see docs/Reliability.md)."""
        from .resilience.checkpoint import CheckpointManager
        return CheckpointManager(directory, keep_last).save(
            self, history=history)

    def restore_checkpoint(self, path: str) -> "Booster":
        """Restore model + training state from a checkpoint file (or the
        newest valid one in a directory) into this booster. The booster
        must have been constructed with the same train/valid datasets
        and parameters as the checkpointed run."""
        from .resilience.checkpoint import restore_checkpoint
        restore_checkpoint(self, path)
        return self

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration

    def num_trees(self) -> int:
        return self._gbdt.num_trees()

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    # ------------------------------------------------------------------
    def eval_train(self, feval=None):
        return self.__eval(self._train_data_name, feval)

    def eval_valid(self, feval=None):
        out = []
        for name in self.name_valid_sets:
            out.extend(self.__eval(name, feval))
        return out

    def eval(self, data=None, name=None, feval=None):
        return self.eval_train(feval) + self.eval_valid(feval)

    def __eval(self, dataset_name, feval=None):
        results = []
        for dname, mname, val, hb in self._gbdt.eval_metrics():
            if dname == "training":
                dname = self._train_data_name
            if dname == dataset_name:
                results.append((dname, mname, val, hb))
        if feval is not None:
            if dataset_name == self._train_data_name:
                ds, updater = self.train_set, self._gbdt.score_updater
            else:
                idx = self.name_valid_sets.index(dataset_name)
                ds = self._gbdt.valid_sets[idx]
                updater = self._gbdt.valid_updaters[idx]
            preds = updater.host_scores()
            preds = preds[0] if self._gbdt.num_class == 1 else preds.reshape(-1)
            ret = feval(preds, ds)
            rets = ret if isinstance(ret, list) else [ret]
            for (n, v, hb) in rets:
                results.append((dataset_name, n, v, hb))
        return results

    # ------------------------------------------------------------------
    def predict(self, data, num_iteration=None, raw_score=False,
                pred_leaf=False, pred_contrib=False, data_has_header=False,
                is_reshape=True, start_iteration=0, pred_early_stop=False,
                pred_early_stop_freq=10, pred_early_stop_margin=10.0,
                **kwargs):
        if isinstance(data, str):
            x, _, _ = _load_data_from_file(data)
        else:
            x = data
        if hasattr(x, "columns"):
            # DataFrame: align category columns to the training capture
            # so codes agree (reference predict-time _data_from_pandas)
            x, _, _, _ = _data_from_pandas(x, "auto",
                                           self.pandas_categorical)
        elif hasattr(x, "values"):
            x = x.values
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else None)

        def run(mat):
            return self._gbdt.predict(
                mat, num_iteration=num_iteration, raw_score=raw_score,
                pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                start_iteration=start_iteration,
                pred_early_stop=pred_early_stop,
                pred_early_stop_freq=pred_early_stop_freq,
                pred_early_stop_margin=pred_early_stop_margin)
        try:
            import scipy.sparse as sp
            is_sp = sp.issparse(x)
        except ImportError:
            is_sp = False
        if is_sp:
            # row-batched sparse prediction: peak dense memory is one
            # (B, F) batch, never the whole matrix (the reference
            # iterates sparse rows directly, c_api.cpp PredictForCSR)
            x = x.tocsr()
            batch = _SPARSE_PREDICT_BATCH
            if x.shape[0] <= batch:
                return run(np.asarray(x.todense()))

            def run_padded(mat):
                # ragged tail: pad rows up to a power-of-two bucket so
                # the last chunk shares a compiled program across calls
                # instead of paying a per-size XLA compile
                n = mat.shape[0]
                bucketed = _bucket_up(n)
                if bucketed != n:
                    pad = np.zeros((bucketed - n, mat.shape[1]),
                                   dtype=mat.dtype)
                    return run(np.concatenate([mat, pad], axis=0))[:n]
                return run(mat)
            parts = [run_padded(np.asarray(x[i:i + batch].todense()))
                     for i in range(0, x.shape[0], batch)]
            return np.concatenate(parts, axis=0)
        return run(x)

    def refit(self, data, label, decay_rate=0.9, **kwargs):
        """Refit leaf values on new data IN PLACE (reference
        Booster.refit keeps the handle too). Historically this rebuilt
        a whole new Booster — training context, predictor caches and
        all — to change one param; now only a binned Dataset is built
        for the gradient context and the tree leaves are rewritten in
        this model with a single ensemble-cache invalidation, so
        back-to-back refit+predict cycles re-tensorize the ensemble
        exactly once per refit. Returns self."""
        self.params["refit_decay_rate"] = decay_rate
        leaf_preds = self.predict(data, pred_leaf=True)
        ds = Dataset(data, label)
        ds._update_params(self.params)
        ds.construct()
        self._gbdt.refit_leaves_on(ds._inner, leaf_preds, decay_rate)
        return self

    # ------------------------------------------------------------------
    def save_model(self, filename, num_iteration=None,
                   start_iteration=0) -> "Booster":
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        if self.pandas_categorical:
            # one write incl. the category-list trailer: append mode is
            # not supported by all file_io schemes (object stores)
            from .io.file_io import write_text
            write_text(filename,
                       self.model_to_string(num_iteration, start_iteration))
        else:
            self._gbdt.save_model(filename, num_iteration, start_iteration)
        return self

    def model_to_string(self, num_iteration=None, start_iteration=0) -> str:
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        s = self._gbdt.save_model_to_string(start_iteration, num_iteration)
        if self.pandas_categorical:
            s += _dump_pandas_categorical(self.pandas_categorical)
        return s

    def dump_model(self, num_iteration=None, start_iteration=0) -> dict:
        return self._gbdt.dump_model(num_iteration, start_iteration)

    def model_from_string(self, model_str: str, verbose=True) -> "Booster":
        """Replace this Booster's model with one loaded from a string
        (reference: basic.py:2241)."""
        model_str, self.pandas_categorical = _split_pandas_categorical(
            model_str)
        self._gbdt = GBDT.load_model_from_string(model_str,
                                                 Config(self.params))
        if verbose:
            log.info("Finished loading model, total used %d iterations",
                     self._gbdt.current_iteration)
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """Output value of one leaf (reference: basic.py:2463
        -> LGBM_BoosterGetLeafValue)."""
        return float(self._gbdt.models[tree_id].leaf_value[leaf_id])

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style=False):
        """Histogram of split threshold values used for `feature`
        (reference: basic.py:2565). Categorical features are rejected
        like the reference."""
        def add(root):
            if "split_index" in root:     # non-leaf
                if feature_names is not None and isinstance(feature, str):
                    split_feature = feature_names[root["split_feature"]]
                else:
                    split_feature = root["split_feature"]
                if split_feature == feature:
                    if isinstance(root["threshold"], str):
                        raise LightGBMError(
                            "Cannot compute split value histogram for the "
                            "categorical feature")
                    values.append(root["threshold"])
                add(root["left_child"])
                add(root["right_child"])

        model = self.dump_model()
        feature_names = model.get("feature_names")
        values: List[float] = []
        for tree_info in model["tree_info"]:
            add(tree_info["tree_structure"])

        if bins is None or isinstance(bins, int) and xgboost_style:
            n_unique = len(np.unique(values))
            bins = max(min(n_unique, bins) if bins is not None
                       else n_unique, 1)
        hist, bin_edges = np.histogram(values, bins=bins)
        if xgboost_style:
            ret = np.column_stack((bin_edges[1:], hist))
            ret = ret[ret[:, 1] > 0]
            try:
                from pandas import DataFrame
                return DataFrame(ret, columns=["SplitValue", "Count"])
            except ImportError:
                return ret
        return hist, bin_edges

    def attr(self, key: str) -> Optional[str]:
        """Get a Booster attribute string (reference: basic.py:2717)."""
        return self._attr.get(key, None)

    def set_attr(self, **kwargs) -> "Booster":
        """Set Booster attributes; None deletes (reference: basic.py:2733)."""
        for key, value in kwargs.items():
            if value is not None:
                if not isinstance(value, str):
                    raise ValueError("Only string values are accepted")
                self._attr[key] = value
            else:
                self._attr.pop(key, None)
        return self

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type="split",
                           iteration=None) -> np.ndarray:
        imp = self._gbdt.feature_importance(importance_type, iteration)
        if importance_type == "split":
            return imp.astype(np.int64)
        return imp

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names)

    def num_feature(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def reset_parameter(self, params) -> "Booster":
        # validate via the config FIRST (it rejects atomically); only then
        # persist into self.params, so a caught rejection leaves neither
        # object mutated
        self._gbdt.config.update(params)
        self.params.update(params)
        self._gbdt.shrinkage_rate = self._gbdt.config.learning_rate
        # learning_rate rides the fused step as a traced argument; any other
        # param is baked in at trace time, so drop the cached programs
        # (the DP learner caches its sharded tree program the same way)
        if any(k != "learning_rate" for k in params):
            self._gbdt._fused_step = None
            if hasattr(self._gbdt.learner, "_tree_w_fn"):
                self._gbdt.learner._tree_w_fn = None
        return self

    def set_network(self, machines, local_listen_port=12400,
                    listen_time_out=120, num_machines=1) -> "Booster":
        from .parallel import network
        network.init_from_params(machines, local_listen_port, num_machines)
        return self

    def free_network(self) -> "Booster":
        from .parallel import network
        network.free()
        return self

    def free_dataset(self) -> "Booster":
        """Drop the training/validation datasets (and their score
        buffers) to free memory (reference: basic.py:1799); further
        update()/eval() calls are invalid."""
        self.train_set = None
        self.name_valid_sets = []
        if self._gbdt is not None:
            g = self._gbdt
            g.train_set = None
            g.valid_sets = []
            g.valid_updaters = []
            g.valid_metrics = []
            g.valid_names = []
            # the dominant allocations: the learner's binned/packed
            # buffers and the (K, N) score state
            g.learner = None
            g.score_updater = None
            g._fused_step = None
        return self

    def shuffle_models(self, start_iteration=0, end_iteration=-1) -> "Booster":
        import random
        models = self._gbdt.models
        end = len(models) if end_iteration < 0 else end_iteration
        seg = models[start_iteration:end]
        random.shuffle(seg)
        models[start_iteration:end] = seg
        return self
