"""Device histogram construction.

Role of the reference's hottest loops — Bin::ConstructHistogram
(reference: src/io/dense_bin.hpp:71-195, 4-way unrolled scalar scatter) and
the OpenCL kernels (src/treelearner/ocl/histogram256.cl, local-memory float
atomics). TPUs have no fast scatter-atomics, so the TPU-native formulation is
a one-hot contraction on the MXU: for a row chunk C,

    hist[f*B+b, k] += sum_n onehot[n, f*B+b] * gh[n, k]

i.e. a (FB, C) x (C, 3) matmul per chunk, accumulated over chunks with
lax.scan. The (gradient, hessian, count) triple rides the tiny K=3 axis;
padding rows carry gh = 0 so buckets can be padded freely.

A fused Pallas kernel (ops/pallas/histogram_kernel.py) implements the same
contract without materializing the one-hot in HBM.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .pallas import histogram_kernel as _pallas_hist

# floor of the derived chunk ladder: shapes with F*B >= 4M/floor elements
# resolve to exactly this, keeping the historical behavior bit-identical
_CHUNK_FLOOR = 2048
_CHUNK_CEIL = 32768


def resolve_chunk_size(chunk_size: int, f: int, num_bins: int) -> int:
    """Row-chunk size for the one-hot contraction.

    chunk_size > 0 wins (explicit caller / Config.hist_chunk_size);
    otherwise LGBM_TPU_HIST_CHUNK; otherwise derived from the contraction
    shape: the (FB, C) x (C, 3) matmul under-fills the MXU when F*B is
    small, so the chunk grows to keep ~2^22 one-hot elements per pass
    (clamped to [2048, 32768], multiple of 256). Read at trace time —
    the jit cache keys on the resolved static, so changing the env var
    after a shape compiled does not retrigger.
    """
    if chunk_size and int(chunk_size) > 0:
        return int(chunk_size)
    env = os.environ.get("LGBM_TPU_HIST_CHUNK", "").strip()
    if env:
        return max(256, int(env))
    c = (1 << 22) // max(int(f) * int(num_bins), 1)
    c = max(_CHUNK_FLOOR, min(_CHUNK_CEIL, c))
    return -(-c // 256) * 256


def _hist_chunk(binned_chunk: jax.Array, gh_chunk: jax.Array, num_bins: int) -> jax.Array:
    """One-hot contraction for one chunk.

    binned_chunk: (C, F) int8/int16 bin codes
    gh_chunk:     (C, 3) f32 (grad, hess, valid-count)
    returns       (F, B, 3) f32 partial histogram
    """
    c, f = binned_chunk.shape
    iota = jnp.arange(num_bins, dtype=jnp.int32)
    onehot = (binned_chunk.astype(jnp.int32)[:, :, None] == iota[None, None, :])
    # (FB, C) @ (C, 3) on the MXU. The one-hot is bf16-exact; gh is split
    # into bf16 hi + lo parts so each product is a fast single-pass bf16
    # matmul while the sum keeps ~f32 fidelity (rel err ~8e-7 vs HIGHEST,
    # tools/microbench_hist2.py). Plain DEFAULT would round gradients to
    # bf16, whose absolute error survives sibling subtraction
    # (subtract_histogram) disproportionately for small leaves; HIGHEST
    # costs ~40% more MXU time.
    onehot2d = onehot.reshape(c, f * num_bins).astype(jnp.bfloat16)
    gh_hi = gh_chunk.astype(jnp.bfloat16)
    gh_lo = (gh_chunk - gh_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((0,), (0,)), ((), ()))
    hist = (jax.lax.dot_general(onehot2d, gh_hi, dimension_numbers=dn,
                                preferred_element_type=jnp.float32)
            + jax.lax.dot_general(onehot2d, gh_lo, dimension_numbers=dn,
                                  preferred_element_type=jnp.float32))
    return hist.reshape(f, num_bins, 3)


@functools.partial(jax.jit, static_argnames=("num_bins", "chunk_size", "use_pallas"))
def build_histogram(binned_rows: jax.Array, gh: jax.Array, num_bins: int,
                    chunk_size: int = 0, use_pallas: bool = False) -> jax.Array:
    """Full histogram for a padded row window.

    binned_rows: (P, F) gathered bin codes for the leaf's rows (pad rows
                 arbitrary — their gh must be zero).
    gh:          (P, 3) f32 (grad, hess, valid) — valid is 0.0 on pad rows.
    chunk_size:  0 = resolve via Config/env/shape (resolve_chunk_size).
    Returns (F, B, 3) f32: per (feature, bin): [sum_grad, sum_hess, count].
    """
    if use_pallas:
        return _pallas_hist.build_histogram_pallas(binned_rows, gh, num_bins)
    p, f = binned_rows.shape
    chunk_size = resolve_chunk_size(chunk_size, f, num_bins)
    if p <= chunk_size:
        return _hist_chunk(binned_rows, gh, num_bins)
    n_chunks = (p + chunk_size - 1) // chunk_size
    pad = n_chunks * chunk_size - p
    if pad:
        binned_rows = jnp.pad(binned_rows, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    binned_rows = binned_rows.reshape(n_chunks, chunk_size, f)
    gh = gh.reshape(n_chunks, chunk_size, 3)

    def body(acc, chunk):
        b, g = chunk
        return acc + _hist_chunk(b, g, num_bins), None

    # the carry is seeded from the FIRST chunk (not zeros) so its type
    # carries the data's varying-manual-axes when this runs inside a
    # shard_map region (a replicated zeros carry + varying per-chunk
    # additions fails shard_map's carry type check); outside shard_map
    # it is the same arithmetic with one add saved
    init = _hist_chunk(binned_rows[0], gh[0], num_bins)
    hist, _ = jax.lax.scan(body, init, (binned_rows[1:], gh[1:]))
    return hist


def accumulate_histogram(acc: jax.Array, binned_rows: jax.Array,
                         gh: jax.Array, num_bins: int,
                         use_pallas: bool = False) -> jax.Array:
    """Streamed-accumulation hook: fold one row chunk's histogram into a
    running (F, B, 3) total — the seam the out-of-core pipeline
    (io/stream.py feeding the chunk core's prebuilt-data path) uses to
    build the root histogram chunk-wise. Integer (quantized) totals are
    chunk-grouping-independent (int32 addition is associative); float
    totals depend on grouping only through f32 addition order, which is
    exact whenever the per-chunk sums are exactly representable. The
    accumulator dtype picks the pipeline: int32 routes to the exact
    quantized contraction."""
    if acc.dtype == jnp.int32:
        return acc + build_histogram_quantized(
            binned_rows, gh, num_bins, use_pallas=use_pallas)
    return acc + build_histogram(binned_rows, gh, num_bins,
                                 use_pallas=use_pallas)


@jax.jit
def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram by subtraction (reference:
    src/treelearner/feature_histogram.hpp:75-81 FeatureHistogram::Subtract).
    Dtype-preserving: on the quantized path (int32 histograms) the
    subtraction is bit-exact integer arithmetic — no catastrophic
    cancellation for small siblings of large parents."""
    return parent - child


def _hist_chunk_q(binned_chunk: jax.Array, ghq_chunk: jax.Array,
                  num_bins: int) -> jax.Array:
    """Integer one-hot contraction for one chunk.

    binned_chunk: (C, F) int bin codes
    ghq_chunk:    (C, 3) int8/int32 [qg, qh, valid]
    returns       (F, B, 3) int32 EXACT partial histogram

    ONE matmul where the float path needs the bf16 hi/lo pair: the
    one-hot is cast to the operand dtype (i8 rides the MXU's native int8
    path) and the int32 accumulator is exact, so there is no split-
    precision correction pass and no rounding of the per-bin sums.
    """
    c, f = binned_chunk.shape
    iota = jnp.arange(num_bins, dtype=jnp.int32)
    onehot = (binned_chunk.astype(jnp.int32)[:, :, None] == iota[None, None, :])
    onehot2d = onehot.reshape(c, f * num_bins).astype(ghq_chunk.dtype)
    dn = (((0,), (0,)), ((), ()))
    hist = jax.lax.dot_general(onehot2d, ghq_chunk, dimension_numbers=dn,
                               preferred_element_type=jnp.int32)
    return hist.reshape(f, num_bins, 3)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_size", "use_pallas"))
def build_histogram_quantized(binned_rows: jax.Array, ghq: jax.Array,
                              num_bins: int, chunk_size: int = 0,
                              use_pallas: bool = False) -> jax.Array:
    """Integer histogram for a padded row window (quantized-grad path).

    binned_rows: (P, F) bin codes (pad rows arbitrary — their ghq rows
                 must be zero, i.e. valid == 0).
    ghq:         (P, 3) int8/int32 [qg, qh, valid] from ops/quantize.
    Returns (F, B, 3) int32 EXACT [sum_qg, sum_qh, count]: chunk order
    cannot change the result (integer addition is associative), unlike
    the float path where the scan order perturbs low bits.
    """
    if use_pallas:
        return _pallas_hist.build_histogram_pallas_quantized(
            binned_rows, ghq, num_bins)
    p, f = binned_rows.shape
    chunk_size = resolve_chunk_size(chunk_size, f, num_bins)
    if p <= chunk_size:
        return _hist_chunk_q(binned_rows, ghq, num_bins)
    n_chunks = (p + chunk_size - 1) // chunk_size
    pad = n_chunks * chunk_size - p
    if pad:
        binned_rows = jnp.pad(binned_rows, ((0, pad), (0, 0)))
        ghq = jnp.pad(ghq, ((0, pad), (0, 0)))
    binned_rows = binned_rows.reshape(n_chunks, chunk_size, f)
    ghq = ghq.reshape(n_chunks, chunk_size, 3)

    def body(acc, chunk):
        b, g = chunk
        return acc + _hist_chunk_q(b, g, num_bins), None

    # carry seeded from the FIRST chunk for the same shard_map varying-
    # manual-axes reason as the float path above
    init = _hist_chunk_q(binned_rows[0], ghq[0], num_bins)
    hist, _ = jax.lax.scan(body, init, (binned_rows[1:], ghq[1:]))
    return hist


@functools.partial(jax.jit, static_argnames=("num_bins", "bucket",
                                             "grad_bits", "chunk_size"))
def gather_and_build_quantized(binned: jax.Array, indices_buf: jax.Array,
                               gh_packed: jax.Array, begin: jax.Array,
                               count: jax.Array, num_bins: int, bucket: int,
                               grad_bits: int,
                               chunk_size: int = 0) -> jax.Array:
    """Quantized analog of gather_and_build: gather the leaf's packed
    (qg|qh) int32 rows and build the exact integer histogram."""
    from . import quantize as quant_ops
    window = jax.lax.dynamic_slice(indices_buf, (begin,), (bucket,))
    valid = (jnp.arange(bucket, dtype=jnp.int32) < count)
    rows = jnp.take(binned, window, axis=0)
    ghq = quant_ops.gh_operand(jnp.take(gh_packed, window), valid, grad_bits)
    return build_histogram_quantized(rows, ghq, num_bins,
                                     chunk_size=chunk_size)


@functools.partial(jax.jit, static_argnames=("num_bins", "bucket",
                                             "chunk_size"))
def gather_and_build(binned: jax.Array, indices_buf: jax.Array, grad: jax.Array,
                     hess: jax.Array, begin: jax.Array, count: jax.Array,
                     num_bins: int, bucket: int,
                     chunk_size: int = 0) -> jax.Array:
    """Gather a leaf's rows from the partition buffer and build its histogram.

    binned:      (N, F) full binned matrix
    indices_buf: (N + max_bucket,) int32 partition permutation (padded tail)
    begin/count: scalars (leaf slice in the partition buffer)
    bucket:      static padded window size >= count
    """
    window = jax.lax.dynamic_slice(indices_buf, (begin,), (bucket,))
    valid = (jnp.arange(bucket, dtype=jnp.int32) < count)
    rows = jnp.take(binned, window, axis=0)
    g = jnp.take(grad, window) * valid
    h = jnp.take(hess, window) * valid
    gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
    return build_histogram(rows, gh, num_bins, chunk_size=chunk_size)
