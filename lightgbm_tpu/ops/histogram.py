"""Device histogram construction.

Role of the reference's hottest loops — Bin::ConstructHistogram
(reference: src/io/dense_bin.hpp:71-195, 4-way unrolled scalar scatter) and
the OpenCL kernels (src/treelearner/ocl/histogram256.cl, local-memory float
atomics). TPUs have no fast scatter-atomics, so the TPU-native formulation is
a one-hot contraction on the MXU: for a row chunk C,

    hist[f*B+b, k] += sum_n onehot[n, f*B+b] * gh[n, k]

i.e. a (FB, C) x (C, 3) matmul per chunk, accumulated over chunks with
lax.scan. The (gradient, hessian, count) triple rides the tiny K=3 axis;
padding rows carry gh = 0 so buckets can be padded freely.

A fused Pallas kernel (ops/pallas/histogram_kernel.py) implements the same
contract without materializing the one-hot in HBM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pallas import histogram_kernel as _pallas_hist


def _hist_chunk(binned_chunk: jax.Array, gh_chunk: jax.Array, num_bins: int) -> jax.Array:
    """One-hot contraction for one chunk.

    binned_chunk: (C, F) int8/int16 bin codes
    gh_chunk:     (C, 3) f32 (grad, hess, valid-count)
    returns       (F, B, 3) f32 partial histogram
    """
    c, f = binned_chunk.shape
    iota = jnp.arange(num_bins, dtype=jnp.int32)
    onehot = (binned_chunk.astype(jnp.int32)[:, :, None] == iota[None, None, :])
    # (FB, C) @ (C, 3) on the MXU. The one-hot is bf16-exact; gh is split
    # into bf16 hi + lo parts so each product is a fast single-pass bf16
    # matmul while the sum keeps ~f32 fidelity (rel err ~8e-7 vs HIGHEST,
    # tools/microbench_hist2.py). Plain DEFAULT would round gradients to
    # bf16, whose absolute error survives sibling subtraction
    # (subtract_histogram) disproportionately for small leaves; HIGHEST
    # costs ~40% more MXU time.
    onehot2d = onehot.reshape(c, f * num_bins).astype(jnp.bfloat16)
    gh_hi = gh_chunk.astype(jnp.bfloat16)
    gh_lo = (gh_chunk - gh_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((0,), (0,)), ((), ()))
    hist = (jax.lax.dot_general(onehot2d, gh_hi, dimension_numbers=dn,
                                preferred_element_type=jnp.float32)
            + jax.lax.dot_general(onehot2d, gh_lo, dimension_numbers=dn,
                                  preferred_element_type=jnp.float32))
    return hist.reshape(f, num_bins, 3)


@functools.partial(jax.jit, static_argnames=("num_bins", "chunk_size", "use_pallas"))
def build_histogram(binned_rows: jax.Array, gh: jax.Array, num_bins: int,
                    chunk_size: int = 2048, use_pallas: bool = False) -> jax.Array:
    """Full histogram for a padded row window.

    binned_rows: (P, F) gathered bin codes for the leaf's rows (pad rows
                 arbitrary — their gh must be zero).
    gh:          (P, 3) f32 (grad, hess, valid) — valid is 0.0 on pad rows.
    Returns (F, B, 3) f32: per (feature, bin): [sum_grad, sum_hess, count].
    """
    if use_pallas:
        return _pallas_hist.build_histogram_pallas(binned_rows, gh, num_bins)
    p, f = binned_rows.shape
    if p <= chunk_size:
        return _hist_chunk(binned_rows, gh, num_bins)
    n_chunks = (p + chunk_size - 1) // chunk_size
    pad = n_chunks * chunk_size - p
    if pad:
        binned_rows = jnp.pad(binned_rows, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    binned_rows = binned_rows.reshape(n_chunks, chunk_size, f)
    gh = gh.reshape(n_chunks, chunk_size, 3)

    def body(acc, chunk):
        b, g = chunk
        return acc + _hist_chunk(b, g, num_bins), None

    # the carry is seeded from the FIRST chunk (not zeros) so its type
    # carries the data's varying-manual-axes when this runs inside a
    # shard_map region (a replicated zeros carry + varying per-chunk
    # additions fails shard_map's carry type check); outside shard_map
    # it is the same arithmetic with one add saved
    init = _hist_chunk(binned_rows[0], gh[0], num_bins)
    hist, _ = jax.lax.scan(body, init, (binned_rows[1:], gh[1:]))
    return hist


@jax.jit
def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram by subtraction (reference:
    src/treelearner/feature_histogram.hpp:75-81 FeatureHistogram::Subtract)."""
    return parent - child


@functools.partial(jax.jit, static_argnames=("num_bins", "bucket"))
def gather_and_build(binned: jax.Array, indices_buf: jax.Array, grad: jax.Array,
                     hess: jax.Array, begin: jax.Array, count: jax.Array,
                     num_bins: int, bucket: int) -> jax.Array:
    """Gather a leaf's rows from the partition buffer and build its histogram.

    binned:      (N, F) full binned matrix
    indices_buf: (N + max_bucket,) int32 partition permutation (padded tail)
    begin/count: scalars (leaf slice in the partition buffer)
    bucket:      static padded window size >= count
    """
    window = jax.lax.dynamic_slice(indices_buf, (begin,), (bucket,))
    valid = (jnp.arange(bucket, dtype=jnp.int32) < count)
    rows = jnp.take(binned, window, axis=0)
    g = jnp.take(grad, window) * valid
    h = jnp.take(hess, window) * valid
    gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
    return build_histogram(rows, gh, num_bins)
