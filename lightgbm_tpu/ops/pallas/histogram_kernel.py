"""Pallas TPU histogram kernel.

TPU-native replacement for the reference's OpenCL histogram kernels
(reference: src/treelearner/ocl/histogram256.cl — per-workgroup local-memory
float atomics). TPUs have no scatter-atomics; instead each grid step builds a
one-hot matrix for a (row-chunk x feature-tile) block in VMEM and contracts it
with (grad, hess, count) on the MXU, accumulating into the output block that
stays resident in VMEM across the row-chunk grid axis.

Layout notes:
  * gh comes in transposed (3, P) so the matmul is (3, C) @ (C, Ft*B) —
    full 128-lane utilization on the output's last axis.
  * output is (3, F, B); the public wrapper transposes to the framework's
    (F, B, 3) contract (tiny array, negligible).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _hist_kernel(codes_ref, gh_ref, out_ref, *, num_bins: int):
    p_idx = pl.program_id(1)
    codes = codes_ref[...].astype(jnp.int32)          # (C, Ft)
    c, ft = codes.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (c, ft, num_bins), 2)
    onehot = (codes[:, :, None] == iota).astype(jnp.float32)
    oh2 = onehot.reshape(c, ft * num_bins)
    gh = gh_ref[...]                                   # (3, C) f32
    acc = jax.lax.dot_general(
        gh, oh2, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                  # (3, Ft*B)
    acc3 = acc.reshape(3, ft, num_bins)

    @pl.when(p_idx == 0)
    def _init():
        out_ref[...] = acc3

    @pl.when(p_idx > 0)
    def _acc():
        out_ref[...] += acc3


@functools.partial(jax.jit, static_argnames=("num_bins", "chunk_rows", "feat_tile"))
def build_histogram_pallas(binned_rows: jax.Array, gh: jax.Array, num_bins: int,
                           chunk_rows: int = 512, feat_tile: int = 8) -> jax.Array:
    """(P, F) codes + (P, 3) gh -> (F, B, 3) f32 histogram."""
    p, f = binned_rows.shape
    # pad rows to chunk multiple (pad gh rows are zero so they add nothing)
    pad_p = (-p) % chunk_rows
    pad_f = (-f) % feat_tile
    if pad_p or pad_f:
        binned_rows = jnp.pad(binned_rows, ((0, pad_p), (0, pad_f)))
    if pad_p:
        gh = jnp.pad(gh, ((0, pad_p), (0, 0)))
    pp, ff = p + pad_p, f + pad_f
    gh_t = gh.T                                        # (3, P)

    grid = (ff // feat_tile, pp // chunk_rows)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=num_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk_rows, feat_tile), lambda fi, pi: (pi, fi)),
            pl.BlockSpec((3, chunk_rows), lambda fi, pi: (0, pi)),
        ],
        out_specs=pl.BlockSpec((3, feat_tile, num_bins), lambda fi, pi: (0, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((3, ff, num_bins), jnp.float32),
    )(binned_rows, gh_t)
    hist = jnp.transpose(out, (1, 2, 0))               # (F, B, 3)
    if pad_f:
        hist = hist[:f]
    return hist
