"""Pallas TPU histogram kernel.

TPU-native replacement for the reference's OpenCL histogram kernels
(reference: src/treelearner/ocl/histogram256.cl — per-workgroup local-memory
float atomics). TPUs have no scatter-atomics; instead each grid step builds
one-hot tiles in VMEM and contracts them with (grad, hess, count) on the MXU,
accumulating into an output block that stays resident in VMEM across the
row-chunk grid axis. The one-hot never touches HBM — that is the entire
point versus the plain-XLA formulation in ops/histogram.py, whose cost is
dominated by streaming the materialized (N, F*B) one-hot through HBM.

Numerics: the one-hot is bf16-exact (0/1); gh is split into bf16 hi + lo
parts, packed side by side into ONE (C, 6) operand so a single bf16 MXU
pass covers both halves (hi+lo recombined in f32 outside the kernel,
rel err ~8e-7 — the same split-precision scheme as ops/histogram.py).
A full-f32 HIGHEST-precision matmul costs ~6 bf16 passes and measured
~3x slower end to end (tools/microbench_injit.py, round-2 kernel).

Mosaic tiling rules require the last two dims of every block to be
(8k, 128k) or span the whole array, so the codes come in TRANSPOSED (F, P)
layout: the feature axis rides sublanes (tile 8) and the row axis rides
lanes (tile 128). Layouts:

    codes (F, P) int8   -> block (8, C)
    gh6   (P, 6) f32    -> block (C, 6)      (6 spans the array: allowed)
    out   (F, B, 6) f32 -> block (8, B, 6), index ignores the row-chunk
                           grid dim, so Pallas keeps it in VMEM and we
                           accumulate across chunks.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

FEAT_TILE = 8


def _hist_kernel(codes_ref, gh6_ref, out_ref, *, num_bins: int):
    p_idx = pl.program_id(1)

    @pl.when(p_idx == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh6 = gh6_ref[...].astype(jnp.bfloat16)            # (C, 6)
    codes = codes_ref[...].astype(jnp.int32)           # (Ft, C)
    ft, c = codes.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (ft, num_bins, c), 1)
    onehot = (codes[:, None, :] == iota).astype(jnp.bfloat16)  # (Ft, B, C)
    part = jax.lax.dot_general(
        onehot.reshape(ft * num_bins, c), gh6,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                  # (Ft*B, 6)
    out_ref[...] += part.reshape(ft, num_bins, 6)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_rows", "interpret"))
def build_histogram_pallas(binned_rows: jax.Array, gh: jax.Array, num_bins: int,
                           chunk_rows: int = 2048,
                           interpret: bool = False) -> jax.Array:
    """(P, F) codes + (P, 3) gh -> (F, B, 3) f32 histogram."""
    return build_histogram_pallas_t(binned_rows.T, gh, num_bins,
                                    chunk_rows=chunk_rows, interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_rows", "interpret"))
def build_histogram_pallas_t(codes_t: jax.Array, gh: jax.Array, num_bins: int,
                             chunk_rows: int = 2048,
                             interpret: bool = False) -> jax.Array:
    """(F, P) transposed codes + (P, 3) gh -> (F, B, 3) f32 histogram.

    The layout the device tree learner stores natively (column-major codes),
    so no transpose sits on the hot path. Pad rows carry gh == 0 so padding
    never contributes mass.
    """
    f, p = codes_t.shape
    pad_p = (-p) % chunk_rows
    pad_f = (-f) % FEAT_TILE
    if pad_p or pad_f:
        codes_t = jnp.pad(codes_t, ((0, pad_f), (0, pad_p)))
    if pad_p:
        gh = jnp.pad(gh, ((0, pad_p), (0, 0)))
    pp, ff = p + pad_p, f + pad_f

    # split-precision operand: [bf16-hi | residual-lo], one MXU pass
    gh_hi = gh.astype(jnp.bfloat16).astype(jnp.float32)
    gh6 = jnp.concatenate([gh_hi, gh - gh_hi], axis=1)           # (P, 6)

    grid = (ff // FEAT_TILE, pp // chunk_rows)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=num_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FEAT_TILE, chunk_rows), lambda fi, pi: (fi, pi)),
            pl.BlockSpec((chunk_rows, 6), lambda fi, pi: (pi, 0)),
        ],
        out_specs=pl.BlockSpec((FEAT_TILE, num_bins, 6),
                               lambda fi, pi: (fi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((ff, num_bins, 6), jnp.float32),
        interpret=interpret,
    )(codes_t, gh6)
    out = out[:, :, :3] + out[:, :, 3:]                          # hi + lo
    if pad_f:
        out = out[:f]
    return out


def _hist_kernel_q(codes_ref, ghq_ref, out_ref, *, num_bins: int,
                   op_bits: int):
    """Integer variant of _hist_kernel: ONE i8 (or i32) matmul per tile
    accumulating EXACT int32 per-bin sums — no hi/lo split operand, no
    recombination pass, and a (C, 4) operand instead of (C, 6)."""
    p_idx = pl.program_id(1)

    @pl.when(p_idx == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    op_dtype = jnp.int8 if op_bits <= 8 else jnp.int32
    ghq = ghq_ref[...].astype(op_dtype)                # (C, 4)
    codes = codes_ref[...].astype(jnp.int32)           # (Ft, C)
    ft, c = codes.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (ft, num_bins, c), 1)
    onehot = (codes[:, None, :] == iota).astype(op_dtype)  # (Ft, B, C)
    part = jax.lax.dot_general(
        onehot.reshape(ft * num_bins, c), ghq,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                                  # (Ft*B, 4)
    out_ref[...] += part.reshape(ft, num_bins, 4)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_rows", "interpret"))
def build_histogram_pallas_quantized(binned_rows: jax.Array, ghq: jax.Array,
                                     num_bins: int, chunk_rows: int = 2048,
                                     interpret: bool = False) -> jax.Array:
    """(P, F) codes + (P, 3) int [qg, qh, valid] -> (F, B, 3) int32."""
    return build_histogram_pallas_quantized_t(
        binned_rows.T, ghq, num_bins, chunk_rows=chunk_rows,
        interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_rows", "interpret"))
def build_histogram_pallas_quantized_t(codes_t: jax.Array, ghq: jax.Array,
                                       num_bins: int, chunk_rows: int = 2048,
                                       interpret: bool = False) -> jax.Array:
    """(F, P) transposed codes + (P, 3) int [qg, qh, valid] ->
    (F, B, 3) int32 exact histogram.

    Same tiling contract as build_histogram_pallas_t; the operand rides
    as int32 blocks (Mosaic's narrow-int tiling is stricter) and is cast
    to int8 inside the kernel when the quantization fits, so the MXU
    still sees the native i8 contraction. Pad rows must carry ghq == 0.
    """
    f, p = codes_t.shape
    op_bits = 8 if ghq.dtype == jnp.int8 else 32
    pad_p = (-p) % chunk_rows
    pad_f = (-f) % FEAT_TILE
    if pad_p or pad_f:
        codes_t = jnp.pad(codes_t, ((0, pad_f), (0, pad_p)))
    ghq4 = jnp.pad(ghq.astype(jnp.int32), ((0, pad_p), (0, 1)))  # (P, 4)
    pp, ff = p + pad_p, f + pad_f

    grid = (ff // FEAT_TILE, pp // chunk_rows)
    out = pl.pallas_call(
        functools.partial(_hist_kernel_q, num_bins=num_bins,
                         op_bits=op_bits),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FEAT_TILE, chunk_rows), lambda fi, pi: (fi, pi)),
            pl.BlockSpec((chunk_rows, 4), lambda fi, pi: (pi, 0)),
        ],
        out_specs=pl.BlockSpec((FEAT_TILE, num_bins, 4),
                               lambda fi, pi: (fi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((ff, num_bins, 4), jnp.int32),
        interpret=interpret,
    )(codes_t, ghq4)
    out = out[:, :, :3]
    if pad_f:
        out = out[:f]
    return out
