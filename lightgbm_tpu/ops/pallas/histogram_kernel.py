"""Pallas TPU histogram kernel.

TPU-native replacement for the reference's OpenCL histogram kernels
(reference: src/treelearner/ocl/histogram256.cl — per-workgroup local-memory
float atomics). TPUs have no scatter-atomics; instead each grid step builds
one-hot tiles in VMEM and contracts them with (grad, hess, count) on the MXU,
accumulating into an output block that stays resident in VMEM across the
row-chunk grid axis. The one-hot never touches HBM — that is the entire
point versus the plain-XLA formulation in ops/histogram.py.

Mosaic tiling rules require the last two dims of every block to be
(8k, 128k) or span the whole array, so the codes come in TRANSPOSED (F, P)
layout: the feature axis rides sublanes (tile 8) and the row axis rides
lanes (tile 128). Layouts:

    codes (F, P) int8  -> block (8, C)
    gh    (P, 3) f32   -> block (C, 3)      (3 spans the array: allowed)
    out   (F, B, 3) f32-> block (8, B, 3), index ignores the row-chunk grid
                          dim, so Pallas keeps it in VMEM and we accumulate.

Per feature in the tile: onehot (B, C) = (codes_row == iota) and a skinny
MXU matmul (B, C) @ (C, 3). The N=3 axis underuses lanes, but MXU time only
scales with M and K, so the pass is effectively free at B <= 128.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

FEAT_TILE = 8


def _hist_kernel(codes_ref, gh_ref, out_ref, *, num_bins: int):
    p_idx = pl.program_id(1)

    @pl.when(p_idx == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]                                   # (C, 3) f32
    codes = codes_ref[...].astype(jnp.int32)           # (Ft, C)
    ft, c = codes.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (ft, num_bins, c), 1)
    onehot = (codes[:, None, :] == iota).astype(jnp.float32)  # (Ft, B, C)
    part = jax.lax.dot_general(
        onehot.reshape(ft * num_bins, c), gh,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                  # (Ft*B, 3)
    out_ref[...] += part.reshape(ft, num_bins, 3)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_rows", "interpret"))
def build_histogram_pallas(binned_rows: jax.Array, gh: jax.Array, num_bins: int,
                           chunk_rows: int = 1024,
                           interpret: bool = False) -> jax.Array:
    """(P, F) codes + (P, 3) gh -> (F, B, 3) f32 histogram."""
    return build_histogram_pallas_t(binned_rows.T, gh, num_bins,
                                    chunk_rows=chunk_rows, interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk_rows", "interpret"))
def build_histogram_pallas_t(codes_t: jax.Array, gh: jax.Array, num_bins: int,
                             chunk_rows: int = 1024,
                             interpret: bool = False) -> jax.Array:
    """(F, P) transposed codes + (P, 3) gh -> (F, B, 3) f32 histogram.

    The layout the device tree learner stores natively (column-major codes),
    so no transpose sits on the hot path. Pad rows carry gh == 0 so padding
    never contributes mass.
    """
    f, p = codes_t.shape
    pad_p = (-p) % chunk_rows
    pad_f = (-f) % FEAT_TILE
    if pad_p or pad_f:
        codes_t = jnp.pad(codes_t, ((0, pad_f), (0, pad_p)))
    if pad_p:
        gh = jnp.pad(gh, ((0, pad_p), (0, 0)))
    pp, ff = p + pad_p, f + pad_f

    grid = (ff // FEAT_TILE, pp // chunk_rows)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=num_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FEAT_TILE, chunk_rows), lambda fi, pi: (fi, pi)),
            pl.BlockSpec((chunk_rows, 3), lambda fi, pi: (pi, 0)),
        ],
        out_specs=pl.BlockSpec((FEAT_TILE, num_bins, 3),
                               lambda fi, pi: (fi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((ff, num_bins, 3), jnp.float32),
        interpret=interpret,
    )(codes_t, gh)
    if pad_f:
        out = out[:f]
    return out
