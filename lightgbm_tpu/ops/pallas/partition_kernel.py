"""Pallas TPU stable 3-way partition of packed row windows.

The device tree learner's partition step (reference DataPartition::Split,
src/treelearner/data_partition.hpp:20-205) must reorder a (W, D)-u32
packed row window into [key==0 | key==1 | key==2] with stable order.
The XLA formulation — `argsort(key, stable)` + `take(rows)` — is
latency-bound: on v5e a random row gather runs at 3-10 GB/s (~5-9
ns/row, tools/microbench_gather.py) against ~800 GB/s HBM, and the
argsort adds ~4.6 ns/row. This kernel replaces both with a
block-streaming pass whose row movement rides the MXU and DMA engines:

  * grid over (row-block, stream): each (BK, D) block is loaded once and
    revisited for the three streams (the block index map ignores the
    stream axis, so Pallas skips the reload).
  * within a block, stream s's rows compact via a one-hot permutation
    matmul: P[i, j] = (rank_s[j] == i) & (key[j] == s), applied to the
    rows split into bf16 BYTE planes. Every output element is a single
    0/1 x byte product (no accumulation), and integers 0..255 are exact
    in bf16, so the permutation is bit-exact; bytes reassemble into u32
    with wrap-safe int32 shifts.
  * the compacted segment DMA-writes at the stream's running offset in a
    PER-STREAM output buffer. Writes are full BK-row blocks; the garbage
    tail past the segment's count lands exactly where the SAME stream's
    next block writes, and TPU grids execute sequentially with each
    step waiting on its copy, so every garbage row is overwritten before
    the kernel ends (the final tail lands in the +BK slack row pad).
  * the three per-stream buffers assemble into the final window with two
    doubled-buffer dynamic slices + selects in XLA — streaming passes at
    HBM bandwidth (dynamic jnp.roll both miscompiles under this jax
    version's lowering cache and is not needed).

Cost: one block load (x3 revisits), one one-hot build + matmul, and one
block store per (block, stream) — ~2-4 ns/row/pass vs ~14 ns for
argsort+take, and linear in W where argsort is O(W log W).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 1024


def _partition_kernel(starts_ref, win_ref, key_ref,
                      out0, out1, out2, scratch, sem, *, block_rows: int):
    s = pl.program_id(1)
    key = key_ref[...]                                   # (BK, 1) int32
    flag = (key == s).astype(jnp.int32)                  # (BK, 1)
    bk = block_rows
    iota_i = jax.lax.broadcasted_iota(jnp.int32, (bk, bk), 0)
    iota_j = jax.lax.broadcasted_iota(jnp.int32, (bk, bk), 1)
    # exclusive rank via strict-lower-triangular matvec: rank[i] =
    # sum_{j<i} flag[j]. Mosaic has no cumsum lowering for TC kernels
    # (the jnp.cumsum formulation fails to lower on real chips); the
    # 0/1 x 0/1 products are exact and accumulate in f32 (exact to
    # 2^24), and the MXU does the whole (BK, BK) matvec in one pass.
    tril = (iota_j < iota_i).astype(jnp.bfloat16)
    rank = jax.lax.dot_general(
        tril, flag.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.int32)
    # P[i, j] = 1 iff block row j is stream s's i-th row
    p = ((rank[:, 0][None, :] == iota_i)
         & (flag[:, 0][None, :] == 1)).astype(jnp.bfloat16)

    win = win_ref[...]                                   # (BK, D) uint32
    w32 = win.astype(jnp.int32)
    planes = [((w32 >> shift) & 0xFF).astype(jnp.bfloat16)
              for shift in (0, 8, 16, 24)]
    bytes_b = jnp.concatenate(planes, axis=1)            # (BK, 4D)
    seg = jax.lax.dot_general(
        p, bytes_b, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (BK, 4D) exact
    d = win.shape[1]
    si = seg.astype(jnp.int32)
    re = (si[:, 0:d] | (si[:, d:2 * d] << 8) | (si[:, 2 * d:3 * d] << 16)
          | (si[:, 3 * d:4 * d] << 24))
    scratch[...] = jax.lax.bitcast_convert_type(re, jnp.uint32)

    b = pl.program_id(0)
    start = starts_ref[s, b]

    @pl.when(s == 0)
    def _w0():
        cp = pltpu.make_async_copy(scratch, out0.at[pl.ds(start, bk)], sem)
        cp.start()
        cp.wait()

    @pl.when(s == 1)
    def _w1():
        cp = pltpu.make_async_copy(scratch, out1.at[pl.ds(start, bk)], sem)
        cp.start()
        cp.wait()

    @pl.when(s == 2)
    def _w2():
        cp = pltpu.make_async_copy(scratch, out2.at[pl.ds(start, bk)], sem)
        cp.start()
        cp.wait()


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def stable_partition3(win: jax.Array, key3: jax.Array,
                      block_rows: int = DEFAULT_BLOCK,
                      interpret: bool = False) -> jax.Array:
    """Stably reorder `win` (W, D) uint32 so rows sort by key3 in {0,1,2}.

    Exact drop-in for jnp.take(win, argsort(key3, stable), axis=0).
    """
    w, d = win.shape
    bk = block_rows
    pad = (-w) % bk
    if pad:
        win = jnp.pad(win, ((0, pad), (0, 0)))
        key3 = jnp.pad(key3, (0, pad), constant_values=2)
    wp = w + pad
    nb = wp // bk

    keys2d = key3.astype(jnp.int32).reshape(wp, 1)
    ind = (keys2d[:, 0].reshape(nb, bk)[None, :, :]
           == jnp.arange(3, dtype=jnp.int32)[:, None, None])
    counts = jnp.sum(ind.astype(jnp.int32), axis=2)      # (3, nb)
    starts = jnp.cumsum(counts, axis=1) - counts         # excl. per stream
    totals = jnp.sum(counts, axis=1)                     # (3,)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, 3),
        in_specs=[
            pl.BlockSpec((bk, d), lambda b, s, starts: (b, 0)),
            pl.BlockSpec((bk, 1), lambda b, s, starts: (b, 0)),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.uint32),
                        pltpu.SemaphoreType.DMA],
    )
    shp = jax.ShapeDtypeStruct((wp + bk, d), jnp.uint32)
    o0, o1, o2 = pl.pallas_call(
        functools.partial(_partition_kernel, block_rows=bk),
        grid_spec=grid_spec,
        out_shape=[shp, shp, shp],
        interpret=interpret,
    )(starts, win, keys2d)

    c0, c1 = totals[0], totals[1]
    rows = jnp.arange(wp + bk, dtype=jnp.int32)
    # Rotate by a traced offset WITHOUT jnp.roll (a traced shift hits a
    # _roll_dynamic lowering-cache KeyError when two same-shape dynamic
    # rolls lower in one module — the actual crash site in the round-5
    # battery) and WITHOUT a modulo gather (random row gathers run at
    # 3-10 GB/s vs ~800 GB/s HBM — the very cost this kernel avoids):
    # dynamic_slice into a doubled buffer keeps the copy contiguous.
    m = wp + bk

    def rotate(o, shift):
        return jax.lax.dynamic_slice(
            jnp.concatenate([o, o], axis=0),
            ((m - shift) % m, 0), (m, d))

    o1r = rotate(o1, c0)
    o2r = rotate(o2, c0 + c1)
    out = jnp.where((rows < c0)[:, None], o0,
                    jnp.where((rows < c0 + c1)[:, None], o1r, o2r))
    return out[:w]
