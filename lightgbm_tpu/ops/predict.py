"""Vectorized tree traversal on device.

Equivalent of the reference's per-row traversal loops (reference:
src/io/tree.cpp:115-207 AddPredictionToScore, tree.h:221-293 Decision) recast
as fixed-trip-count gather iterations: all N rows advance one tree level per
step; finished rows (negative node = leaf) freeze. No data-dependent control
flow, so the whole ensemble scoring jits cleanly.

Trees are tensorized into padded arrays. Two threshold spaces exist like the
reference: bin thresholds for training-time scoring of binned datasets
(DecisionInner) and real-valued thresholds for raw-feature prediction
(Decision).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2
K_ZERO_THRESHOLD = 1e-35


class EnsembleArrays(NamedTuple):
    """Padded (T, max_nodes)/(T, max_leaves) ensemble tensors."""
    split_feature: jax.Array    # (T, M) int32
    threshold: jax.Array        # (T, M) f64-as-f32 real thresholds
    threshold_bin: jax.Array    # (T, M) int32 bin thresholds
    decision_type: jax.Array    # (T, M) int32
    left_child: jax.Array       # (T, M) int32
    right_child: jax.Array      # (T, M) int32
    leaf_value: jax.Array       # (T, L) f32
    cat_boundaries: jax.Array   # (T, C+1) int32
    cat_threshold: jax.Array    # (T, W) int32 (uint32 bitset words)
    cat_boundaries_inner: jax.Array
    cat_threshold_inner: jax.Array
    max_depth: int


def _bucket_up(v: int) -> int:
    """Next power of two: shape-bucketing so growing ensembles reuse the
    same compiled program instead of recompiling per tree count."""
    out = 1
    while out < v:
        out *= 2
    return out


def trees_to_arrays(trees: Sequence, dtype=jnp.float32,
                    bucket: bool = False) -> EnsembleArrays:
    """Tensorize trees into padded ensemble arrays.

    bucket=True additionally pads every shape axis (tree count, nodes,
    leaves, categorical widths) up to the next power of two. Padding
    trees are single-leaf with value 0, so summed predictions are
    unchanged — but a predict called every few iterations of a growing
    booster then compiles O(log T) programs instead of O(T) (round 3
    observed a mid-training predict recompiling through the TPU tunnel
    for >10 min). Do NOT bucket when the OUTPUT shape depends on the
    tree axis (leaf-index prediction)."""
    t_real = len(trees)
    t_count = _bucket_up(t_real) if bucket else t_real
    bk = _bucket_up if bucket else (lambda v: v)
    max_nodes = bk(max(max(t.num_leaves - 1, 1) for t in trees))
    max_leaves = bk(max(t.num_leaves for t in trees))
    max_cats = bk(max(max(t.num_cat, 0) for t in trees))
    max_words = bk(max(max(len(t.cat_threshold), 1) for t in trees))
    max_words_in = bk(max(max(len(t.cat_threshold_inner), 1) for t in trees))

    def pad2(get, shape, dt):
        out = np.zeros((t_count,) + shape, dtype=dt)
        for i, tr in enumerate(trees):
            v = get(tr)
            out[i, : len(v)] = v
        return out

    sf = pad2(lambda t: t.split_feature[: max(t.num_leaves - 1, 0)], (max_nodes,), np.int32)
    th = pad2(lambda t: t.threshold[: max(t.num_leaves - 1, 0)], (max_nodes,), np.float64)
    tb = pad2(lambda t: t.threshold_in_bin[: max(t.num_leaves - 1, 0)], (max_nodes,), np.int32)
    dt_ = pad2(lambda t: t.decision_type[: max(t.num_leaves - 1, 0)], (max_nodes,), np.int32)
    lc = pad2(lambda t: t.left_child[: max(t.num_leaves - 1, 0)], (max_nodes,), np.int32)
    rc = pad2(lambda t: t.right_child[: max(t.num_leaves - 1, 0)], (max_nodes,), np.int32)
    lv = pad2(lambda t: t.leaf_value[: t.num_leaves], (max_leaves,), np.float64)
    cb = pad2(lambda t: np.asarray(t.cat_boundaries, dtype=np.int64), (max_cats + 2,), np.int32)
    ct = pad2(lambda t: np.asarray(t.cat_threshold, dtype=np.int64), (max_words,), np.int64)
    cbi = pad2(lambda t: np.asarray(t.cat_boundaries_inner, dtype=np.int64), (max_cats + 2,), np.int32)
    cti = pad2(lambda t: np.asarray(t.cat_threshold_inner, dtype=np.int64), (max_words_in,), np.int64)
    # single-leaf trees: make node 0 route to leaf 0 both sides
    for i, tr in enumerate(trees):
        if tr.num_leaves == 1:
            lc[i, 0] = -1
            rc[i, 0] = -1
    # bucket-padding trees are single-leaf with value 0 (no-ops)
    for i in range(t_real, t_count):
        lc[i, 0] = -1
        rc[i, 0] = -1
    max_depth = max(t.depth() for t in trees)
    max_depth = max(1, int(np.ceil(max(1, max_depth) / 8)) * 8)
    return EnsembleArrays(
        jnp.asarray(sf), jnp.asarray(th.astype(np.float32)), jnp.asarray(tb),
        jnp.asarray(dt_), jnp.asarray(lc), jnp.asarray(rc),
        jnp.asarray(lv.astype(np.float64).astype(dtype)),
        jnp.asarray(cb), jnp.asarray(ct & 0xFFFFFFFF, dtype=jnp.uint32).astype(jnp.int32),
        jnp.asarray(cbi), jnp.asarray(cti & 0xFFFFFFFF, dtype=jnp.uint32).astype(jnp.int32),
        max_depth,
    )


def padded_tree_class(arrays: EnsembleArrays, classes) -> jax.Array:
    """(T_pad,) tree->class map for predict_raw_ensemble: real trees take
    `classes`, bucket-padding trees map to class 0 (their leaf value is 0,
    so they add nothing). Lives next to the bucketing so every caller of
    trees_to_arrays(bucket=True) shares one padding invariant."""
    t_pad = arrays.split_feature.shape[0]
    tc = np.zeros(t_pad, dtype=np.int32)
    classes = np.asarray(classes, dtype=np.int32)
    tc[:len(classes)] = classes
    return jnp.asarray(tc)


def _traverse_one_tree_binned(binned, feat_missing, feat_default, feat_numbins,
                              sf, tb, dtp, lc, rc, cbi, cti, max_depth):
    """All rows walk one tree over binned codes (DecisionInner semantics)."""
    n = binned.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def body(_, node):
        live = node >= 0
        node_c = jnp.maximum(node, 0)
        f = sf[node_c]
        fbin = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0].astype(jnp.int32)
        thr = tb[node_c]
        dt = dtp[node_c]
        is_cat = (dt & 1) > 0
        default_left = (dt & 2) > 0
        mt = (dt >> 2) & 3
        mtype_f = feat_missing[f]
        numbin_f = feat_numbins[f]
        default_f = feat_default[f]
        is_missing = jnp.where(
            mt == MISSING_ZERO, fbin == default_f,
            jnp.where(mt == MISSING_NAN, fbin == numbin_f - 1, False))
        num_left = jnp.where(is_missing, default_left, fbin <= thr)
        # categorical: bitset membership on inner bins
        cat_idx = thr
        lo = cbi[jnp.clip(cat_idx, 0, cbi.shape[0] - 1)]
        hi = cbi[jnp.clip(cat_idx + 1, 0, cbi.shape[0] - 1)]
        word_idx = lo + fbin // 32
        in_range = (fbin // 32) < (hi - lo)
        word = cti[jnp.clip(word_idx, 0, cti.shape[0] - 1)]
        cat_left = in_range & (((word >> (fbin % 32)) & 1) == 1)
        go_left = jnp.where(is_cat, cat_left, num_left)
        nxt = jnp.where(go_left, lc[node_c], rc[node_c])
        return jnp.where(live, nxt, node)

    node = jax.lax.fori_loop(0, max_depth, body, node)
    return ~node  # leaf indices (rows stuck at depth cap return garbage only
                  # if max_depth < true depth, which trees_to_arrays prevents)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_binned_leaf(binned, feat_missing, feat_default, feat_numbins,
                        sf, tb, dtp, lc, rc, cbi, cti, *, max_depth):
    return _traverse_one_tree_binned(binned, feat_missing, feat_default,
                                     feat_numbins, sf, tb, dtp, lc, rc,
                                     cbi, cti, max_depth)


def predict_binned_tree_values(binned, feat_missing, feat_default,
                               feat_numbins, tree, dtype=jnp.float32):
    """Per-row leaf values of a single (host) Tree over binned data.

    bucket=True: this runs once per ITERATION per valid set during
    training (ScoreUpdater.add_tree), and without bucketing every
    distinct (num_leaves, cat-width) pair retraces predict_binned_leaf
    — a remote compile each through the tunneled TPU. Bucketing
    collapses the shapes to O(log L) programs; the output indexes tree
    0 only, so padding trees never contribute."""
    arr = trees_to_arrays([tree], dtype=dtype, bucket=True)
    leaves = predict_binned_leaf(
        binned, feat_missing, feat_default, feat_numbins,
        arr.split_feature[0], arr.threshold_bin[0], arr.decision_type[0],
        arr.left_child[0], arr.right_child[0],
        arr.cat_boundaries_inner[0], arr.cat_threshold_inner[0],
        max_depth=arr.max_depth)
    return arr.leaf_value[0][leaves]


def _traverse_one_tree_raw(x, sf, th, dtp, lc, rc, cb, ct, max_depth):
    """All rows walk one tree over raw feature values (Decision semantics)."""
    n = x.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def body(_, node):
        live = node >= 0
        node_c = jnp.maximum(node, 0)
        f = sf[node_c]
        fval = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
        thr = th[node_c]
        dt = dtp[node_c]
        is_cat = (dt & 1) > 0
        default_left = (dt & 2) > 0
        mt = (dt >> 2) & 3
        is_nan = jnp.isnan(fval)
        fval_n = jnp.where(is_nan & (mt != MISSING_NAN), 0.0, fval)
        is_missing = jnp.where(
            mt == MISSING_ZERO, jnp.abs(fval_n) <= K_ZERO_THRESHOLD,
            jnp.where(mt == MISSING_NAN, jnp.isnan(fval_n), False))
        num_left = jnp.where(is_missing, default_left, fval_n <= thr)
        # categorical on raw int values
        ival = jnp.where(is_nan, -1, fval).astype(jnp.int32)
        cat_idx = thr.astype(jnp.int32)
        lo = cb[jnp.clip(cat_idx, 0, cb.shape[0] - 1)]
        hi = cb[jnp.clip(cat_idx + 1, 0, cb.shape[0] - 1)]
        word_idx = lo + ival // 32
        in_range = (ival >= 0) & ((ival // 32) < (hi - lo))
        word = ct[jnp.clip(word_idx, 0, ct.shape[0] - 1)]
        cat_left = in_range & (((word >> (ival % 32)) & 1) == 1)
        go_left = jnp.where(is_cat, cat_left, num_left)
        nxt = jnp.where(go_left, lc[node_c], rc[node_c])
        return jnp.where(live, nxt, node)

    node = jax.lax.fori_loop(0, max_depth, body, node)
    return ~node


@functools.partial(jax.jit, static_argnames=("max_depth", "num_class"))
def predict_raw_ensemble(x: jax.Array, arrays: EnsembleArrays,
                         tree_class: jax.Array, *, max_depth: int,
                         num_class: int) -> jax.Array:
    """Raw scores (N, num_class): sum of per-class tree outputs."""
    n = x.shape[0]

    def per_tree(carry, tree_idx):
        scores = carry
        leaves = _traverse_one_tree_raw(
            x, arrays.split_feature[tree_idx], arrays.threshold[tree_idx],
            arrays.decision_type[tree_idx], arrays.left_child[tree_idx],
            arrays.right_child[tree_idx], arrays.cat_boundaries[tree_idx],
            arrays.cat_threshold[tree_idx], max_depth)
        vals = arrays.leaf_value[tree_idx][leaves]
        k = tree_class[tree_idx]
        scores = scores.at[:, k].add(vals)
        return scores, None

    init = jnp.zeros((n, num_class), dtype=jnp.float32)
    t_count = arrays.split_feature.shape[0]
    scores, _ = jax.lax.scan(per_tree, init, jnp.arange(t_count))
    return scores


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_leaf_index_ensemble(x: jax.Array, arrays: EnsembleArrays,
                                *, max_depth: int) -> jax.Array:
    """(N, T) leaf index per tree (pred_leaf=True)."""
    def per_tree(_, tree_idx):
        leaves = _traverse_one_tree_raw(
            x, arrays.split_feature[tree_idx], arrays.threshold[tree_idx],
            arrays.decision_type[tree_idx], arrays.left_child[tree_idx],
            arrays.right_child[tree_idx], arrays.cat_boundaries[tree_idx],
            arrays.cat_threshold[tree_idx], max_depth)
        return None, leaves

    t_count = arrays.split_feature.shape[0]
    _, leaves = jax.lax.scan(per_tree, None, jnp.arange(t_count))
    return leaves.T
