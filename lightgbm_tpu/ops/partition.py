"""Device data partition: leaf -> row-index ranges.

Equivalent of the reference DataPartition (reference:
src/treelearner/data_partition.hpp:20-205): a permutation buffer grouped by
leaf plus per-leaf (begin, count). The reference re-partitions one leaf's
slice with per-thread buffers; here it is a stable sort by a 2-bit key on a
fixed-size padded window, so every split step is one jitted program.

The window [begin, begin+bucket) may overrun into the next leaf's range; pad
positions (>= count) get the highest key, and a *stable* sort therefore
returns them in original order at the window tail — the overrun region is
rewritten byte-identical, so neighbours are untouched.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


def decide_left(bins: jax.Array, threshold, default_left, missing_type,
                default_bin, num_bins_f, max_bin_idx=None) -> jax.Array:
    """Binned split decision (reference: include/LightGBM/tree.h:243
    NumericalDecisionInner): missing bin goes to the default side, otherwise
    left iff bin <= threshold."""
    is_missing = jnp.where(
        missing_type == MISSING_ZERO, bins == default_bin,
        jnp.where(missing_type == MISSING_NAN, bins == num_bins_f - 1, False))
    return jnp.where(is_missing, default_left, bins <= threshold)


@functools.partial(jax.jit, static_argnames=("bucket",))
def partition_step(indices_buf: jax.Array, binned: jax.Array,
                   begin: jax.Array, count: jax.Array,
                   feature: jax.Array, threshold: jax.Array,
                   default_left: jax.Array, missing_type: jax.Array,
                   default_bin: jax.Array, num_bins_f: jax.Array,
                   *, bucket: int):
    """Split one leaf's index window into (left | right).

    indices_buf: (N + max_bucket,) int32 permutation buffer
    binned:      (N, F) bin codes
    Returns (new_indices_buf, left_count).
    """
    window = jax.lax.dynamic_slice(indices_buf, (begin,), (bucket,))
    valid = jnp.arange(bucket, dtype=jnp.int32) < count
    fbins = binned[window, feature].astype(jnp.int32)
    go_left = decide_left(fbins, threshold, default_left, missing_type,
                          default_bin, num_bins_f)
    # key: 0 = left, 1 = right, 2 = padding/overrun (stays in place)
    key = jnp.where(valid, jnp.where(go_left, 0, 1), 2).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    new_window = window[order]
    left_count = jnp.sum((key == 0).astype(jnp.int32))
    new_buf = jax.lax.dynamic_update_slice(indices_buf, new_window, (begin,))
    return new_buf, left_count


@functools.partial(jax.jit, static_argnames=("bucket",))
def partition_step_categorical(indices_buf: jax.Array, binned: jax.Array,
                               begin: jax.Array, count: jax.Array,
                               feature: jax.Array, bitset: jax.Array,
                               *, bucket: int):
    """Categorical split: left iff the row's bin is in the bitset
    (reference: CategoricalDecisionInner + Common::FindInBitset)."""
    window = jax.lax.dynamic_slice(indices_buf, (begin,), (bucket,))
    valid = jnp.arange(bucket, dtype=jnp.int32) < count
    fbins = binned[window, feature].astype(jnp.int32)
    word = bitset[jnp.clip(fbins // 32, 0, bitset.shape[0] - 1)]
    go_left = ((word >> (fbins % 32)) & 1) == 1
    go_left = go_left & (fbins // 32 < bitset.shape[0])
    key = jnp.where(valid, jnp.where(go_left, 0, 1), 2).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    new_window = window[order]
    left_count = jnp.sum((key == 0).astype(jnp.int32))
    new_buf = jax.lax.dynamic_update_slice(indices_buf, new_window, (begin,))
    return new_buf, left_count


@jax.jit
def init_partition(indices: jax.Array, buf_size: int | None = None):
    """Root partition from a (possibly bagged) index set."""
    return indices


def make_indices_buffer(n_total: int, max_bucket: int,
                        bag_indices=None) -> jax.Array:
    """Allocate the padded permutation buffer."""
    import numpy as np
    buf = np.zeros(n_total + max_bucket, dtype=np.int32)
    if bag_indices is None:
        buf[:n_total] = np.arange(n_total, dtype=np.int32)
    else:
        buf[: len(bag_indices)] = bag_indices
    return jnp.asarray(buf)
