"""Device-side EFB helpers: column-histogram expansion and row routing.

Counterpart of the reference's per-group histogram offsets + FixHistogram
(reference: src/io/dataset.cpp:820-960 ConstructHistograms works per
feature-GROUP; FeatureHistogram reads its subfeature's offset slice and
Dataset::FixHistogram (dataset.h:419) reconstructs the elided default bin
by subtraction from the leaf totals). Both steps are static gathers /
elementwise math — ideal XLA work.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def expand_column_hist(col_hist: jax.Array,       # (C, Bc, 3)
                       totals: jax.Array,         # (3,) leaf sums
                       hist_idx: jax.Array,       # (F, B) int32 flat index
                       f_elide: jax.Array,        # (F,) int32 0/1
                       f_default: jax.Array,      # (F,) int32 default bin
                       ) -> jax.Array:
    """Column histograms -> per-feature histograms (F, B, 3).

    hist_idx points into the flattened (C*Bc, 3) array with one trailing
    zero slot for invalid positions; elided default bins are reconstructed
    as totals - sum(other bins), the FixHistogram identity.
    """
    c, bc, _ = col_hist.shape
    flat = jnp.concatenate(
        [col_hist.reshape(c * bc, 3), jnp.zeros((1, 3), col_hist.dtype)])
    fh = flat[hist_idx]                               # (F, B, 3)
    rem = totals[None, :] - fh.sum(axis=1)            # (F, 3)
    b = fh.shape[1]
    donehot = (jnp.arange(b, dtype=jnp.int32)[None, :]
               == f_default[:, None]).astype(fh.dtype)       # (F, B)
    fix = donehot[:, :, None] * rem[:, None, :] * f_elide[:, None, None]
    return fh + fix


def logical_bins_for_feature(col_codes: jax.Array, base, default_bin,
                             num_bins_f, elide) -> jax.Array:
    """Map a column's raw codes to one subfeature's logical bins.

    For single-feature columns (elide == 0) codes ARE the bins. For bundle
    members, codes in [base, base + nbin - 2] unmap to the feature's
    non-default bins; anything else means 'this feature at its default'.
    """
    j = col_codes - base
    inside = (j >= 0) & (j < num_bins_f - 1)
    logical = j + (j >= default_bin).astype(col_codes.dtype)
    bundled = jnp.where(inside, logical, default_bin)
    return jnp.where(elide > 0, bundled, col_codes)
