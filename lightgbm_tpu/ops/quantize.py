"""Gradient/hessian quantization for integer histogram construction.

The float histogram path (ops/histogram.py) keeps f32 fidelity on the MXU
by splitting (grad, hess) into bf16 hi + lo parts — TWO bf16 matmuls per
row chunk. The GPU gradient-boosting literature replaces the float pair
with per-iteration integer gradients ("XGBoost: Scalable GPU Accelerated
Learning" packs the pair into one integer word; LightGBM's quantized
training discretizes to int8/int16 with stochastic rounding): integer
accumulation is EXACT, so one low-precision matmul replaces the hi/lo
pair, histogram subtraction loses no bits, and distributed reductions
move integer lanes instead of f32 triples.

This module is the one copy of that discretization:

  * per-iteration (and per-class, since each class's tree quantizes its
    own gradient vector) scales s_g, s_h mapping grad/hess onto
    [-qmax, qmax] signed integers;
  * stochastic rounding q = floor(x * s + u), u ~ U[0, 1) — unbiased, so
    per-bin sums concentrate around the exact value instead of
    accumulating rounding drift;
  * an int32-lane packing (qg << 16 | qh) for row transport — one word
    per row instead of two f32 — and the (N, 3) [qg, qh, valid] integer
    operand the one-hot contraction consumes;
  * exact dequantization of integer histograms back to f32 for the
    split scan (ops/split.py rescales with the histogram's scales before
    gain computation).

Overflow safety: per-bin int32 sums are bounded by qmax * N.  The
effective qmax is capped at 2^30 / N so even an adversarial all-max
gradient vector cannot overflow the int32 accumulator (or a psum of
shard-local partial sums, whose total is bounded by the same global N).
At 16-bit this gracefully degrades toward 31 - log2(N) effective bits on
very large datasets; at 8-bit the cap only binds above ~8M rows.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_EPS = 1e-12


def quant_max(grad_bits: int, n: int) -> int:
    """Largest quantized magnitude for `grad_bits` that is also safe to
    accumulate over n rows in int32 (see module docstring)."""
    qmax = (1 << (grad_bits - 1)) - 1
    cap = (1 << 30) // max(int(n), 1)
    return max(1, min(qmax, cap))


def operand_dtype(grad_bits: int):
    """Matmul operand dtype: int8 rides the MXU's native i8 path; wider
    quantizations contract as int32 (still one pass, still exact)."""
    return jnp.int8 if grad_bits <= 8 else jnp.int32


def gh_scales(grad: jax.Array, hess: jax.Array, grad_bits: int, n: int):
    """(s_g, s_h) f32 scalars mapping this iteration's grad/hess onto
    [-qcap, qcap]. Computed from the max magnitude (the reference
    GradientDiscretizer uses the same max-abs scaling)."""
    qcap = jnp.float32(quant_max(grad_bits, n))
    s_g = qcap / (jnp.max(jnp.abs(grad)) + _EPS)
    s_h = qcap / (jnp.max(jnp.abs(hess)) + _EPS)
    return s_g, s_h


def _round(x: jax.Array, key, stochastic: bool) -> jax.Array:
    if stochastic:
        u = jax.random.uniform(key, x.shape)
        return jnp.floor(x + u)
    return jnp.rint(x)


def quantize_gh_core(grad: jax.Array, hess: jax.Array, key: jax.Array,
                     *, grad_bits: int, stochastic: bool = True):
    """Discretize one iteration's (grad, hess) to signed integers packed
    into ONE int32 lane per row — the canonical UNJITTED core, callable
    from inside other jitted programs (the whole-tree growers) without
    nesting jit. Top-level callers use the jitted `quantize_gh` wrapper.

    Returns (packed (N,) int32, s_g, s_h): qg in the high 16 bits, qh in
    the low 16 (both within int16 by construction: quant_max <= 32767).
    """
    n = grad.shape[0]
    qcap = quant_max(grad_bits, n)
    s_g, s_h = gh_scales(grad, hess, grad_bits, n)
    kg, kh = jax.random.split(key)
    qg = jnp.clip(_round(grad * s_g, kg, stochastic), -qcap, qcap) \
        .astype(jnp.int32)
    qh = jnp.clip(_round(hess * s_h, kh, stochastic), -qcap, qcap) \
        .astype(jnp.int32)
    return pack_gh(qg, qh), s_g, s_h


quantize_gh = functools.partial(jax.jit,
                                static_argnames=("grad_bits", "stochastic"))(
    quantize_gh_core)


def quantize_gh_pmax(grad: jax.Array, hess: jax.Array, key: jax.Array,
                     *, grad_bits: int, n_total: int, axis_name=None,
                     stochastic: bool = True):
    """Sharded in-program discretization (unjitted, for use inside
    shard_map tree programs): the max-abs scales are pmax'd over
    `axis_name` so every shard quantizes against the same GLOBAL range,
    and the overflow cap uses the global row count `n_total` (per-bin
    int32 sums — and their psum across shards — stay exact). The
    stochastic-rounding key is decorrelated per shard via fold_in."""
    qcap = quant_max(grad_bits, max(int(n_total), grad.shape[0]))
    mg = jnp.max(jnp.abs(grad))
    mh = jnp.max(jnp.abs(hess))
    if axis_name is not None:
        mg = jax.lax.pmax(mg, axis_name)
        mh = jax.lax.pmax(mh, axis_name)
        key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
    s_g = jnp.float32(qcap) / (mg + _EPS)
    s_h = jnp.float32(qcap) / (mh + _EPS)
    kg, kh = jax.random.split(key)
    qg = jnp.clip(_round(grad * s_g, kg, stochastic), -qcap, qcap) \
        .astype(jnp.int32)
    qh = jnp.clip(_round(hess * s_h, kh, stochastic), -qcap, qcap) \
        .astype(jnp.int32)
    return pack_gh(qg, qh), s_g, s_h


def pack_gh(qg: jax.Array, qh: jax.Array) -> jax.Array:
    """(qg << 16) | (qh & 0xffff): the one-int32-lane row format."""
    return (qg << 16) | (qh & jnp.int32(0xFFFF))


def unpack_gh(packed: jax.Array):
    """Inverse of pack_gh; both int32 shifts are arithmetic, so the low
    half sign-extends exactly."""
    qg = packed >> 16
    qh = (packed << 16) >> 16
    return qg, qh


def gh_operand(packed: jax.Array, valid: jax.Array,
               grad_bits: int) -> jax.Array:
    """(N, 3) integer [qg, qh, valid] matmul operand from packed rows.

    `valid` is a 0/1 mask (pad / out-of-leaf rows contribute nothing);
    the third lane makes the count channel ride the same single
    contraction the float path's K=3 axis does.
    """
    qg, qh = unpack_gh(packed)
    v = valid.astype(jnp.int32)
    return jnp.stack([qg * v, qh * v, v], axis=1) \
        .astype(operand_dtype(grad_bits))


def dequant_scale3(s_g: jax.Array, s_h: jax.Array) -> jax.Array:
    """(3,) f32 [1/s_g, 1/s_h, 1] — multiply an integer histogram by this
    to recover f32 (sum_grad, sum_hess, count)."""
    return jnp.stack([1.0 / s_g, 1.0 / s_h, jnp.float32(1.0)])


def dequantize_histogram(hist_q: jax.Array, s_g: jax.Array,
                         s_h: jax.Array) -> jax.Array:
    """(..., 3) int32 integer histogram -> f32 with the iteration's
    scales. Counts pass through unscaled."""
    return hist_q.astype(jnp.float32) * dequant_scale3(s_g, s_h)


# ---------------------------------------------------------------------------
# Leaf-wise re-quantization (the packed compact/chunk growth cores).
#
# Quantizing once against the ROOT's max-abs scale starves deep leaves:
# a leaf whose gradients span 1% of the root range uses ~log2(100) fewer
# effective bits than its budget. The renewal scheme (LightGBM's per-leaf
# renormalization, rendered for integer row transport):
#
#   * rows are STORED at 16-bit resolution (the packed (qg|qh) word has a
#     16-bit field per component regardless of grad_bits, so the extra
#     storage bits are free);
#   * per leaf, the histogram OPERAND is re-quantized from the stored
#     int16 values down to grad_bits at a LEAF-LOCAL scale: the ratio
#     r = qcap_op / max|q16 over the leaf's rows| maps the leaf's actual
#     range onto the full operand budget (the row maxes are measured
#     during the partition pass, which reads every parent row anyway);
#   * the leaf's histogram pool entry is rescaled to the new ratio before
#     sibling subtraction (counts stay exact ints; the f32 rescale noise
#     is ~2^-24 relative, the float path's own noise floor);
#   * the split scan dequantizes with the leaf's effective scale
#     s_leaf = s16 * r.
#
# Per-row error ~1/(s16 * r_leaf) instead of 1/s_root: a leaf spanning
# 1% of the root range at grad_bits=8 recovers the ~6.6 bits the fixed
# scale wasted.
# ---------------------------------------------------------------------------


def storage_bits(grad_bits: int, renew: bool) -> int:
    """Row-storage resolution for the packed working buffer: 16-bit when
    leaf re-quantization is on (the packed word's field width — free),
    grad_bits when off (bit-exact match with the masked strategy)."""
    return 16 if renew else grad_bits


def requant_ratio(leaf_max_q: jax.Array, qcap_op: int) -> jax.Array:
    """Leaf-local operand rescale ratio from the leaf's max |stored int|
    (f32). All-zero leaves get ratio 1 (nothing to rescale)."""
    return jnp.where(leaf_max_q > 0.0,
                     jnp.float32(qcap_op) / jnp.maximum(leaf_max_q, 1.0),
                     jnp.float32(1.0))


def gh_operand_scaled(packed: jax.Array, valid: jax.Array, grad_bits: int,
                      qcap_op: int, r_g: jax.Array,
                      r_h: jax.Array) -> jax.Array:
    """(N, 3) [qg, qh, valid] matmul operand re-quantized to the leaf's
    scale: q_op = clip(rint(q16 * r), -qcap_op, qcap_op). With r == 1.0
    this reduces exactly to gh_operand (f32 holds ints <= 32767
    exactly), so the fixed-scale path shares this one code path."""
    qg, qh = unpack_gh(packed)
    qg2 = jnp.clip(jnp.rint(qg.astype(jnp.float32) * r_g),
                   -qcap_op, qcap_op).astype(jnp.int32)
    qh2 = jnp.clip(jnp.rint(qh.astype(jnp.float32) * r_h),
                   -qcap_op, qcap_op).astype(jnp.int32)
    v = valid.astype(jnp.int32)
    return jnp.stack([qg2 * v, qh2 * v, v], axis=1) \
        .astype(operand_dtype(grad_bits))


def rescale_histogram(hist_q: jax.Array, r_g: jax.Array,
                      r_h: jax.Array) -> jax.Array:
    """Re-express an int32 (..., 3) histogram built at ratio r_old into
    ratio r_new units (pass r = r_new / r_old per lane). The count lane
    is NOT touched (exact integers); the (g, h) lanes round-trip through
    f32, bounded-safe because per-bin |sum| <= qcap_op * count <= 2^30
    in the TARGET units too (every row's rescaled magnitude is clipped
    to qcap_op)."""
    gh2 = jnp.rint(hist_q[..., :2].astype(jnp.float32)
                   * jnp.stack([r_g, r_h])).astype(jnp.int32)
    return jnp.concatenate([gh2, hist_q[..., 2:]], axis=-1)


def wire_dtype(grad_bits: int, n: int):
    """Reduce-scatter payload dtype for the DP scatter mode's quantized
    histogram lanes: int16 when the SHARD-SUM bound fits — the collective
    accumulates global per-bin sums, bounded by quant_max * n, so the
    narrow wire is exact iff that product fits int16 — else int32 (still
    2 lanes, 2/3 the f32 triple's bytes)."""
    return (jnp.int16 if quant_max(grad_bits, n) * max(int(n), 1) <= 32767
            else jnp.int32)
