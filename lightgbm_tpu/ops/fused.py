"""Fused per-split device program.

One split of leaf-wise growth = partition + child histogram + sibling
subtraction + two split scans. The reference runs these as separate host
phases (serial_tree_learner.cpp:400-605); a GPU pays a kernel launch per
phase, and a tunneled TPU pays a host round-trip. Fusing them into a single
jitted program leaves exactly ONE dispatch and ONE small host fetch
(left_count + two winner tuples) per split — the histograms stay on device
for the children's future splits.

The left child's histogram is built fresh from the parent window (rows not
going left contribute zero weight); the right child's comes from parent
subtraction (reference FeatureHistogram::Subtract). Numerical and
categorical partition decisions are both evaluated and selected by a scalar
flag — no control flow divergence under jit.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import quantize as quant_ops
from . import split as split_ops
from .histogram import build_histogram, build_histogram_quantized
from .partition import decide_left


class FusedStepOut(NamedTuple):
    indices_buf: jax.Array
    left_count: jax.Array
    left_hist: jax.Array
    right_hist: jax.Array
    left_res: split_ops.SplitResult
    right_res: split_ops.SplitResult


def _scan(hist, sg, sh, cnt, meta, min_c, max_c, scan_kwargs, cost=None):
    (f_numbins, f_missing, f_default, feature_mask, monotone, penalty) = meta
    return split_ops.find_best_split.__wrapped__(
        hist, sg, sh, cnt, f_numbins, f_missing, f_default, feature_mask,
        monotone, min_c, max_c, penalty, cost, **scan_kwargs)


def _route_and_partition(indices_buf, binned, iparams, cat_bitset,
                         *, bucket):
    """The ONE copy of the per-split routing + stable partition shared
    by the float and quantized fused steps (any drift would silently
    mis-route one path). Returns (begin, window, rows, valid, go_left,
    new_buf, left_count)."""
    begin, count, feature, threshold = (iparams[0], iparams[1], iparams[2],
                                        iparams[3])
    default_left = iparams[4] > 0
    missing_type = iparams[5]
    default_bin = iparams[6]
    numbins_f = iparams[7]
    is_categorical = iparams[8] > 0
    window = jax.lax.dynamic_slice(indices_buf, (begin,), (bucket,))
    pos = jnp.arange(bucket, dtype=jnp.int32)
    valid = pos < count
    rows = jnp.take(binned, window, axis=0)           # (bucket, F)

    fbins = jnp.take_along_axis(
        rows, jnp.full((bucket, 1), feature, jnp.int32), axis=1)[:, 0]
    fbins = fbins.astype(jnp.int32)
    num_left = decide_left(fbins, threshold, default_left, missing_type,
                           default_bin, numbins_f)
    word = cat_bitset[jnp.clip(fbins // 32, 0, cat_bitset.shape[0] - 1)]
    cat_left = (((word >> (fbins % 32)) & 1) == 1) \
        & (fbins // 32 < cat_bitset.shape[0])
    go_left = jnp.where(is_categorical, cat_left, num_left)

    key = jnp.where(valid, jnp.where(go_left, 0, 1), 2).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    new_window = window[order]
    left_count = jnp.sum((key == 0).astype(jnp.int32))
    new_buf = jax.lax.dynamic_update_slice(indices_buf, new_window, (begin,))
    return begin, window, rows, valid, go_left, new_buf, left_count


@functools.partial(
    jax.jit,
    static_argnames=("bucket", "num_bins", "hist_chunk", "use_pallas"),
    donate_argnames=("indices_buf",))
def fused_split_step(
    indices_buf: jax.Array,      # (N + max_bucket,) partition permutation
    binned: jax.Array,           # (N, F)
    grad: jax.Array, hess: jax.Array,
    iparams: jax.Array,          # (15,) int32: [begin, count, feature,
                                 #  threshold, default_left, missing_type,
                                 #  default_bin, numbins_f(split feature),
                                 #  is_categorical, bitset words 0..5]
    cat_bitset: jax.Array,       # (8,) int32 bitset words
    fparams: jax.Array,          # (10,) f32: [lsum_g, lsum_h, lcnt,
                                 #  rsum_g, rsum_h, rcnt, lmin, lmax,
                                 #  rmin, rmax]
    parent_hist: jax.Array,                       # (F, B, 3)
    feature_meta,                 # tuple of (F,) arrays + mask + penalty
    child_costs=None,             # (2, F) CEGB costs for (left, right)
    *,
    bucket: int, num_bins: int,
    l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
    hist_chunk: int = 0, use_pallas: bool = False,
) -> FusedStepOut:
    left_sums = fparams[0:3]
    right_sums = fparams[3:6]
    lmin, lmax, rmin, rmax = fparams[6], fparams[7], fparams[8], fparams[9]
    (begin, window, rows, valid, go_left, new_buf,
     left_count) = _route_and_partition(indices_buf, binned, iparams,
                                        cat_bitset, bucket=bucket)

    # left-child histogram from the (already gathered) parent rows
    w = (valid & go_left)
    g = jnp.take(grad, window) * w
    h = jnp.take(hess, window) * w
    gh = jnp.stack([g, h, w.astype(jnp.float32)], axis=1)
    left_hist = build_histogram(rows, gh, num_bins, chunk_size=hist_chunk,
                                use_pallas=use_pallas)
    right_hist = parent_hist - left_hist

    scan_kwargs = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    lcost = child_costs[0] if child_costs is not None else None
    rcost = child_costs[1] if child_costs is not None else None
    left_res = _scan(left_hist, left_sums[0], left_sums[1], left_sums[2],
                     feature_meta, lmin, lmax, scan_kwargs, lcost)
    right_res = _scan(right_hist, right_sums[0], right_sums[1], right_sums[2],
                      feature_meta, rmin, rmax, scan_kwargs, rcost)
    return FusedStepOut(new_buf, left_count, left_hist, right_hist,
                        left_res, right_res)


@functools.partial(
    jax.jit,
    static_argnames=("bucket", "num_bins", "hist_chunk", "use_pallas"))
def fused_root_step(
    indices_buf: jax.Array, binned: jax.Array,
    grad: jax.Array, hess: jax.Array, count: jax.Array,
    feature_meta, root_cost=None,
    *, bucket: int, num_bins: int,
    l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
    hist_chunk: int = 0, use_pallas: bool = False,
):
    """Root histogram + scan; returns (hist, totals(3,), SplitResult)."""
    window = jax.lax.dynamic_slice(indices_buf, (0,), (bucket,))
    valid = jnp.arange(bucket, dtype=jnp.int32) < count
    rows = jnp.take(binned, window, axis=0)
    g = jnp.take(grad, window) * valid
    h = jnp.take(hess, window) * valid
    gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
    hist = build_histogram(rows, gh, num_bins, chunk_size=hist_chunk,
                           use_pallas=use_pallas)
    totals = hist[0].sum(axis=0)
    scan_kwargs = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    res = _scan(hist, totals[0], totals[1], totals[2], feature_meta,
                jnp.float32(-jnp.inf), jnp.float32(jnp.inf), scan_kwargs,
                root_cost)
    return hist, totals, res


# ---------------------------------------------------------------------------
# Quantized-gradient fused steps: the same one-dispatch-per-split contract,
# but (grad, hess) arrive pre-discretized as ONE packed int32 lane per row
# (ops/quantize.py), histograms build with a single integer one-hot
# contraction and live in the pool as EXACT int32 — sibling subtraction is
# bit-exact integer arithmetic — and the split scans rescale the leaf's
# sums back to f32 with the iteration's (g_scale, h_scale) before gain
# computation. The jit caches key on grad_bits (the hist operand dtype).
# ---------------------------------------------------------------------------


def _dequant_scan(hist_q, scales, sg, sh, cnt, meta, min_c, max_c,
                  scan_kwargs, cost=None):
    hist = quant_ops.dequantize_histogram(hist_q, scales[0], scales[1])
    return _scan(hist, sg, sh, cnt, meta, min_c, max_c, scan_kwargs, cost)


@functools.partial(
    jax.jit,
    static_argnames=("bucket", "num_bins", "grad_bits", "hist_chunk",
                     "use_pallas"),
    donate_argnames=("indices_buf",))
def fused_split_step_q(
    indices_buf: jax.Array,
    binned: jax.Array,
    gh_packed: jax.Array,        # (N,) int32 packed (qg << 16 | qh)
    iparams: jax.Array,
    cat_bitset: jax.Array,
    fparams: jax.Array,
    parent_hist: jax.Array,      # (F, B, 3) int32 EXACT parent histogram
    scales: jax.Array,           # (2,) f32 [g_scale, h_scale]
    feature_meta,
    child_costs=None,
    *,
    bucket: int, num_bins: int, grad_bits: int,
    l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
    hist_chunk: int = 0, use_pallas: bool = False,
) -> FusedStepOut:
    left_sums = fparams[0:3]
    right_sums = fparams[3:6]
    lmin, lmax, rmin, rmax = fparams[6], fparams[7], fparams[8], fparams[9]
    (begin, window, rows, valid, go_left, new_buf,
     left_count) = _route_and_partition(indices_buf, binned, iparams,
                                        cat_bitset, bucket=bucket)

    w = (valid & go_left)
    ghq = quant_ops.gh_operand(jnp.take(gh_packed, window), w, grad_bits)
    left_hist = build_histogram_quantized(rows, ghq, num_bins,
                                          chunk_size=hist_chunk,
                                          use_pallas=use_pallas)
    # bit-exact integer sibling subtraction (FeatureHistogram::Subtract):
    # a 10-row child of a 1M-row parent loses NOTHING here, where the f32
    # path's subtraction leaves ~(parent magnitude * 1e-7) of noise
    right_hist = parent_hist - left_hist

    scan_kwargs = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    lcost = child_costs[0] if child_costs is not None else None
    rcost = child_costs[1] if child_costs is not None else None
    left_res = _dequant_scan(left_hist, scales, left_sums[0], left_sums[1],
                             left_sums[2], feature_meta, lmin, lmax,
                             scan_kwargs, lcost)
    right_res = _dequant_scan(right_hist, scales, right_sums[0],
                              right_sums[1], right_sums[2], feature_meta,
                              rmin, rmax, scan_kwargs, rcost)
    return FusedStepOut(new_buf, left_count, left_hist, right_hist,
                        left_res, right_res)


@functools.partial(
    jax.jit,
    static_argnames=("bucket", "num_bins", "grad_bits", "hist_chunk",
                     "use_pallas"))
def fused_root_step_q(
    indices_buf: jax.Array, binned: jax.Array,
    gh_packed: jax.Array, scales: jax.Array, count: jax.Array,
    feature_meta, root_cost=None,
    *, bucket: int, num_bins: int, grad_bits: int,
    l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
    hist_chunk: int = 0, use_pallas: bool = False,
):
    """Quantized root: integer histogram + dequantized scan; returns
    (hist_q int32, f32 totals(3,), SplitResult)."""
    window = jax.lax.dynamic_slice(indices_buf, (0,), (bucket,))
    valid = jnp.arange(bucket, dtype=jnp.int32) < count
    rows = jnp.take(binned, window, axis=0)
    ghq = quant_ops.gh_operand(jnp.take(gh_packed, window), valid, grad_bits)
    hist_q = build_histogram_quantized(rows, ghq, num_bins,
                                       chunk_size=hist_chunk,
                                       use_pallas=use_pallas)
    # leaf totals in f32 come from the SAME dequantized sums the scans
    # see, so prefix/complement identities hold exactly
    totals = quant_ops.dequantize_histogram(
        hist_q[0].sum(axis=0), scales[0], scales[1])
    scan_kwargs = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    res = _dequant_scan(hist_q, scales, totals[0], totals[1], totals[2],
                        feature_meta, jnp.float32(-jnp.inf),
                        jnp.float32(jnp.inf), scan_kwargs, root_cost)
    return hist_q, totals, res


# ---------------------------------------------------------------------------
# whole-tree split-loop formulation (models/device_learner.py growth cores)

def run_split_loop(cond, body, state, num_steps: int,
                   program: str = "per_split"):
    """Run a growth core's leaf-wise split loop under the selected
    `grow_program` formulation.

    ``per_split`` is the classic data-dependent ``lax.while_loop`` —
    exits the moment no leaf has positive gain. ``fused_tree`` is a
    fixed-trip ``lax.scan`` over ``num_steps`` (= num_leaves - 1, the
    most splits a tree can take) whose body is gated by ``lax.cond``.
    Both lower to ONE device program per tree; the scan form has a
    STATIC trip count, which is what makes the whole-tree program
    batchable with ``vmap`` (large-K multiclass: K trees, one dispatch)
    and gives XLA a loop it can fully unroll/schedule.

    Bit-exactness: unbatched ``lax.cond`` executes only the taken
    branch, so once ``cond(state)`` goes False the identity arm carries
    the state through the remaining trips untouched — ``k`` stops
    advancing and the split records can never be overwritten; the
    result is bit-identical to the while_loop form. Under ``vmap`` the
    cond lowers to a select that runs both arms; the speculative body
    arm only writes into the carry COPY of an already-stopped tree,
    which the select discards (XLA clamps dynamic-slice indices, so
    garbage state cannot fault).
    """
    if program != "fused_tree":
        return jax.lax.while_loop(cond, body, state)

    def _trip(st, _):
        return jax.lax.cond(cond(st), body, lambda s: s, st), None

    out, _ = jax.lax.scan(_trip, state, None, length=num_steps)
    return out
