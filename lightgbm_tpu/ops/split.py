"""Vectorized best-split search over (feature, bin, missing-direction).

Behavioral equivalent of the reference's per-feature threshold sweeps
(reference: src/treelearner/feature_histogram.hpp:91-116
FindBestThresholdNumerical and :508-648 FindBestThresholdSequence), recast as
a fully-vectorized cumsum + masked argmax over the whole (F, B) plane — ideal
VPU work, no data-dependent control flow.

Semantics reproduced:
  * two sweeps = two missing directions. dir=-1 accumulates from the right
    (missing goes LEFT, default_left=True); dir=+1 from the left (missing
    goes RIGHT). Ties prefer dir=-1, and within dir=-1 the larger threshold,
    within dir=+1 the smaller (loop orders + strict-> comparisons in the
    reference).
  * MissingType::Zero skips the default(zero) bin in both accumulations so
    the zero bin always travels with the missing direction.
  * MissingType::NaN keeps the NaN bin (last bin) out of the dir=-1 right
    accumulation so NaN travels left there; in dir=+1 it stays right.
  * L1 soft-thresholding, L2, max_delta_step clamp, monotone-constraint
    rejection and min/max output clamps (feature_histogram.hpp:446-490).
  * min_data_in_leaf / min_sum_hessian_in_leaf feasibility masks.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class SplitResult(NamedTuple):
    """Winning split for one leaf (all scalars, device)."""
    gain: jax.Array          # f32, NEG_INF if no valid split
    feature: jax.Array       # int32 inner feature index
    threshold: jax.Array     # int32 bin threshold (left: bin <= thr)
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array    # f32 (exact integers)
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(0.0, jnp.abs(s) - l1)


def _leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    out = -_threshold_l1(sum_grad, l1) / (sum_hess + l2)
    # max_delta_step <= 0 means unbounded (traced-scalar-safe clip)
    limit = jnp.where(max_delta_step > 0.0, max_delta_step, jnp.inf)
    return jnp.clip(out, -limit, limit)


def _leaf_output_constrained(sum_grad, sum_hess, l1, l2, max_delta_step,
                             min_c, max_c):
    return jnp.clip(_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step),
                    min_c, max_c)


def _gain_given_output(sum_grad, sum_hess, l1, l2, output):
    sg_l1 = _threshold_l1(sum_grad, l1)
    return -(2.0 * sg_l1 * output + (sum_hess + l2) * output * output)


def leaf_split_gain(sum_grad, sum_hess, l1, l2, max_delta_step):
    """Objective value of keeping a node whole (reference GetLeafSplitGain)."""
    out = _leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)
    return _gain_given_output(sum_grad, sum_hess, l1, l2, out)


def _split_gains(gl, hl, gr, hr, l1, l2, mds, min_c, max_c, mono):
    """Candidate gain; monotone violations -> 0 (reference GetSplitGains)."""
    lo = _leaf_output_constrained(gl, hl, l1, l2, mds, min_c, max_c)
    ro = _leaf_output_constrained(gr, hr, l1, l2, mds, min_c, max_c)
    gain = (_gain_given_output(gl, hl, l1, l2, lo)
            + _gain_given_output(gr, hr, l1, l2, ro))
    violate = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
    return jnp.where(violate, 0.0, gain)


def per_feature_best(
    hist: jax.Array,            # (F, B, 3) f32 [sum_grad, sum_hess, count]
    sum_grad: jax.Array,        # scalar: leaf total gradient
    sum_hess: jax.Array,        # scalar: leaf total hessian
    num_data: jax.Array,        # scalar f32: leaf row count
    feature_num_bins: jax.Array,  # (F,) int32 per-feature bin counts
    feature_missing: jax.Array,   # (F,) int32 MissingType (0/1/2)
    feature_default_bins: jax.Array,  # (F,) int32 default (zero) bin
    feature_mask: jax.Array,    # (F,) bool — sampled-in features
    monotone: jax.Array,        # (F,) int32 constraints (-1/0/1)
    min_constraint: jax.Array,  # scalar leaf output min (monotone prop)
    max_constraint: jax.Array,  # scalar leaf output max
    feature_penalty: jax.Array = None,  # (F,) gain multiplier
                                 # (feature_contri; reference
                                 # feature_histogram.hpp:88 gain *= penalty)
    feature_cost: jax.Array = None,     # (F,) subtractive CEGB cost
                                 # (reference cegb DetlaGain terms)
    *,
    num_bins: int,
    l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
):
    """Per-feature best (gain, threshold, default_left) plus the prefix
    tensors needed to materialize a winner. This is the unit the parallel
    learners reduce over (reference: the per-feature OMP loop in
    FindBestSplitsFromHistograms, serial_tree_learner.cpp:524-605)."""
    f, b, _ = hist.shape
    tgrid = jnp.arange(b, dtype=jnp.int32)[None, :]          # thresholds (1, B)
    nbins = feature_num_bins[:, None]                        # (F, 1)
    is_nan = (feature_missing[:, None] == 2)
    is_zero = (feature_missing[:, None] == 1)
    default_b = feature_default_bins[:, None]

    # The (grad, hess, count) channels ride one (F, B, 3) array through
    # the accumulations and the two missing-directions stack into one
    # leading axis, so the whole sweep is 2 cumsums + one stacked gain
    # chain instead of 6 + 2 — this chain runs per split inside the
    # whole-tree loop, where op count is latency (docs/DESIGN.md 6a-r3).
    # Element-wise order is unchanged, so results are bit-identical.

    # Zero-missing mode: the default bin never enters either accumulation,
    # so its mass rides with `parent - accumulated`, i.e. the missing side.
    skip = is_zero & (tgrid == default_b)
    eff = jnp.where(skip[:, :, None], 0.0, hist)

    # dir=+1: left = prefix over bins [0..t]
    pre = jnp.cumsum(eff, axis=1)                            # (F, B, 3)

    # dir=-1: right = suffix over bins [t+1 .. last], where `last` excludes
    # the NaN bin (so NaN goes left). suffix[t] computed via reversed cumsum.
    nan_excl = is_nan & (tgrid >= nbins - 1)                  # NaN bin mask
    m1_eff = jnp.where(nan_excl[:, :, None], 0.0, eff)
    # strict suffix sums: sum over j > t
    suf = jnp.cumsum(m1_eff[:, ::-1, :], axis=1)[:, ::-1, :] - m1_eff

    totals = jnp.stack([sum_grad, sum_hess, num_data])       # (3,)
    # left sums per direction: p1 = prefix; m1 = total - suffix
    left2 = jnp.stack([pre, totals[None, None, :] - suf])    # (2, F, B, 3)
    right2 = totals[None, None, None, :] - left2

    # valid threshold ranges per feature (reference loop bounds):
    #   dir=+1: t in [0, nb-2]; NaN mode unchanged (NaN bin can sit alone
    #           on the right at t = nb-2).
    #   dir=-1: t in [0, nb-2]; NaN mode: t in [0, nb-3] (right side would
    #           be empty at nb-2 since NaN is excluded there).
    base_valid = (tgrid < nbins - 1) & feature_mask[:, None] & (nbins > 1)
    zero_skip_t = is_zero & (tgrid == default_b)               # not a candidate
    valid2 = jnp.stack([base_valid & ~zero_skip_t,
                        base_valid & ~zero_skip_t
                        & ~(is_nan & (tgrid >= nbins - 2))])   # (2, F, B)

    ok2 = (valid2
           & (left2[..., 2] >= min_data_in_leaf)
           & (right2[..., 2] >= min_data_in_leaf)
           & (left2[..., 1] >= min_sum_hessian)
           & (right2[..., 1] >= min_sum_hessian))
    gains2 = _split_gains(left2[..., 0], left2[..., 1],
                          right2[..., 0], right2[..., 1], l1, l2,
                          max_delta_step, min_constraint, max_constraint,
                          monotone[None, :, None])
    gains2 = jnp.where(ok2, gains2, NEG_INF)
    gains_p1, gains_m1 = gains2[0], gains2[1]

    gain_shift = leaf_split_gain(sum_grad, sum_hess, l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split
    gains_p1 = jnp.where(gains_p1 > min_gain_shift, gains_p1, NEG_INF)
    gains_m1 = jnp.where(gains_m1 > min_gain_shift, gains_m1, NEG_INF)

    # tie-breaking: dir=-1 prefers larger threshold -> argmax over reversed
    # bins; dir=+1 prefers smaller -> plain argmax. Across dirs prefer -1.
    def pick(gains, prefer_large_t):
        per_f = jnp.max(gains, axis=1)
        if prefer_large_t:
            t_best = (b - 1) - jnp.argmax(gains[:, ::-1], axis=1)
        else:
            t_best = jnp.argmax(gains, axis=1)
        return per_f, t_best.astype(jnp.int32)

    best_f_m1, best_t_m1 = pick(gains_m1, True)
    best_f_p1, best_t_p1 = pick(gains_p1, False)

    use_m1 = best_f_m1 >= best_f_p1
    per_feature_gain = jnp.where(use_m1, best_f_m1, best_f_p1)
    per_feature_t = jnp.where(use_m1, best_t_m1, best_t_p1)
    # relative gains (reference: output->gain -= min_gain_shift), then the
    # feature_contri multiplier and CEGB cost subtraction
    per_feature_rel = jnp.where(per_feature_gain > NEG_INF / 2,
                                per_feature_gain - min_gain_shift, NEG_INF)
    if feature_penalty is not None:
        per_feature_rel = jnp.where(per_feature_rel > NEG_INF / 2,
                                    per_feature_rel * feature_penalty,
                                    per_feature_rel)
    if feature_cost is not None:
        per_feature_rel = jnp.where(per_feature_rel > NEG_INF / 2,
                                    per_feature_rel - feature_cost,
                                    per_feature_rel)
    prefix = (pre, suf)
    return per_feature_rel, per_feature_t, use_m1, prefix


def materialize_split(feat, per_feature_rel, per_feature_t, use_m1, prefix,
                      sum_grad, sum_hess, num_data,
                      min_constraint, max_constraint,
                      *, l1, l2, max_delta_step) -> SplitResult:
    """Build the full SplitResult for one chosen feature."""
    pre, suf = prefix
    gain = per_feature_rel[feat]
    thr = per_feature_t[feat]
    dleft = use_m1[feat]
    lg = jnp.where(dleft, sum_grad - suf[feat, thr, 0], pre[feat, thr, 0])
    lh = jnp.where(dleft, sum_hess - suf[feat, thr, 1], pre[feat, thr, 1])
    lc = jnp.where(dleft, num_data - suf[feat, thr, 2], pre[feat, thr, 2])
    rg = sum_grad - lg
    rh = sum_hess - lh
    rc = num_data - lc
    lo = _leaf_output_constrained(lg, lh, l1, l2, max_delta_step,
                                  min_constraint, max_constraint)
    ro = _leaf_output_constrained(rg, rh, l1, l2, max_delta_step,
                                  min_constraint, max_constraint)
    return SplitResult(gain, feat.astype(jnp.int32), thr, dleft,
                       lg, lh, lc, rg, rh, rc, lo, ro)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins",))
def find_best_split(
    hist: jax.Array, sum_grad: jax.Array, sum_hess: jax.Array,
    num_data: jax.Array, feature_num_bins: jax.Array,
    feature_missing: jax.Array, feature_default_bins: jax.Array,
    feature_mask: jax.Array, monotone: jax.Array,
    min_constraint: jax.Array, max_constraint: jax.Array,
    feature_penalty: jax.Array = None, feature_cost: jax.Array = None,
    *, num_bins: int, l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
) -> SplitResult:
    per_feature_rel, per_feature_t, use_m1, prefix = per_feature_best(
        hist, sum_grad, sum_hess, num_data, feature_num_bins,
        feature_missing, feature_default_bins, feature_mask, monotone,
        min_constraint, max_constraint, feature_penalty, feature_cost,
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    feat = jnp.argmax(per_feature_rel).astype(jnp.int32)
    return materialize_split(
        feat, per_feature_rel, per_feature_t, use_m1, prefix,
        sum_grad, sum_hess, num_data, min_constraint, max_constraint,
        l1=l1, l2=l2, max_delta_step=max_delta_step)


def calculate_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    """Public helper (reference CalculateSplittedLeafOutput)."""
    return _leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins",))
def find_best_split_quantized(
    hist_q: jax.Array, g_scale: jax.Array, h_scale: jax.Array,
    sum_grad: jax.Array, sum_hess: jax.Array,
    num_data: jax.Array, feature_num_bins: jax.Array,
    feature_missing: jax.Array, feature_default_bins: jax.Array,
    feature_mask: jax.Array, monotone: jax.Array,
    min_constraint: jax.Array, max_constraint: jax.Array,
    feature_penalty: jax.Array = None, feature_cost: jax.Array = None,
    *, num_bins: int, l1: float, l2: float, max_delta_step: float,
    min_data_in_leaf: int, min_sum_hessian: float, min_gain_to_split: float,
) -> SplitResult:
    """Quantized-histogram split scan: rescale the leaf's EXACT integer
    (sum_qg, sum_qh, count) sums back to f32 with the iteration's scales
    BEFORE gain computation, then run the identical vectorized sweep.
    The integer domain carries construction and sibling subtraction; the
    gain arithmetic stays in f32 where the reference's formulas live.
    """
    from .quantize import dequantize_histogram
    hist = dequantize_histogram(hist_q, g_scale, h_scale)
    return find_best_split.__wrapped__(
        hist, sum_grad, sum_hess, num_data, feature_num_bins,
        feature_missing, feature_default_bins, feature_mask, monotone,
        min_constraint, max_constraint, feature_penalty, feature_cost,
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)


class CatSplitResult(NamedTuple):
    gain: jax.Array
    feature: jax.Array
    left_mask: jax.Array     # (B,) bool — inner bins routed left
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array


def per_feature_best_categorical(
    hist: jax.Array, sum_grad: jax.Array, sum_hess: jax.Array,
    num_data: jax.Array, feature_num_bins: jax.Array,
    feature_missing: jax.Array, feature_mask: jax.Array,
    min_constraint: jax.Array, max_constraint: jax.Array,
    feature_penalty: jax.Array = None,
    *, num_bins: int, l1: float, l2: float, cat_l2: float, cat_smooth: float,
    max_delta_step: float, min_data_in_leaf: int, min_sum_hessian: float,
    min_gain_to_split: float, max_cat_threshold: int, max_cat_to_onehot: int,
    min_data_per_group: int,
):
    """Per-feature categorical k-vs-rest best gains (reference:
    feature_histogram.hpp:118-279 FindBestThresholdCategorical).

    One-hot mode for small cardinality; otherwise bins are sorted by
    grad/(hess+cat_smooth) and prefixes from both ends are scanned (bounded
    by max_cat_threshold). Vectorized over features x sorted-positions.
    Deviation noted: the reference's min_data_per_group *running-group*
    accumulation is approximated by the per-candidate right-count check.

    Returns (rel_gains (F,), aux) where rel_gains are min_gain_shift-
    relative (penalty-scaled) gains comparable to per_feature_best's, and
    aux holds what materialize_cat_split needs to build the winner's
    left-bin mask. Split out from the monolithic jit so the whole-tree
    device program can merge categorical and numerical candidates in one
    traced scan (the device analog of SerialTreeLearner._merge_categorical).
    """
    f, b, _ = hist.shape
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    bgrid = jnp.arange(b, dtype=jnp.int32)[None, :]
    nbins = feature_num_bins[:, None]
    # used_bin = num_bin - 1 + (missing_type == None): the trailing
    # overflow/NaN bin is not a candidate unless the feature is "full"
    is_full = (feature_missing[:, None] == 0)
    used_bin = nbins - 1 + is_full.astype(jnp.int32)
    bin_ok = bgrid < used_bin

    gain_shift = leaf_split_gain(sum_grad, sum_hess, l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split
    use_onehot = (feature_num_bins <= max_cat_to_onehot)

    def gains_for(gl, hl, eff_l2, ok):
        gr = sum_grad - gl
        hr = sum_hess - hl
        gains = _split_gains(gl, hl, gr, hr, l1, eff_l2, max_delta_step,
                             min_constraint, max_constraint, 0)
        return jnp.where(ok, gains, NEG_INF)

    # ---- one-hot mode: left = single bin --------------------------------
    oh_ok = (bin_ok
             & (c >= min_data_in_leaf) & (h >= min_sum_hessian)
             & ((num_data - c) >= min_data_in_leaf)
             & ((sum_hess - h) >= min_sum_hessian))
    # reference computes gain(other, bin) == gain(bin, other); symmetric
    oh_gains = gains_for(g, h, l2, oh_ok)
    oh_gains = jnp.where(oh_gains > min_gain_shift, oh_gains, NEG_INF)
    oh_best = jnp.max(oh_gains, axis=1)
    oh_t = jnp.argmax(oh_gains, axis=1).astype(jnp.int32)

    # ---- sorted mode ----------------------------------------------------
    # (g, h, c) ride one (F, B, 3) array through the sort-gather, the
    # roll and the cumsum, and the two walk directions stack on a
    # leading axis — one gather + one cumsum + one gain chain instead of
    # 3/6/2 (bit-identical; this runs per split in the device loop)
    eff_l2 = l2 + cat_l2
    valid_sorted = bin_ok & (c >= cat_smooth)
    ctr = jnp.where(valid_sorted, g / (h + cat_smooth), jnp.inf)
    order = jnp.argsort(ctr, axis=1)                    # (F, B) bins by ctr
    hs = jnp.take_along_axis(hist, order[:, :, None], axis=1)
    v_s = jnp.take_along_axis(valid_sorted, order, axis=1)
    n_valid = jnp.sum(v_s.astype(jnp.int32), axis=1, keepdims=True)
    hs = jnp.where(v_s[:, :, None], hs, 0.0)
    max_num_cat = jnp.minimum(max_cat_threshold, (n_valid + 1) // 2)
    pos = jnp.arange(b, dtype=jnp.int32)[None, :]

    # dir=-1 walks from the high-ctr end: flip, then rotate so valid
    # entries lead (they sit at the tail after the flip)
    shift = b - n_valid[:, 0]
    roll_idx = (pos + shift[:, None]) % b

    def roll_rows(x):
        return jnp.take_along_axis(x, roll_idx, axis=1)

    hr = jnp.take_along_axis(hs[:, ::-1, :], roll_idx[:, :, None], axis=1)
    v_r = roll_rows(v_s[:, ::-1])

    hd2 = jnp.stack([hs, hr])                           # (2, F, B, 3)
    vd2 = jnp.stack([v_s, v_r])
    left2 = jnp.cumsum(hd2, axis=2)
    gl2, hl2, cl2 = left2[..., 0], left2[..., 1], left2[..., 2]
    ok2 = (vd2 & (pos < max_num_cat)
           & (cl2 >= min_data_in_leaf) & (hl2 >= min_sum_hessian)
           & ((num_data - cl2)
              >= jnp.maximum(min_data_in_leaf, min_data_per_group))
           & ((sum_hess - hl2) >= min_sum_hessian))
    gains2 = gains_for(gl2, hl2, eff_l2, ok2)
    gains2 = jnp.where(gains2 > min_gain_shift, gains2, NEG_INF)
    best2 = jnp.max(gains2, axis=2)
    ti2 = jnp.argmax(gains2, axis=2).astype(jnp.int32)
    (fwd_best, bwd_best), (fwd_t, bwd_t) = best2, ti2

    use_fwd = fwd_best >= bwd_best
    sort_best = jnp.where(use_fwd, fwd_best, bwd_best)
    sort_t = jnp.where(use_fwd, fwd_t, bwd_t)

    per_gain = jnp.where(use_onehot, oh_best, sort_best)
    per_gain = jnp.where(feature_mask, per_gain, NEG_INF)
    rel = jnp.where(per_gain > NEG_INF / 2,
                    per_gain - min_gain_shift, NEG_INF)
    if feature_penalty is not None:
        rel = jnp.where(rel > NEG_INF / 2, rel * feature_penalty, rel)
    order_r = roll_rows(order[:, ::-1])
    aux = (use_onehot, oh_t, sort_t, use_fwd, order, v_s, order_r, v_r)
    return rel, aux


def materialize_cat_split(feat, rel, aux, hist,
                          sum_grad, sum_hess, num_data,
                          min_constraint, max_constraint,
                          *, l1, l2, cat_l2,
                          max_delta_step) -> CatSplitResult:
    """Build the full CatSplitResult (incl. the left-bin mask over inner
    bins) for one chosen categorical feature."""
    use_onehot, oh_t, sort_t, use_fwd, order, v_s, order_r, v_r = aux
    b = hist.shape[1]
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    gain = rel[feat]

    pos_b = jnp.arange(b, dtype=jnp.int32)
    onehot_mask = (pos_b == oh_t[feat])
    k = sort_t[feat]
    sel_sorted = (pos_b <= k)
    fwd_mask = jnp.zeros(b, dtype=bool).at[order[feat]].set(
        sel_sorted & v_s[feat])
    bwd_mask = jnp.zeros(b, dtype=bool).at[order_r[feat]].set(
        sel_sorted & v_r[feat])
    sorted_mask = jnp.where(use_fwd[feat], fwd_mask, bwd_mask)
    left_mask = jnp.where(use_onehot[feat], onehot_mask, sorted_mask)

    lg = jnp.sum(jnp.where(left_mask, g[feat], 0.0))
    lh = jnp.sum(jnp.where(left_mask, h[feat], 0.0))
    lc = jnp.sum(jnp.where(left_mask, c[feat], 0.0))
    rg = sum_grad - lg
    rh = sum_hess - lh
    rc = num_data - lc
    w_l2 = jnp.where(use_onehot[feat], l2, l2 + cat_l2)
    lo = jnp.clip(-_threshold_l1(lg, l1) / (lh + w_l2),
                  min_constraint, max_constraint)
    ro = jnp.clip(-_threshold_l1(rg, l1) / (rh + w_l2),
                  min_constraint, max_constraint)
    limit = jnp.where(max_delta_step > 0, max_delta_step, jnp.inf)
    lo = jnp.clip(lo, -limit, limit)
    ro = jnp.clip(ro, -limit, limit)
    return CatSplitResult(gain, feat.astype(jnp.int32), left_mask,
                          lg, lh, lc, rg, rh, rc, lo, ro)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins",))
def find_best_split_categorical(
    hist: jax.Array, sum_grad: jax.Array, sum_hess: jax.Array,
    num_data: jax.Array, feature_num_bins: jax.Array,
    feature_missing: jax.Array, feature_mask: jax.Array,
    min_constraint: jax.Array, max_constraint: jax.Array,
    *, num_bins: int, l1: float, l2: float, cat_l2: float, cat_smooth: float,
    max_delta_step: float, min_data_in_leaf: int, min_sum_hessian: float,
    min_gain_to_split: float, max_cat_threshold: int, max_cat_to_onehot: int,
    min_data_per_group: int,
) -> CatSplitResult:
    """Whole-leaf categorical winner (host-loop learner entry point)."""
    rel, aux = per_feature_best_categorical(
        hist, sum_grad, sum_hess, num_data, feature_num_bins,
        feature_missing, feature_mask, min_constraint, max_constraint,
        num_bins=num_bins, l1=l1, l2=l2, cat_l2=cat_l2,
        cat_smooth=cat_smooth, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split,
        max_cat_threshold=max_cat_threshold,
        max_cat_to_onehot=max_cat_to_onehot,
        min_data_per_group=min_data_per_group)
    feat = jnp.argmax(rel).astype(jnp.int32)
    return materialize_cat_split(
        feat, rel, aux, hist, sum_grad, sum_hess, num_data,
        min_constraint, max_constraint,
        l1=l1, l2=l2, cat_l2=cat_l2, max_delta_step=max_delta_step)
