"""Evaluation metrics (reference factory: src/metric/metric.cpp:16-61)."""
from .metric import METRIC_NAMES, Metric, create_metric, create_metrics

__all__ = ["Metric", "create_metric", "create_metrics", "METRIC_NAMES"]
