"""All evaluation metrics.

Parity set with the reference (reference: src/metric/{regression,binary,
multiclass,xentropy,rank,map}_metric.hpp + dcg_calculator.cpp). Scores come
in raw; metrics apply the objective's ConvertOutput exactly like the
reference's Metric::Eval(score, objective) contract.

Round-1 note: metric reductions run host-side on fetched predictions
(once per metric_freq); device-side versions are a later optimization.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..utils import log


def _weighted_mean(values: np.ndarray, weight: Optional[np.ndarray]) -> float:
    if weight is None:
        return float(np.mean(values))
    return float(np.sum(values * weight) / np.sum(weight))


class Metric:
    higher_better = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self.metadata = metadata

    @property
    def names(self) -> List[str]:
        return [self.name]

    def eval(self, score: np.ndarray, objective) -> List[float]:
        raise NotImplementedError

    def _convert(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None:
            import jax.numpy as jnp
            out = objective.convert_output(jnp.asarray(score))
            return np.asarray(out)
        return score


class _PointwiseRegression(Metric):
    """Template for averaged pointwise losses
    (reference: regression_metric.hpp:22 RegressionMetric<T>)."""

    def point_loss(self, y, p):
        raise NotImplementedError

    def transform(self, v: float) -> float:
        return v

    def eval(self, score, objective):
        p = self._convert(score, objective).reshape(-1)
        loss = self.point_loss(self.label, p)
        return [self.transform(_weighted_mean(loss, self.weight))]


class L2Metric(_PointwiseRegression):
    name = "l2"

    def point_loss(self, y, p):
        return (y - p) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def transform(self, v):
        return math.sqrt(v)


class L1Metric(_PointwiseRegression):
    name = "l1"

    def point_loss(self, y, p):
        return np.abs(y - p)


class QuantileMetric(_PointwiseRegression):
    name = "quantile"

    def point_loss(self, y, p):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseRegression):
    name = "huber"

    def point_loss(self, y, p):
        a = self.config.alpha
        d = np.abs(y - p)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegression):
    name = "fair"

    def point_loss(self, y, p):
        c = self.config.fair_c
        x = np.abs(y - p)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegression):
    name = "poisson"

    def point_loss(self, y, p):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_PointwiseRegression):
    name = "mape"

    def point_loss(self, y, p):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseRegression):
    name = "gamma"

    def point_loss(self, y, p):
        eps = 1e-10
        psafe = np.maximum(p, eps)
        return y / psafe + np.log(psafe)  # negative log-likelihood (shape=1)


class GammaDevianceMetric(_PointwiseRegression):
    name = "gamma_deviance"

    def point_loss(self, y, p):
        eps = 1e-10
        frac = y / np.maximum(p, eps)
        return 2.0 * (frac - np.log(np.maximum(frac, eps)) - 1.0)


class TweedieMetric(_PointwiseRegression):
    name = "tweedie"

    def point_loss(self, y, p):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        psafe = np.maximum(p, eps)
        a = y * np.power(psafe, 1.0 - rho) / (1.0 - rho)
        b = np.power(psafe, 2.0 - rho) / (2.0 - rho)
        return -a + b


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score, objective):
        p = np.clip(self._convert(score, objective).reshape(-1), 1e-15, 1 - 1e-15)
        y = (self.label > 0).astype(np.float64)
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [_weighted_mean(loss, self.weight)]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective):
        p = self._convert(score, objective).reshape(-1)
        y = (self.label > 0).astype(np.float64)
        err = ((p > 0.5) != (y > 0)).astype(np.float64)
        return [_weighted_mean(err, self.weight)]


class AUCMetric(Metric):
    """Weighted sort-based AUC (reference: binary_metric.hpp:159)."""
    name = "auc"
    higher_better = True

    def eval(self, score, objective):
        s = np.asarray(score).reshape(-1)
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(-s, kind="stable")
        s_s, y_s, w_s = s[order], y[order], w[order]
        pos_w = y_s * w_s
        neg_w = (1 - y_s) * w_s
        # handle ties: group by equal score
        boundary = np.concatenate([[True], s_s[1:] != s_s[:-1]])
        group = np.cumsum(boundary) - 1
        n_groups = group[-1] + 1
        gpos = np.bincount(group, weights=pos_w, minlength=n_groups)
        gneg = np.bincount(group, weights=neg_w, minlength=n_groups)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(gneg)[:-1]])
        auc_sum = np.sum(gpos * (cum_neg_before + 0.5 * gneg))
        total_pos = pos_w.sum()
        total_neg = neg_w.sum()
        if total_pos == 0 or total_neg == 0:
            return [1.0]
        # reference accumulates pos-above-neg; ours counts neg ranked below
        return [1.0 - auc_sum / (total_pos * total_neg)]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective):
        p = self._convert(score, objective)  # (K, N)
        k = p.shape[0]
        y = self.label.astype(np.int64)
        py = np.clip(p[y, np.arange(len(y))], 1e-15, None)
        return [_weighted_mean(-np.log(py), self.weight)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective):
        p = self._convert(score, objective)
        pred = np.argmax(p, axis=0)
        err = (pred != self.label.astype(np.int64)).astype(np.float64)
        return [_weighted_mean(err, self.weight)]


class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, score, objective):
        p = np.clip(self._convert(score, objective).reshape(-1), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [_weighted_mean(loss, self.weight)]


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective):
        # score -> lambda parameterization (reference xentropy_metric.hpp:166)
        s = np.asarray(score).reshape(-1)
        hhat = np.log1p(np.exp(s))
        w = self.weight if self.weight is not None else np.ones_like(s)
        z = np.clip(1.0 - np.exp(-w * hhat), 1e-15, 1 - 1e-15)
        y = self.label
        loss = -(y * np.log(z) + (1 - y) * np.log(1 - z))
        return [float(np.mean(loss))]


class KLDivMetric(Metric):
    name = "kldiv"

    def eval(self, score, objective):
        p = np.clip(self._convert(score, objective).reshape(-1), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        kl = (y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p)))
        return [_weighted_mean(kl, self.weight)]


class NDCGMetric(Metric):
    """NDCG at eval_at positions (reference: rank_metric.hpp:19 +
    dcg_calculator.cpp:42-129)."""
    name = "ndcg"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        self.label_gain = np.asarray(self.config.label_gain, dtype=np.float64)

    @property
    def names(self):
        return [f"ndcg@{k}" for k in self.eval_at]

    def eval(self, score, objective):
        s = np.asarray(score).reshape(-1)
        qb = self.metadata.query_boundaries
        results = np.zeros(len(self.eval_at))
        sum_w = 0.0
        for q in range(len(qb) - 1):
            lo, hi = qb[q], qb[q + 1]
            ls = self.label[lo:hi].astype(np.int64)
            ss = s[lo:hi]
            qw = 1.0
            sum_w += qw
            gains = self.label_gain[ls]
            disc = 1.0 / np.log2(np.arange(len(ls)) + 2.0)
            ideal = np.sort(gains)[::-1]
            order = np.argsort(-ss, kind="stable")
            got = gains[order]
            for i, k in enumerate(self.eval_at):
                kk = min(k, len(ls))
                maxdcg = np.sum(ideal[:kk] * disc[:kk])
                if maxdcg <= 0:
                    results[i] += 1.0
                else:
                    results[i] += np.sum(got[:kk] * disc[:kk]) / maxdcg
        return list(results / max(sum_w, 1.0))


class MapMetric(Metric):
    """Mean average precision at ks (reference: map_metric.hpp:20)."""
    name = "map"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]

    @property
    def names(self):
        return [f"map@{k}" for k in self.eval_at]

    def eval(self, score, objective):
        s = np.asarray(score).reshape(-1)
        qb = self.metadata.query_boundaries
        results = np.zeros(len(self.eval_at))
        nq = len(qb) - 1
        for q in range(nq):
            lo, hi = qb[q], qb[q + 1]
            rel = (self.label[lo:hi] > 0).astype(np.float64)
            order = np.argsort(-s[lo:hi], kind="stable")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            prec = hits / (np.arange(len(rel_sorted)) + 1.0)
            for i, k in enumerate(self.eval_at):
                kk = min(k, len(rel_sorted))
                denom = min(kk, int(rel.sum())) or 1
                results[i] += np.sum(prec[:kk] * rel_sorted[:kk]) / denom
        return list(results / max(nq, 1))


_CLASSES = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "multi_logloss": MultiLoglossMetric,
    "multi_error": MultiErrorMetric, "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric, "kldiv": KLDivMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
}

METRIC_NAMES = sorted(_CLASSES)

# objective name -> default metric (reference: config metric defaulting)
_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber",
    "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss", "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss", "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "lambdarank": "ndcg",
}


def create_metric(name: str, config) -> Optional[Metric]:
    name = str(name).lower()
    if name in ("", "none", "null", "na", "custom"):
        return None
    cls = _CLASSES.get(name)
    if cls is None:
        log.warning("Unknown metric type name: %s", name)
        return None
    return cls(config)


def create_metrics(metric_names: Sequence[str], config,
                   objective_name: str) -> List[Metric]:
    names = list(metric_names or [])
    if not names:
        default = _DEFAULT_FOR_OBJECTIVE.get(objective_name)
        names = [default] if default else []
    out = []
    for n in names:
        m = create_metric(n, config)
        if m is not None:
            out.append(m)
    return out
