"""Plotting utilities (reference: python-package/lightgbm/plotting.py)."""
from __future__ import annotations

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _to_booster(obj) -> Booster:
    if isinstance(obj, LGBMModel):
        return obj.booster_
    if isinstance(obj, Booster):
        return obj
    raise TypeError("booster must be Booster or LGBMModel")


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    dpi=None, grid=True, precision=3, **kwargs):
    import matplotlib.pyplot as plt
    booster = _to_booster(booster)
    importance = booster.feature_importance(importance_type)
    names = booster.feature_name()
    tuples = sorted(zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot trees with zero importance")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain" else str(x),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        ax.set_ylim(-1, len(values))
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None, xlim=None,
                ylim=None, title="Metric during training", xlabel="Iterations",
                ylabel="auto", figsize=None, dpi=None, grid=True):
    import matplotlib.pyplot as plt
    if isinstance(booster, LGBMModel):
        eval_results = booster.evals_result_
    elif isinstance(booster, dict):
        eval_results = booster
    else:
        raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    msuite = eval_results[names[0]]
    if metric is None:
        metric = list(msuite.keys())[0]
    for name in names:
        if metric in eval_results.get(name, {}):
            results = eval_results[name][metric]
            ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef=0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with @index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True, **kwargs):
    import matplotlib.pyplot as plt
    booster = _to_booster(booster)
    if isinstance(feature, str):
        feature = booster.feature_name().index(feature)
    values = []
    for tree in booster._gbdt.models:
        for node in range(tree.num_leaves - 1):
            if tree.split_feature[node] == feature and not tree._is_categorical(node):
                values.append(float(tree.threshold[node]))
    if not values:
        raise ValueError("Feature was not used in splitting of trees")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    ax.bar(centers, hist, align="center",
           width=width_coef * (bin_edges[1] - bin_edges[0]), **kwargs)
    if title:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@", "index")
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        **kwargs):
    import graphviz
    booster = _to_booster(booster)
    tree = booster._gbdt.models[tree_index]
    names = booster.feature_name()
    graph = graphviz.Digraph(**kwargs)
    show_info = show_info or []

    def add(node, parent=None, decision=None):
        if node >= 0:
            name = f"split{node}"
            feat = names[tree.split_feature[node]] \
                if tree.split_feature[node] < len(names) else str(tree.split_feature[node])
            label = f"{feat}"
            if tree._is_categorical(node):
                label += " = [cats]"
            else:
                label += f" <= {tree.threshold[node]:.{precision}f}"
            if "split_gain" in show_info:
                label += f"\\ngain: {tree.split_gain[node]:.{precision}f}"
            if "internal_count" in show_info:
                label += f"\\ncount: {tree.internal_count[node]}"
            graph.node(name, label=label)
            add(tree.left_child[node], name, "yes")
            add(tree.right_child[node], name, "no")
        else:
            leaf = ~node
            name = f"leaf{leaf}"
            label = f"leaf {leaf}: {tree.leaf_value[leaf]:.{precision}f}"
            if "leaf_count" in show_info:
                label += f"\\ncount: {tree.leaf_count[leaf]}"
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    add(0)
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None, dpi=None,
              show_info=None, precision=3, **kwargs):
    import matplotlib.image as mpimg
    import matplotlib.pyplot as plt
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    graph = create_tree_digraph(booster, tree_index, show_info, precision)
    import io
    s = io.BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
