"""Device mesh construction for distributed training.

The reference builds a TCP/MPI machine mesh (reference:
src/network/linkers_socket.cpp Construct full-mesh handshake); here the mesh
is a jax.sharding.Mesh over local + remote devices — ICI within a slice, DCN
across hosts — and every collective is an XLA op.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import log


def make_mesh(num_devices: Optional[int] = None, axis_name: str = "data",
              devices=None) -> Mesh:
    """1-D mesh over the first num_devices devices (default: all).

    The default (all devices) is the GLOBAL mesh owned by
    distributed/bootstrap — jax.devices() spans every process under
    jax.distributed, so the identical learner code serves the virtual
    single-process mesh and a real multi-host group. Cached there so
    learners, ingest, and checkpoints agree on one mesh object."""
    if num_devices is None and devices is None:
        from ..distributed import bootstrap
        return bootstrap.global_mesh(axis_name)
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(data: int, feature: int, devices=None) -> Mesh:
    """2-D (data, feature) mesh — the grid the reference's parallel modes
    decompose over (rows x features)."""
    devs = list(devices if devices is not None else jax.devices())
    log.check(len(devs) >= data * feature, "not enough devices for mesh")
    arr = np.array(devs[: data * feature]).reshape(data, feature)
    return Mesh(arr, ("data", "feature"))


def shard_rows(mesh: Mesh, arr, axis_name: str = "data"):
    return jax.device_put(arr, NamedSharding(mesh, P(axis_name) if arr.ndim == 1
                                             else P(axis_name, None)))


def shard_features(mesh: Mesh, arr, axis_name: str = "feature"):
    if arr.ndim == 1:
        return jax.device_put(arr, NamedSharding(mesh, P(axis_name)))
    return jax.device_put(arr, NamedSharding(mesh, P(None, axis_name)))


def replicate(mesh: Mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P()))
