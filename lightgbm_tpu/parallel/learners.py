"""Distributed tree learners: data-parallel, feature-parallel, voting-parallel.

The reference's three parallel modes (reference: src/treelearner/
{data,feature,voting}_parallel_tree_learner.cpp) re-expressed on a TPU mesh:

* **FeatureParallelTreeLearner** — all rows on every device, features
  sharded. The reference partitions features per machine, finds local bests
  and allreduces the winner (feature_parallel_tree_learner.cpp:33-76,
  SyncUpGlobalBestSplit). Here the binned matrix and histograms carry a
  `P(None, 'feature')` sharding and the UNCHANGED serial compute runs under
  jit — GSPMD partitions the one-hot contraction and bin scans by feature
  and inserts the argmax-allreduce automatically. The transport layer of the
  reference (network.cpp) has no equivalent code: it is the XLA compiler.

* **DataParallelTreeLearner** — rows sharded, every split does a
  cross-device histogram reduction (reference:
  data_parallel_tree_learner.cpp:149-164 ReduceScatter of all histograms).
  Implemented as explicit shard_map programs: each shard keeps a *local*
  partition-index buffer over its own rows, builds a local histogram on the
  MXU, and a `psum` over the 'data' axis yields the global histogram
  (rides ICI; psum_scatter variant for the sharded-scan path).

* **VotingParallelTreeLearner** — data-parallel with 2-stage voting
  (reference: voting_parallel_tree_learner.cpp:170-260 PV-Tree): each shard
  elects its local top-k features by gain, votes are summed with a psum,
  and only the globally-elected 2k features' histograms are reduced,
  making communication O(k·B) instead of O(F·B).
"""
from __future__ import annotations

import functools
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:   # jax < 0.5: experimental API, check_rep not check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map_exp(f, *args, **kwargs)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..io.dataset import Dataset
from ..models.device_learner import (DeviceTreeLearner,
                                     objective_buffer_names,
                                     padded_shard_cols, swapped_attrs)
from ..models.serial_learner import SerialTreeLearner, _bucket, _MIN_BUCKET
from ..models.tree import Tree
from ..ops import histogram as hist_ops
from ..ops import split as split_ops
from ..resilience import faults
from ..telemetry import counters as telem_counters
from ..telemetry import recorder as telem
from ..telemetry import spans as telem_spans
from ..utils import log
from ..utils.envs import dp_reduce_mode_env
from .mesh import make_mesh


class FeatureParallelTreeLearner(SerialTreeLearner):
    """Feature-sharded learner: serial algorithm + GSPMD shardings."""

    def __init__(self, config: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None):
        super().__init__(config, dataset)
        self.mesh = mesh or make_mesh(axis_name="feature")
        s = self.mesh.devices.size
        f = int(self.binned.shape[1])
        pad_f = (-f) % s
        if pad_f:
            # pad features so the shard axis divides them; padded features
            # are trivial (1 bin) and masked out of every scan
            self.binned = jnp.pad(self.binned, ((0, 0), (0, pad_f)))
            self.f_numbins = jnp.pad(self.f_numbins, (0, pad_f),
                                     constant_values=1)
            self.f_missing = jnp.pad(self.f_missing, (0, pad_f))
            self.f_default = jnp.pad(self.f_default, (0, pad_f))
            self.f_categorical = jnp.pad(self.f_categorical, (0, pad_f))
            self.f_monotone = jnp.pad(self.f_monotone, (0, pad_f))
        self.num_features = f + pad_f
        fsh = NamedSharding(self.mesh, P(None, "feature"))
        vsh = NamedSharding(self.mesh, P("feature"))
        self.binned = jax.device_put(self.binned, fsh)
        self.f_numbins = jax.device_put(self.f_numbins, vsh)
        self.f_missing = jax.device_put(self.f_missing, vsh)
        self.f_default = jax.device_put(self.f_default, vsh)
        self.f_categorical = jax.device_put(self.f_categorical, vsh)
        self.f_monotone = jax.device_put(self.f_monotone, vsh)

    def _feature_mask(self, rng) -> np.ndarray:
        mask = super()._feature_mask(rng)
        if len(mask) < self.num_features:  # padded features never sampled
            mask = np.concatenate(
                [mask, np.zeros(self.num_features - len(mask), dtype=bool)])
        return mask


def _sharded_chunk_opt_in(learner) -> str:
    """The ONE copy of the sharded learners' chunk opt-in: honor
    LGBM_TPU_STRATEGY=chunk when the learner class supports the chunk
    core (all four reductions since round 4: DP psum, DP scatter,
    voting, FP sliced), warn when it cannot."""
    from ..utils.envs import strategy_env
    want = strategy_env()
    capable = getattr(learner, "_chunk_capable", True)
    if want == "chunk" and not capable:
        log.warning("%s does not support the chunk strategy; "
                    "using compact", type(learner).__name__)
    return "chunk" if (want == "chunk" and capable) else "compact"


def _dp_pspec(mesh):
    return NamedSharding(mesh, P("data"))


class DataParallelTreeLearner(SerialTreeLearner):
    """Row-sharded learner with explicit local partitions + psum histograms."""

    def __init__(self, config: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None):
        super().__init__(config, dataset)
        self.mesh = mesh or make_mesh(axis_name="data")
        self.shards = int(self.mesh.devices.size)
        n = dataset.num_data
        if getattr(dataset, "row_shard", None) is not None:
            log.fatal(
                "the host-loop data-parallel learner needs the full "
                "binned matrix on every rank, but this dataset is row-"
                "sharded (dist_shard_mode=rows, rows %d:%d of %d). Only "
                "the device data-parallel learner trains on row-sharded "
                "ingest; fix the config it fell back for, or use "
                "dist_shard_mode=replicated",
                dataset.row_shard[0], dataset.row_shard[1], n)
        self.local_n = -(-n // self.shards)
        pad = self.local_n * self.shards - n
        binned_np = dataset.binned
        if pad:
            binned_np = np.pad(binned_np, ((0, pad), (0, 0)))
        self.n_pad = n + pad
        self.max_local_bucket = _bucket(self.local_n, 1 << 30)
        rsh = NamedSharding(self.mesh, P("data", None))
        self.binned = jax.device_put(
            jnp.asarray(binned_np).reshape(self.shards, self.local_n, -1), rsh)
        self._build_sharded_fns()

    # -- shard_map programs --------------------------------------------
    def _build_sharded_fns(self):
        mesh = self.mesh
        num_bins = self.device_bins

        def hist_fn(binned_l, idx_l, grad_l, hess_l, begin_l, count_l, *, bucket):
            binned_l = binned_l[0]
            idx_l = idx_l[0]
            grad_l = grad_l[0]
            hess_l = hess_l[0]
            window = jax.lax.dynamic_slice(idx_l, (begin_l[0],), (bucket,))
            valid = jnp.arange(bucket, dtype=jnp.int32) < count_l[0]
            rows = jnp.take(binned_l, window, axis=0)
            g = jnp.take(grad_l, window) * valid
            h = jnp.take(hess_l, window) * valid
            gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
            local = hist_ops.build_histogram(rows, gh, num_bins)
            # the reference reduce-scatters histograms across machines
            # (data_parallel_tree_learner.cpp:149-164); psum is the dense
            # equivalent over ICI and leaves the result replicated for the
            # scan that follows
            return jax.lax.psum(local, "data")

        def part_fn(idx_buf, binned_l, begin_l, count_l, feat, thr, dleft,
                    mtype, dbin, nbins, *, bucket):
            from ..ops.partition import decide_left
            idx_l = idx_buf[0]
            binned_l = binned_l[0]
            window = jax.lax.dynamic_slice(idx_l, (begin_l[0],), (bucket,))
            valid = jnp.arange(bucket, dtype=jnp.int32) < count_l[0]
            fbins = binned_l[window, feat].astype(jnp.int32)
            go_left = decide_left(fbins, thr, dleft, mtype, dbin, nbins)
            key = jnp.where(valid, jnp.where(go_left, 0, 1), 2).astype(jnp.int32)
            order = jnp.argsort(key, stable=True)
            new_window = window[order]
            left_cnt = jnp.sum((key == 0).astype(jnp.int32))
            new_idx = jax.lax.dynamic_update_slice(idx_l, new_window,
                                                   (begin_l[0],))
            return new_idx[None], left_cnt[None]

        def hist_fn_q(binned_l, idx_l, packed_l, begin_l, count_l, leaf_n,
                      *, bucket):
            """Quantized-gradient local histogram + COMPACT int32
            allreduce (reference ReduceScatter role, quantized rendering):
            each shard builds its exact int32 (F, B, 3) histogram from
            the packed (qg|qh) rows, but the collective moves only TWO
            int32 lanes [sum_qg, sum_qh] — the count lane is dropped from
            the wire (2/3 the bytes of the float path's f32 triple, with
            exact integer summation instead of f32 rounding) and
            reconstructed from the hessian lane via the leaf's exact
            global count: cnt_bin = round(qh_bin * leaf_n / qh_total).
            Exact for constant-hessian objectives (every row quantizes to
            the same qh); for varying hessians the min_data gate becomes
            approximate, the same class of deviation as the reference's
            hessian-derived counts."""
            from ..ops import quantize as quant_ops
            binned_l = binned_l[0]
            idx_l = idx_l[0]
            packed_row = packed_l[0]
            window = jax.lax.dynamic_slice(idx_l, (begin_l[0],), (bucket,))
            valid = jnp.arange(bucket, dtype=jnp.int32) < count_l[0]
            rows = jnp.take(binned_l, window, axis=0)
            ghq = quant_ops.gh_operand(jnp.take(packed_row, window), valid,
                                       self._quant_bits)
            local = hist_ops.build_histogram_quantized(rows, ghq, num_bins)
            payload = local[:, :, :2]                 # (F, B, 2) int32
            glob = jax.lax.psum(payload, "data")
            qh_tot = glob[0, :, 1].sum().astype(jnp.float32)
            cnt = jnp.round(
                glob[:, :, 1].astype(jnp.float32)
                * (leaf_n / jnp.maximum(qh_tot, 1.0))).astype(jnp.int32)
            return jnp.concatenate([glob, cnt[:, :, None]], axis=2)

        self._hist_fns: Dict[int, object] = {}
        self._hist_fns_q: Dict[int, object] = {}
        self._part_fns: Dict[int, object] = {}

        def get_hist_fn_q(bucket):
            if bucket not in self._hist_fns_q:
                f = shard_map(
                    functools.partial(hist_fn_q, bucket=bucket), mesh=mesh,
                    in_specs=(P("data", None, None), P("data", None),
                              P("data", None), P("data"), P("data"), P()),
                    out_specs=P())
                self._hist_fns_q[bucket] = jax.jit(f)
            return self._hist_fns_q[bucket]

        self._get_hist_fn_q = get_hist_fn_q

        def get_hist_fn(bucket):
            if bucket not in self._hist_fns:
                f = shard_map(
                    functools.partial(hist_fn, bucket=bucket), mesh=mesh,
                    in_specs=(P("data", None, None), P("data", None),
                              P("data", None), P("data", None),
                              P("data"), P("data")),
                    out_specs=P())
                self._hist_fns[bucket] = jax.jit(f)
            return self._hist_fns[bucket]

        def get_part_fn(bucket):
            if bucket not in self._part_fns:
                f = shard_map(
                    functools.partial(part_fn, bucket=bucket), mesh=mesh,
                    in_specs=(P("data", None), P("data", None, None),
                              P("data"), P("data"), P(), P(), P(), P(), P(),
                              P()),
                    out_specs=(P("data", None), P("data")))
                self._part_fns[bucket] = jax.jit(f)
            return self._part_fns[bucket]

        self._get_hist_fn = get_hist_fn
        self._get_part_fn = get_part_fn

    # -- learner overrides ---------------------------------------------
    def train(self, grad, hess, bag_indices=None, iter_seed: int = 0):
        # reshape row-vectors to (S, local_n) shards
        rsh = NamedSharding(self.mesh, P("data", None))
        pad = self.n_pad - self.dataset.num_data
        if pad:
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
        self._grad2 = jax.device_put(
            grad.reshape(self.shards, self.local_n), rsh)
        self._hess2 = jax.device_put(
            hess.reshape(self.shards, self.local_n), rsh)
        if self._quant_bits:
            # per-iteration discretization (ops/quantize.py): every shard
            # holds one packed int32 (qg|qh) lane per row, histograms and
            # their allreduce ride exact integers
            from ..ops import quantize as quant_ops
            qkey = jax.random.PRNGKey((2 * iter_seed + 1) % (2**31 - 1))
            packed, s_g, s_h = quant_ops.quantize_gh(
                grad, hess, qkey, grad_bits=self._quant_bits)
            self._packed2 = jax.device_put(
                packed.reshape(self.shards, self.local_n), rsh)
            self._qscales = (s_g, s_h)
        # local index buffers per shard
        bufs = np.zeros((self.shards, self.local_n + self.max_local_bucket),
                        dtype=np.int32)
        counts = np.zeros(self.shards, dtype=np.int64)
        n = self.dataset.num_data
        if bag_indices is None:
            for s in range(self.shards):
                hi = min(self.local_n, n - s * self.local_n)
                bufs[s, :hi] = np.arange(hi, dtype=np.int32)
                counts[s] = max(hi, 0)
        else:
            shard_of = bag_indices // self.local_n
            local_of = bag_indices % self.local_n
            for s in range(self.shards):
                rows = local_of[shard_of == s]
                bufs[s, : len(rows)] = rows
                counts[s] = len(rows)
        self._idx_buf = jax.device_put(jnp.asarray(bufs), rsh)
        self._leaf_begin: Dict[int, np.ndarray] = {0: np.zeros(self.shards, np.int64)}
        self._leaf_count: Dict[int, np.ndarray] = {0: counts}
        return self._train_from_root(iter_seed)

    def _train_from_root(self, iter_seed):
        """Run the shared leaf-wise loop with sharded primitives."""
        from ..models.tree import Tree
        cfg = self.config
        rng = np.random.RandomState(
            (cfg.feature_fraction_seed + iter_seed) % (2**31 - 1))
        base_mask = self._feature_mask(rng)
        tree = Tree(cfg.num_leaves)

        class _St:  # mirrors serial _LeafState with per-shard ranges
            pass

        def mk_state(leaf_id, sum_grad, sum_hess, depth, min_c, max_c):
            st = _St()
            st.leaf_id = leaf_id
            st.sum_grad = sum_grad
            st.sum_hess = sum_hess
            st.depth = depth
            st.min_c, st.max_c = min_c, max_c
            st.hist = None
            st.split = None
            return st

        def build_hist(leaf_id):
            # host-collective boundary (histogram allreduce): dispatched
            # through the fault layer so injected transport failures land
            # here and transient ones retry with backoff (the programs
            # are side-effect-free, so a re-dispatch is always safe)
            begins = self._leaf_begin[leaf_id]
            cnts = self._leaf_count[leaf_id]
            bucket = _bucket(max(int(cnts.max()), 1), self.max_local_bucket)
            # forensic counter (unconditional, once per leaf): the
            # reduced histogram's payload — the role the reference's
            # ReduceScatter buffer plays; quantized ships 2 int32 lanes,
            # float 3 f32 lanes (4 bytes each either way)
            f = int(self.binned.shape[-1])
            lanes = 2 if self._quant_bits else 3
            telem_counters.incr("dist_reduce_scatter_bytes",
                                f * self.device_bins * lanes * 4)
            with telem.phase("dist_hist_exchange"), \
                    telem_spans.span("dp_hist", leaf=int(leaf_id),
                                     bucket=bucket):
                if self._quant_bits:
                    fn = self._get_hist_fn_q(bucket)
                    return faults.run_collective(
                        lambda: fn(self.binned, self._idx_buf,
                                   self._packed2,
                                   jnp.asarray(begins, jnp.int32),
                                   jnp.asarray(cnts, jnp.int32),
                                   jnp.float32(float(cnts.sum()))),
                        site="dp_hist")
                fn = self._get_hist_fn(bucket)
                return faults.run_collective(
                    lambda: fn(self.binned, self._idx_buf, self._grad2,
                               self._hess2, jnp.asarray(begins, jnp.int32),
                               jnp.asarray(cnts, jnp.int32)),
                    site="dp_hist")

        root_hist = build_hist(0)
        totals = np.asarray(
            jax.device_get(root_hist[0].sum(axis=0)), dtype=np.float64)
        if self._quant_bits:
            s_g, s_h = jax.device_get(self._qscales)
            totals = np.array([totals[0] / float(s_g),
                               totals[1] / float(s_h), totals[2]])
        root = mk_state(0, float(totals[0]), float(totals[1]), 0,
                        -np.inf, np.inf)
        root.hist = root_hist
        root.count = int(self._leaf_count[0].sum())
        root.split = self._scan_state(root, base_mask, rng)
        leaves = {0: root}

        for _ in range(cfg.num_leaves - 1):
            best_leaf, best_gain = -1, 1e-10
            for li, st in leaves.items():
                if st.split is not None and st.split["gain"] > best_gain:
                    best_leaf, best_gain = li, st.split["gain"]
            if best_leaf < 0:
                break
            self._apply_split_dp(tree, leaves, best_leaf, base_mask, rng,
                                 build_hist, mk_state)
        self.leaves = leaves
        return tree

    def _scan_state(self, st, base_mask, rng):
        mask = (self._node_feature_mask(base_mask, rng)
                & (self.f_categorical == 0))
        if self._quant_bits:
            s_g, s_h = self._qscales
            res = split_ops.find_best_split_quantized(
                st.hist, s_g, s_h, jnp.float32(st.sum_grad),
                jnp.float32(st.sum_hess), jnp.float32(st.count),
                self.f_numbins, self.f_missing, self.f_default, mask,
                self.f_monotone, jnp.float32(st.min_c),
                jnp.float32(st.max_c), **self._scan_args())
        else:
            res = split_ops.find_best_split(
                st.hist, jnp.float32(st.sum_grad), jnp.float32(st.sum_hess),
                jnp.float32(st.count), self.f_numbins, self.f_missing,
                self.f_default, mask,
                self.f_monotone, jnp.float32(st.min_c),
                jnp.float32(st.max_c), **self._scan_args())
        return self._fetch_split(res)

    def _apply_split_dp(self, tree, leaves, leaf_id, base_mask, rng,
                        build_hist, mk_state):
        ds = self.dataset
        st = leaves[leaf_id]
        sp = st.split
        inner_f = sp["feature"]
        real_f = ds.inner_to_real(inner_f)
        mapper = ds.bin_mappers[real_f]
        begins = self._leaf_begin[leaf_id]
        cnts = self._leaf_count[leaf_id]
        bucket = _bucket(max(int(cnts.max()), 1), self.max_local_bucket)
        fn = self._get_part_fn(bucket)
        with telem_spans.span("dp_partition", leaf=int(leaf_id),
                              bucket=bucket):
            new_buf, left_cnts = faults.run_collective(
                lambda: fn(
                    self._idx_buf, self.binned,
                    jnp.asarray(begins, jnp.int32),
                    jnp.asarray(cnts, jnp.int32),
                    jnp.int32(inner_f), jnp.int32(sp["threshold"]),
                    jnp.bool_(sp["default_left"]),
                    jnp.int32(mapper.missing_type),
                    jnp.int32(mapper.default_bin),
                    jnp.int32(mapper.num_bin)),
                site="dp_partition")
        self._idx_buf = new_buf
        left_cnts = np.asarray(jax.device_get(left_cnts), dtype=np.int64)

        thr_real = ds.real_threshold(inner_f, sp["threshold"])
        new_leaf = tree.split(
            leaf_id, inner_f, real_f, sp["threshold"], thr_real,
            sp["left_output"], sp["right_output"], sp["left_count"],
            sp["right_count"], sp["left_sum_hess"], sp["right_sum_hess"],
            sp["gain"], mapper.missing_type, sp["default_left"])

        self._leaf_begin[new_leaf] = begins + left_cnts
        self._leaf_count[new_leaf] = cnts - left_cnts
        self._leaf_count[leaf_id] = left_cnts

        left = mk_state(leaf_id, sp["left_sum_grad"], sp["left_sum_hess"],
                        st.depth + 1, st.min_c, st.max_c)
        left.count = sp["left_count"]
        right = mk_state(new_leaf, sp["right_sum_grad"], sp["right_sum_hess"],
                         st.depth + 1, st.min_c, st.max_c)
        right.count = sp["right_count"]
        smaller, larger = ((left, right) if left.count <= right.count
                          else (right, left))
        self._compute_child_hists(st, smaller, larger, build_hist)
        for child in (smaller, larger):
            child.split = (self._scan_state(child, base_mask, rng)
                           if child.hist is not None else None)
        leaves[leaf_id] = left
        leaves[new_leaf] = right

    def _compute_child_hists(self, st, smaller, larger, build_hist):
        if self._splittable_dp(smaller):
            smaller.hist = build_hist(smaller.leaf_id)
        if self._splittable_dp(larger):
            larger.hist = (hist_ops.subtract_histogram(st.hist, smaller.hist)
                           if smaller.hist is not None
                           else build_hist(larger.leaf_id))
        st.hist = None

    def _splittable_dp(self, st) -> bool:
        cfg = self.config
        return (st.count >= 2 * cfg.min_data_in_leaf
                and st.sum_hess >= 2 * cfg.min_sum_hessian_in_leaf
                and (cfg.max_depth <= 0 or st.depth < cfg.max_depth))

    def leaf_rows(self, leaf_id: int) -> np.ndarray:
        """Global row ids of a leaf (for leaf renewal)."""
        bufs = np.asarray(jax.device_get(self._idx_buf))
        out = []
        for s in range(self.shards):
            b = int(self._leaf_begin[leaf_id][s])
            c = int(self._leaf_count[leaf_id][s])
            out.append(bufs[s, b:b + c].astype(np.int64) + s * self.local_n)
        return np.concatenate(out) if out else np.zeros(0, np.int64)


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Data-parallel + top-k feature election (PV-Tree).

    Communication per split is O(2k·B): each shard votes for its local
    top-k features from its LOCAL histogram, votes are psum'd, and only the
    elected features' histograms are globally reduced
    (reference: voting_parallel_tree_learner.cpp:170-260).
    """

    def _build_sharded_fns(self):
        super()._build_sharded_fns()
        mesh = self.mesh
        num_bins = self.device_bins
        cfg = self.config
        top_k = max(1, int(cfg.top_k))
        scan_kwargs = self._scan_args()

        def vote_hist_fn(binned_l, idx_l, grad_l, hess_l, begin_l, count_l,
                         sum_g, sum_h, n_total, nbins, missing, defaults,
                         mask, mono, *, bucket):
            binned_l = binned_l[0]
            idx_l = idx_l[0]
            window = jax.lax.dynamic_slice(idx_l, (begin_l[0],), (bucket,))
            valid = jnp.arange(bucket, dtype=jnp.int32) < count_l[0]
            rows = jnp.take(binned_l, window, axis=0)
            g = jnp.take(grad_l[0], window) * valid
            h = jnp.take(hess_l[0], window) * valid
            gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
            local_hist = hist_ops.build_histogram(rows, gh, num_bins)
            # local voting on LOCAL histogram with globally-scaled
            # constraints (reference scales min_data by 1/num_machines,
            # voting_parallel_tree_learner.cpp:57-59)
            local_n = jnp.sum(valid.astype(jnp.float32))
            local_g = local_hist[0, :, 0].sum()
            local_h = local_hist[0, :, 1].sum()
            rel, _, _, _ = split_ops.per_feature_best(
                local_hist, local_g, local_h, local_n, nbins, missing,
                defaults, mask, mono, jnp.float32(-jnp.inf),
                jnp.float32(jnp.inf),
                **{**scan_kwargs,
                   # the reference scales BOTH local gates by machine
                   # count (voting_parallel_tree_learner.cpp:58-59)
                   "min_data_in_leaf":
                       scan_kwargs["min_data_in_leaf"] // self.shards,
                   "min_sum_hessian":
                       scan_kwargs["min_sum_hessian"] / self.shards})
            f = rel.shape[0]
            k = min(top_k, f)
            _, top_idx = jax.lax.top_k(rel, k)
            votes = jnp.zeros(f, jnp.float32).at[top_idx].add(
                jnp.where(rel[top_idx] > split_ops.NEG_INF / 2, 1.0, 0.0))
            votes = jax.lax.psum(votes, "data")
            # elect global top-2k, reduce only their histograms
            k2 = min(2 * k, f)
            _, elected = jax.lax.top_k(votes, k2)
            elected_hist = jax.lax.psum(local_hist[elected], "data")
            # scatter back into a full-size (F, B, 3) global hist; the scan
            # masks non-elected features out via elected_mask
            full = jnp.zeros((f, num_bins, 3), jnp.float32)
            full = full.at[elected].set(elected_hist)
            elected_mask = jnp.zeros(f, bool).at[elected].set(True)
            return full, elected_mask

        def vote_hist_fn_q(binned_l, idx_l, packed_l, begin_l, count_l,
                           scale3, nbins, missing, defaults, mask, mono,
                           *, bucket):
            """Quantized PV-Tree election: the local histogram is EXACT
            int32 (one integer contraction), local voting scans its
            dequantized rendering (local counts stay exact), and the
            reduced collective — the only cross-shard histogram traffic —
            moves the elected 2k features' int32 histograms."""
            from ..ops import quantize as quant_ops
            binned_l = binned_l[0]
            idx_l = idx_l[0]
            window = jax.lax.dynamic_slice(idx_l, (begin_l[0],), (bucket,))
            valid = jnp.arange(bucket, dtype=jnp.int32) < count_l[0]
            rows = jnp.take(binned_l, window, axis=0)
            ghq = quant_ops.gh_operand(jnp.take(packed_l[0], window), valid,
                                       self._quant_bits)
            local_q = hist_ops.build_histogram_quantized(rows, ghq, num_bins)
            local_hist = local_q.astype(jnp.float32) * scale3
            local_n = jnp.sum(valid.astype(jnp.float32))
            local_g = local_hist[0, :, 0].sum()
            local_h = local_hist[0, :, 1].sum()
            rel, _, _, _ = split_ops.per_feature_best(
                local_hist, local_g, local_h, local_n, nbins, missing,
                defaults, mask, mono, jnp.float32(-jnp.inf),
                jnp.float32(jnp.inf),
                **{**scan_kwargs,
                   "min_data_in_leaf":
                       scan_kwargs["min_data_in_leaf"] // self.shards,
                   "min_sum_hessian":
                       scan_kwargs["min_sum_hessian"] / self.shards})
            f = rel.shape[0]
            k = min(top_k, f)
            _, top_idx = jax.lax.top_k(rel, k)
            votes = jnp.zeros(f, jnp.float32).at[top_idx].add(
                jnp.where(rel[top_idx] > split_ops.NEG_INF / 2, 1.0, 0.0))
            votes = jax.lax.psum(votes, "data")
            k2 = min(2 * k, f)
            _, elected = jax.lax.top_k(votes, k2)
            # int32 collective: exact integer reduction of the elected
            # features' histograms (O(2k*B) int32 lanes on the wire)
            elected_q = jax.lax.psum(local_q[elected], "data")
            elected_hist = elected_q.astype(jnp.float32) * scale3
            full = jnp.zeros((f, num_bins, 3), jnp.float32)
            full = full.at[elected].set(elected_hist)
            elected_mask = jnp.zeros(f, bool).at[elected].set(True)
            return full, elected_mask

        self._vote_fns: Dict[int, object] = {}
        self._vote_fns_q: Dict[int, object] = {}

        def get_vote_fn(bucket):
            if bucket not in self._vote_fns:
                fn = shard_map(
                    functools.partial(vote_hist_fn, bucket=bucket), mesh=mesh,
                    in_specs=(P("data", None, None), P("data", None),
                              P("data", None), P("data", None), P("data"),
                              P("data"), P(), P(), P(), P(), P(), P(), P(),
                              P()),
                    out_specs=(P(), P()))
                self._vote_fns[bucket] = jax.jit(fn)
            return self._vote_fns[bucket]

        def get_vote_fn_q(bucket):
            if bucket not in self._vote_fns_q:
                fn = shard_map(
                    functools.partial(vote_hist_fn_q, bucket=bucket),
                    mesh=mesh,
                    in_specs=(P("data", None, None), P("data", None),
                              P("data", None), P("data"), P("data"),
                              P(), P(), P(), P(), P(), P()),
                    out_specs=(P(), P()))
                self._vote_fns_q[bucket] = jax.jit(fn)
            return self._vote_fns_q[bucket]

        self._get_vote_fn = get_vote_fn
        self._get_vote_fn_q = get_vote_fn_q

    def _scan_state(self, st, base_mask, rng):
        # build voting histogram instead of the dense psum one
        begins = self._leaf_begin[st.leaf_id]
        cnts = self._leaf_count[st.leaf_id]
        bucket = _bucket(max(int(cnts.max()), 1), self.max_local_bucket)
        fmask = self._node_feature_mask(base_mask, rng) & (self.f_categorical == 0)
        # forensic counter: votes (one f32 lane per feature) + the
        # elected 2k features' int32 histogram triples — the PV-Tree
        # O(2k*B) wire payload
        f = int(self.binned.shape[-1])
        k2 = min(2 * max(1, int(self.config.top_k)), f)
        telem_counters.incr("dist_reduce_scatter_bytes",
                            f * 4 + k2 * self.device_bins * 3 * 4)
        with telem.phase("dist_hist_exchange"), \
                telem_spans.span("vote_hist", bucket=bucket):
            if self._quant_bits:
                from ..ops.quantize import dequant_scale3
                fn = self._get_vote_fn_q(bucket)
                full_hist, elected_mask = faults.run_collective(
                    lambda: fn(
                        self.binned, self._idx_buf, self._packed2,
                        jnp.asarray(begins, jnp.int32),
                        jnp.asarray(cnts, jnp.int32),
                        dequant_scale3(*self._qscales), self.f_numbins,
                        self.f_missing, self.f_default, fmask,
                        self.f_monotone),
                    site="vote_hist")
            else:
                fn = self._get_vote_fn(bucket)
                full_hist, elected_mask = faults.run_collective(
                    lambda: fn(
                        self.binned, self._idx_buf, self._grad2,
                        self._hess2,
                        jnp.asarray(begins, jnp.int32),
                        jnp.asarray(cnts, jnp.int32),
                        jnp.float32(st.sum_grad), jnp.float32(st.sum_hess),
                        jnp.float32(st.count), self.f_numbins,
                        self.f_missing,
                        self.f_default, fmask, self.f_monotone),
                    site="vote_hist")
        res = split_ops.find_best_split(
            full_hist, jnp.float32(st.sum_grad), jnp.float32(st.sum_hess),
            jnp.float32(st.count), self.f_numbins, self.f_missing,
            self.f_default, fmask & elected_mask, self.f_monotone,
            jnp.float32(st.min_c), jnp.float32(st.max_c), **self._scan_args())
        return self._fetch_split(res)

    def _compute_child_hists(self, st, smaller, larger, build_hist):
        # voting cannot use parent-minus-sibling subtraction (elected
        # feature sets differ per leaf); _scan_state builds its own
        # vote-reduced histogram, so children just get a go-ahead marker
        st.hist = None
        for child in (smaller, larger):
            child.hist = "voting" if self._splittable_dp(child) else None


class DeviceDataParallelTreeLearner(DeviceTreeLearner):
    """Whole-tree data-parallel learner: rows sharded over a 1-D 'data'
    mesh, the ENTIRE leaf-wise tree (partition + histograms + scans) grown
    inside one jitted shard_map program.

    The reference's per-split communication — ReduceScatter of all local
    histograms plus an Allreduce of the best split (reference:
    src/treelearner/data_parallel_tree_learner.cpp:149-164, :246
    SyncUpGlobalBestSplit) — maps to ONE collective over the smaller
    child's (C, B, 3) histogram per split. Two reduction modes:

    * psum (fallback): the histogram is summed and replicated; every
      shard runs the identical argmax/scan, so the global-best sync
      costs nothing extra.
    * reduce-scatter (default when the dataset has no EFB bundles and
      no by-node sampling): lax.psum_scatter tiles the histogram's
      column axis across shards — each shard owns C/D columns of every
      pool slot (pool memory /D, ~half the reduce traffic), scans its
      slice, and the winner is elected from a (D, 12) all_gather of
      candidate rows, exactly the reference's comm pattern.

    Each shard physically partitions only its own rows (local
    DataPartition semantics, :256-262 global leaf counts come from the
    reduced histograms). No host round-trips inside a tree.
    """

    _chunk_capable = True

    def __init__(self, config: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None):
        # LGBM_TPU_STRATEGY=chunk opts the sharded program into the
        # switch-free chunk core; resolve_strategy may fall chunk back
        # to compact (LRU-capped pool), so read self.strategy afterwards
        super().__init__(config, dataset,
                         strategy=_sharded_chunk_opt_in(self),
                         device_place=False)
        self.mesh = mesh or make_mesh(axis_name="data")
        self.shards = int(self.mesh.devices.size)
        # reduce-scatter mode needs the identity feature->column mapping
        # and shard-independent feature masks (see grow_tree_compact_core
        # / grow_tree_chunk_core — both cores carry the scatter seam)
        mode = dp_reduce_mode_env()
        self.scatter_cols = (
            self.shards if (mode != "psum"
                            and dataset.bundle_arrays() is None
                            and not (0.0 < config.feature_fraction_bynode
                                     < 1.0)
                            and self.shards > 1)
            else 0)
        n = dataset.num_data
        self.local_n = -(-n // self.shards)
        self.n_pad = self.local_n * self.shards

        if self._shard is not None:
            # streamed: no resident codes — train() assembles one
            # working buffer per local mesh device from the host wire
            # store (_train_streamed)
            pass
        elif getattr(dataset, "row_shard", None) is not None:
            # rows-mode ingest: this host's arrays hold ONLY its row
            # block; lift them onto the global mesh with zero cross-host
            # traffic (every device receives exactly its own rows)
            self.codes_pack = self._global_from_local(self.codes_pack)
            self.codes_row = self._global_from_local(self.codes_row)
        else:
            # place the packed buffers row-sharded and padded (the base
            # class kept them host-side); pad rows carry zero codes and
            # are fenced off by w == 0 inside the step
            pad = self.n_pad - n
            rsh = NamedSharding(self.mesh, P("data", None))
            cp, cr = self.codes_pack, self.codes_row
            if pad:
                cp = np.pad(cp, ((0, pad), (0, 0)))
                cr = np.pad(cr, ((0, pad), (0, 0)))
            self.codes_pack = jax.device_put(jnp.asarray(cp), rsh)
            self.codes_row = jax.device_put(jnp.asarray(cr), rsh)
        self._meta = (self.f_numbins, self.f_missing, self.f_default,
                      self.f_monotone, self.f_penalty, self.f_categorical,
                      self.f_col, self.f_base, self.f_elide, self.hist_idx)
        self._tree_w_fn = None

    # -- row-sharded ingest (dist_shard_mode=rows) ---------------------
    def _local_mesh_positions(self):
        """(mesh position, device) pairs of this process's devices along
        the 'data' axis — position p owns global rows [p*local_n,
        (p+1)*local_n)."""
        me = jax.process_index()
        return [(p, d) for p, d in enumerate(self.mesh.devices.flat)
                if d.process_index == me]

    def _global_from_local(self, block) -> jax.Array:
        """Lift this host's (local rows, C) ingest block onto the global
        'data' mesh: each locally-owned mesh position takes its own
        local_n-row slice (zero-padded at the global tail) and
        `make_array_from_single_device_arrays` stitches the per-device
        pieces into one row-sharded global array — no collective, the
        code matrix never crosses the wire. Requires the block to start
        on a local_n boundary and to cover every position this
        process's devices own (`ingest.load_sharded` aligns blocks to
        the local device count, so both hold by construction)."""
        from ..utils.log import LightGBMError
        begin, end = self.dataset.row_shard
        n = self.dataset.num_data
        local_n = self.local_n
        if begin % local_n:
            raise LightGBMError(
                f"row-sharded ingest block starts at row {begin}, not a "
                f"multiple of the per-device block ({local_n} rows = "
                f"ceil({n} rows / {self.shards} devices)); re-ingest "
                "with ingest.load_sharded so blocks align to device "
                "boundaries")
        block = np.asarray(block)
        bufs = []
        for p, dev in self._local_mesh_positions():
            lo = p * local_n - begin
            if lo < 0 or (lo >= block.shape[0] and p * local_n < n):
                raise LightGBMError(
                    f"row-sharded ingest block {begin}:{end} does not "
                    f"cover mesh position {p} (rows {p * local_n}:"
                    f"{(p + 1) * local_n}) owned by this process — the "
                    "ingest world and the training mesh disagree; "
                    "re-ingest (ingest.reshard) after any world-size "
                    "change")
            sl = block[max(lo, 0):lo + local_n]
            if sl.shape[0] < local_n:
                sl = np.pad(sl, ((0, local_n - sl.shape[0]), (0, 0)))
            bufs.append(jax.device_put(jnp.asarray(sl), dev))
        return jax.make_array_from_single_device_arrays(
            (self.n_pad, int(block.shape[1])),
            NamedSharding(self.mesh, P("data", None)), bufs)

    def _count_hist_wire(self, n_splits: int) -> None:
        """Analytic reduce-scatter byte accounting for the in-program
        per-leaf histogram exchange (the collective lives inside the
        jitted tree program, so unlike the host-loop learners there is
        no host boundary to count at): root + one smaller-child
        histogram per split, (C, B, 3) lanes of 4 bytes (int32 when
        quantized, f32 otherwise)."""
        telem_counters.incr(
            "dist_reduce_scatter_bytes",
            (int(n_splits) + 1) * int(self.c_cols)
            * int(self.device_bins) * 3 * 4)

    def replay_tree(self, rec_h, k: int, rec_cat_h=None):
        # every grown tree passes through here (generic, fused and
        # streamed paths), so this is the one host point that sees the
        # split count the wire accounting needs
        self._count_hist_wire(int(k))
        return super().replay_tree(rec_h, k, rec_cat_h)

    # ------------------------------------------------------------------
    def _grow_statics(self):
        # quantized statics: rows carry w=0 pads (and per-shard bag
        # masks), so the packed layout keeps the weight word; the
        # overflow cap and the scatter wire dtype bound on GLOBAL rows
        quant_kw = dict(quant_bits=self.quant_bits,
                        quant_renew=self.quant_renew,
                        quant_total_rows=self.n_pad)
        if self.strategy == "chunk":
            from ..utils.envs import flag
            return dict(c_cols=self.c_cols, item_bits=self.item_bits,
                        chunk_rows=self.chunk_rows,
                        fuse_hist=not flag("LGBM_TPU_CHUNK_NO_FUSE_HIST"),
                        scatter_cols=self.scatter_cols,
                        partition=self._partition_mode,
                        **quant_kw, **self._statics())
        return dict(c_cols=self.c_cols, item_bits=self.item_bits,
                    pool_slots=self.pool_slots,
                    scatter_cols=self.scatter_cols,
                    window_step=self.window_step,
                    partition=self._partition_mode,
                    **quant_kw, **self._statics())

    def _sharded_tree_fn(self, with_bag_key: bool, allow_bagging=True,
                         goss=None):
        """shard_map'd whole-tree program. with_bag_key=True computes the
        per-shard bag weights inside the program (fused path); False takes
        an explicit (n_pad,) weight vector (generic path). allow_bagging
        =False forces full-data growth regardless of bagging params (the
        GOSS-warmup contract). goss=(top_rate, other_rate) switches the
        in-program sampling to per-shard GOSS: each shard keeps its local
        top rows by |g*h| and amplifies a uniform sample of the rest —
        the reference's distributed behavior (BaggingHelper runs on each
        machine's local partition, goss.hpp:60-117 under num_machines>1),
        so no global top-k collective is needed."""
        from ..models.device_learner import (grow_tree_chunk_core, grow_tree_compact_core)
        grow_core = (grow_tree_chunk_core if self.strategy == "chunk" else grow_tree_compact_core)
        statics = self._grow_statics()
        meta = self._meta
        cfg = self.config
        n = self.dataset.num_data
        local_n = self.local_n
        bag_on = (goss is None and allow_bagging and cfg.bagging_freq > 0
                  and cfg.bagging_fraction < 1.0)
        frac = float(cfg.bagging_fraction)

        def local(cp_l, cr_l, g_l, h_l, w_or_key, base_mask, key):
            i = jax.lax.axis_index("data")
            pos = jnp.arange(local_n, dtype=jnp.int32)
            real = jnp.clip(n - i * local_n, 0, local_n)
            alive = pos < real
            if with_bag_key and goss is not None:
                top_rate, other_rate = goss
                realf = real.astype(jnp.float32)
                top_l = jnp.maximum(1, (realf * top_rate).astype(jnp.int32))
                other_l = jnp.maximum(
                    1, (realf * other_rate).astype(jnp.int32))
                # exact local top_l by |g*h| (rank-based like the
                # single-chip fused GOSS; pads carry gmag 0 and sit after
                # equal-key alive rows in the stable sort)
                gmag = jnp.abs(g_l * h_l) * alive.astype(jnp.float32)
                ridx = jnp.argsort(-gmag, stable=True)
                rank_of = jnp.zeros(local_n, jnp.int32).at[ridx].set(pos)
                is_top = (rank_of < top_l) & alive
                u = jnp.where(
                    alive & ~is_top,
                    jax.random.uniform(
                        jax.random.fold_in(w_or_key, i), (local_n,)),
                    jnp.inf)
                cut = jnp.sort(u)[other_l - 1]
                # alive/~is_top guard: on a degenerate shard (all padding,
                # or fewer rest-rows than other_l) cut is inf and a bare
                # u <= cut would select pad and top rows
                is_other = (u <= cut) & alive & ~is_top
                mult = ((realf - top_l.astype(jnp.float32))
                        / jnp.maximum(other_l, 1).astype(jnp.float32))
                amp = jnp.where(is_other, mult, 1.0)
                g_l = g_l * amp
                h_l = h_l * amp
                w_l = (is_top | is_other).astype(jnp.float32)
            elif with_bag_key:
                if bag_on:
                    # per-shard exact-count bagging over the shard's real
                    # rows (reference bags each machine's local partition,
                    # gbdt.cpp:210-276 under num_machines > 1)
                    u = jnp.where(
                        alive,
                        jax.random.uniform(
                            jax.random.fold_in(w_or_key, i), (local_n,)),
                        jnp.inf)
                    k_local = jnp.maximum(
                        1, (real.astype(jnp.float32) * frac)
                        .astype(jnp.int32))
                    cut = jnp.sort(u)[k_local - 1]
                    # the alive guard matters on an all-padding shard
                    # (real == 0): u is all-inf there and (u <= cut) would
                    # otherwise select every pad row
                    w_l = ((u <= cut) & alive).astype(jnp.float32)
                else:
                    w_l = alive.astype(jnp.float32)
            else:
                w_l = w_or_key * alive.astype(jnp.float32)
            rec, rec_cat, leaf_id, ks, tot = grow_core(
                cp_l, cr_l, g_l, h_l, w_l, base_mask, *meta, key,
                axis_name="data", **statics)
            # rec_cat (the categorical winners' left-bin masks) is
            # replicated: psum mode scans identical reduced histograms
            # everywhere, scatter mode transports the mask through the
            # candidate election. Placeholder zeros keep the output
            # pytree uniform when the dataset has no categoricals.
            if rec_cat is None:
                rec_cat = jnp.zeros((rec.shape[0], 1), jnp.float32)
            return rec, rec_cat, leaf_id, ks, tot

        w_spec = P() if with_bag_key else P("data")
        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P("data", None), P("data", None), P("data"),
                      P("data"), w_spec, P(), P()),
            out_specs=(P(), P(), P("data"), P(), P()), check_vma=False)

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              bag_indices: Optional[np.ndarray] = None,
              iter_seed: int = 0) -> Tree:
        cfg = self.config
        n = self.dataset.num_data
        pad = self.n_pad - n
        if bag_indices is None:
            wv = np.ones(self.n_pad, dtype=np.float32)
            if pad:
                wv[n:] = 0.0
            self._bag_mask_host = None
        else:
            wv = np.zeros(self.n_pad, dtype=np.float32)
            wv[bag_indices] = 1.0
            self._bag_mask_host = wv[:n] > 0
        rng = np.random.RandomState(
            (cfg.feature_fraction_seed + iter_seed) % (2**31 - 1))
        base_mask = jnp.asarray(self._feature_mask(rng))
        key = jax.random.PRNGKey(iter_seed)
        if self._shard is not None:
            return self._train_streamed(grad, hess, wv, base_mask, key)
        if self._tree_w_fn is None:
            fn = self._sharded_tree_fn(with_bag_key=False)
            nn, npad = n, self.n_pad

            @jax.jit
            def run(cp, cr, g, h, w, mask, k):
                g = jnp.pad(g, (0, npad - nn))
                h = jnp.pad(h, (0, npad - nn))
                rec, rec_cat, leaf_id, ks, tot = fn(cp, cr, g, h, w, mask, k)
                return rec, rec_cat, leaf_id[:nn], ks, tot
            self._tree_w_fn = run
        rec, rec_cat, leaf_id, n_splits, _ = self._tree_w_fn(
            self.codes_pack, self.codes_row, grad, hess, jnp.asarray(wv),
            base_mask, key)
        self.last_leaf_id = leaf_id
        self._leaf_id_host = None
        if self._has_cat:
            rec_h, rec_cat_h, k = jax.device_get((rec, rec_cat, n_splits))
        else:
            rec_h, k = jax.device_get((rec, n_splits))
            rec_cat_h = None
        k = int(k)
        if k == 0:
            log.warning("No further splits with positive gain")
        return self.replay_tree(rec_h, k, rec_cat_h)

    # -- streamed (out-of-core) data-parallel path ---------------------
    def _host_rows(self, arr, lo: int, hi: int) -> np.ndarray:
        """np.float32 rows [lo:hi) of an (N,) row vector that is either
        process-local or a global row-sharded jax array (the score-
        derived gradients after the first distributed iteration). A
        sharded slice must be covered by ONE addressable shard — true by
        construction: the device at mesh position p holds exactly the
        rows position p's working buffer needs."""
        if isinstance(arr, np.ndarray):
            return np.asarray(arr[lo:hi], dtype=np.float32)
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(jax.device_get(arr))[lo:hi].astype(
                np.float32, copy=False)
        for s in arr.addressable_shards:
            sl = s.index[0]
            start = sl.start or 0
            stop = arr.shape[0] if sl.stop is None else sl.stop
            if start <= lo and hi <= stop:
                return np.asarray(jax.device_get(s.data))[
                    lo - start:hi - start].astype(np.float32, copy=False)
        from ..utils.log import LightGBMError
        raise LightGBMError(
            f"streamed data-parallel assembly: rows {lo}:{hi} are not "
            "addressable on this process (the gradient sharding does "
            "not match the 'data' mesh row blocks)")

    def _dp_stream_init(self, local_n: int, d_cols: int, cw: int):
        """Per-device jit building one (local_n + CH, d_cols) u32
        working buffer: gh words [g*w, h*w, w] + LOCAL row ids at column
        cw, code section zeroed (chunk writes fill it). Float layout
        only — create_tree_learner rejects quant x stream x data."""
        jkey = ("dp_init", local_n, d_cols, cw)
        fn = self._stream_jits.get(jkey)
        if fn is None:
            CH = int(self.chunk_rows)

            def init(g, h, w):
                gh_u = jax.lax.bitcast_convert_type(
                    jnp.stack([g * w, h * w, w], axis=1), jnp.uint32)
                ids = jnp.arange(local_n, dtype=jnp.uint32)[:, None]
                tail = jnp.concatenate([gh_u, ids], axis=1)
                buf = jnp.zeros((local_n + CH, d_cols), jnp.uint32)
                return jax.lax.dynamic_update_slice(
                    buf, tail, (jnp.int32(0), jnp.int32(cw)))

            fn = jax.jit(init)
            self._stream_jits[jkey] = fn
        return fn

    def _streamed_tree_fn(self):
        """jitted shard_map'd prebuilt chunk-core program: each shard's
        buffer already holds its own rows (codes + gh words), per-leaf
        histogram psums over 'data' are the only cross-shard exchange."""
        fn = getattr(self, "_stream_dp_fn", None)
        if fn is not None:
            return fn
        from ..models.device_learner import grow_tree_chunk_core
        statics = dict(self._grow_statics())
        statics["scatter_cols"] = 0   # prebuilt runs the plain psum lane
        statics["data_prebuilt"] = True
        meta = self._meta
        nn = self.dataset.num_data

        def local(buf_l, g_l, h_l, w_l, base_mask, key):
            dummy_row = jnp.zeros((1, 1), jnp.uint8)
            rec, rec_cat, leaf_id, ks, tot = grow_tree_chunk_core(
                buf_l, dummy_row, g_l, h_l, w_l, base_mask, *meta, key,
                axis_name="data", **statics)
            if rec_cat is None:
                rec_cat = jnp.zeros((rec.shape[0], 1), jnp.float32)
            return rec, rec_cat, leaf_id, ks, tot

        smapped = shard_map(
            local, mesh=self.mesh,
            in_specs=(P("data", None), P("data"), P("data"), P("data"),
                      P(), P()),
            out_specs=(P(), P(), P("data"), P(), P()), check_vma=False)

        @jax.jit
        def run(data0, g, h, w, mask, k):
            rec, rec_cat, leaf_id, ks, tot = smapped(
                data0, g, h, w, mask, k)
            return rec, rec_cat, leaf_id[:nn], ks, tot

        self._stream_dp_fn = run
        return run

    def _train_streamed(self, grad, hess, wv, base_mask, key):
        """stream_mode=chunked x data-parallel: every local mesh device
        gets its own (local_n + CH, d_cols) working buffer assembled
        from the host wire store (with dist_shard_mode=rows the local
        block IS everything this host stores), the per-device buffers
        join into one row-sharded global array, and the chunk core runs
        prebuilt under shard_map. The code matrix and the float rows
        never cross hosts — per-leaf histogram psums are the only
        cross-host bytes."""
        from ..utils.log import LightGBMError
        shard = self._shard
        n = self.dataset.num_data
        local_n = self.local_n
        CH = int(self.chunk_rows)
        cw = int(shard.code_words)
        d_cols = cw + 3 + 1           # codes | g*w, h*w, w | row id
        row_shard = getattr(self.dataset, "row_shard", None)
        shard_begin = int(row_shard[0]) if row_shard is not None else 0
        mine = self._local_mesh_positions()
        shard.track_buffer("data0",
                           len(mine) * (local_n + CH) * d_cols * 4)
        bufs, g_parts, h_parts, w_parts = [], [], [], []
        for p, dev in mine:
            lo = p * local_n
            hi = min(lo + local_n, n)
            rows = max(hi - lo, 0)
            gp = np.zeros(local_n, np.float32)
            hp = np.zeros(local_n, np.float32)
            if rows:
                gp[:rows] = self._host_rows(grad, lo, hi)
                hp[:rows] = self._host_rows(hess, lo, hi)
            wp = np.asarray(wv[lo:lo + local_n], dtype=np.float32)
            gj = jax.device_put(jnp.asarray(gp), dev)
            hj = jax.device_put(jnp.asarray(hp), dev)
            wj = jax.device_put(jnp.asarray(wp), dev)
            buf = self._dp_stream_init(local_n, d_cols, cw)(gj, hj, wj)
            if rows:
                wire_lo = lo - shard_begin
                if wire_lo < 0 or wire_lo + rows > shard.num_rows:
                    raise LightGBMError(
                        f"streamed assembly: mesh position {p} needs "
                        f"global rows {lo}:{hi} but this host's wire "
                        f"store holds rows {shard_begin}:"
                        f"{shard_begin + shard.num_rows} — re-ingest "
                        "(ingest.reshard) after any world-size change")
                for s, cnt, dv in shard.iter_chunks(
                        row_ids=np.arange(wire_lo, wire_lo + rows),
                        device=dev):
                    buf = self._stream_write(buf, dv, s)
            bufs.append(buf)
            g_parts.append(gj)
            h_parts.append(hj)
            w_parts.append(wj)
        rsh = NamedSharding(self.mesh, P("data", None))
        vsh = NamedSharding(self.mesh, P("data"))
        mk = jax.make_array_from_single_device_arrays
        data0 = mk((self.shards * (local_n + CH), d_cols), rsh, bufs)
        gg = mk((self.n_pad,), vsh, g_parts)
        hh = mk((self.n_pad,), vsh, h_parts)
        ww = mk((self.n_pad,), vsh, w_parts)
        try:
            rec, rec_cat, leaf_id, n_splits, _ = self._streamed_tree_fn()(
                data0, gg, hh, ww, base_mask, key)
        finally:
            shard.release_buffer("data0")
        self.last_leaf_id = leaf_id
        self._leaf_id_host = None
        if self._has_cat:
            rec_h, rec_cat_h, k = jax.device_get((rec, rec_cat, n_splits))
        else:
            rec_h, k = jax.device_get((rec, n_splits))
            rec_cat_h = None
        k = int(k)
        if k == 0:
            log.warning("No further splits with positive gain")
        return self.replay_tree(rec_h, k, rec_cat_h)

    # ------------------------------------------------------------------
    def make_fused_step(self, objective, goss=None, bagging=True):
        """Fused sharded boosting iteration (see DeviceTreeLearner
        .make_fused_step): gradients auto-shard over the score, the tree
        grows under shard_map with per-split psum, the score update is
        elementwise over the sharded leaf assignment."""
        from ..models.device_learner import leaf_values_from_rec
        n = self.dataset.num_data
        npad = self.n_pad
        L = int(self.config.num_leaves)
        # fused GOSS runs per shard (local top-k + amplification, the
        # reference's per-machine BaggingHelper semantics); rates come
        # from config, counts are derived from each shard's real rows
        goss_rates = None
        if goss is not None:
            goss_rates = (float(self.config.top_rate),
                          float(self.config.other_rate))
        fn = self._sharded_tree_fn(with_bag_key=True,
                                   allow_bagging=bagging,
                                   goss=goss_rates)

        has_cat = self._has_cat

        obj_keys = objective_buffer_names(objective)

        @jax.jit
        def step_impl(codes_pack, codes_row, obj_bufs, score_row,
                      base_mask, tree_key, bag_key, shrinkage):
            # codes + objective buffers as args, not closure constants —
            # see the serial make_fused_step note (compile payload)
            with swapped_attrs(objective, obj_keys, obj_bufs):
                g, h = objective.get_gradients(score_row)
            g = jnp.pad(g, (0, npad - n))
            h = jnp.pad(h, (0, npad - n))
            rec, rec_cat, leaf_id_pad, k, _ = fn(
                codes_pack, codes_row,
                g, h, bag_key, base_mask, tree_key)
            leaf_id = leaf_id_pad[:n]
            lv = leaf_values_from_rec(rec, k, L)
            delta = jnp.take(lv, jnp.clip(leaf_id, 0, L - 1)) * shrinkage
            new_score = score_row + delta
            # in-program sentry reduction (see the serial step contract)
            finite = jnp.all(jnp.isfinite(new_score))
            return (new_score, rec, rec_cat if has_cat else None,
                    leaf_id, k, finite)

        def step(score_row, base_mask, tree_key, bag_key, shrinkage):
            obj_bufs = tuple(getattr(objective, k) for k in obj_keys)
            return step_impl(self.codes_pack, self.codes_row, obj_bufs,
                             score_row, base_mask, tree_key, bag_key,
                             shrinkage)

        # contract surface for tests/tools (program-size pinning)
        step.impl = step_impl
        step.obj_keys = obj_keys
        return step


class DeviceVotingParallelTreeLearner(DeviceDataParallelTreeLearner):
    """Whole-tree voting-parallel learner (PV-Tree) on the device: the
    data-parallel shard_map program with per-split two-stage voting —
    local top-k election by locally-scanned gains, vote psum, and a
    reduction of ONLY the elected 2k features' histograms
    (voting_parallel_tree_learner.cpp:170-260). Communication per split
    is O(2k*B), constant in feature count. Both growth cores carry the
    voting seam (make_voting_search), so LGBM_TPU_STRATEGY=chunk works
    here too."""

    def __init__(self, config: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None):
        super().__init__(config, dataset, mesh)
        self.scatter_cols = 0              # voting replaces the scatter
        self.voting_k = max(1, int(config.top_k))

    def _grow_statics(self):
        d = super()._grow_statics()
        d["voting_k"] = self.voting_k
        return d


class DeviceFeatureParallelTreeLearner(DeviceTreeLearner):
    """Whole-tree feature-parallel learner: rows REPLICATED, columns
    partitioned — each shard builds histograms only for its word-aligned
    column slice (the local slice over all rows IS the global histogram,
    so there is no histogram collective at all) and the best split is
    elected from a (D, 12) all_gather of per-shard candidates — the
    reference FeatureParallelTreeLearner's exact communication shape
    (feature_parallel_tree_learner.cpp:33-76, SyncUpGlobalBestSplit),
    with the entire leaf-wise tree grown inside one shard_map program
    instead of one host round-trip per split."""

    supports_fused_goss = True    # rows replicated: single-chip GOSS

    def __init__(self, config: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None):
        super().__init__(config, dataset,
                         strategy=_sharded_chunk_opt_in(self),
                         device_place=False)
        self.mesh = mesh or make_mesh(axis_name="feature")
        self.shards = int(self.mesh.devices.size)
        cs = padded_shard_cols(self.c_cols, self.shards, self.item_bits)
        self._c_pad = cs * self.shards
        # repack with word-aligned per-shard column capacity; honor the
        # LGBM_TPU_PACK_WORDS A/B lever if it asks for an even wider row
        import os as _os
        pack_words = int(_os.environ.get("LGBM_TPU_PACK_WORDS", "0"))
        env_cols = pack_words * (32 // self.item_bits)
        host_codes = np.asarray(self.codes_row)
        self.codes_pack = jnp.asarray(self.pack_codes(
            host_codes, col_target=max(self._c_pad, env_cols)))
        self.codes_row = jnp.asarray(host_codes)
        self._meta = (self.f_numbins, self.f_missing, self.f_default,
                      self.f_monotone, self.f_penalty, self.f_categorical,
                      self.f_col, self.f_base, self.f_elide, self.hist_idx)
        self._tree_fn = None

    def _grow_statics(self):
        if self.strategy == "chunk":
            from ..utils.envs import flag
            return dict(c_cols=self.c_cols, item_bits=self.item_bits,
                        chunk_rows=self.chunk_rows,
                        fuse_hist=not flag("LGBM_TPU_CHUNK_NO_FUSE_HIST"),
                        feature_shards=self.shards,
                        partition=self._partition_mode,
                        **self._statics())
        return dict(c_cols=self.c_cols, item_bits=self.item_bits,
                    pool_slots=self.pool_slots,
                    feature_shards=self.shards,
                    window_step=self.window_step,
                    partition=self._partition_mode,
                    **self._statics())

    def _sharded_tree_fn(self):
        from ..models.device_learner import (grow_tree_chunk_core,
                                             grow_tree_compact_core)
        grow_core = (grow_tree_chunk_core if self.strategy == "chunk"
                     else grow_tree_compact_core)
        statics = self._grow_statics()
        meta = self._meta

        def local(cp, cr, g, h, w, base_mask, key):
            rec, rec_cat, leaf_id, ks, tot = grow_core(
                cp, cr, g, h, w, base_mask, *meta, key,
                axis_name="feature", **statics)
            # replicated: the elected candidate row carries the winning
            # categorical mask (see _elect in grow_tree_compact_core)
            if rec_cat is None:
                rec_cat = jnp.zeros((rec.shape[0], 1), jnp.float32)
            return rec, rec_cat, leaf_id, ks, tot

        reps = (P(),) * 7
        return shard_map(local, mesh=self.mesh, in_specs=reps,
                         out_specs=(P(), P(), P(), P(), P()),
                         check_vma=False)

    def _run_grow(self, grad, hess, w, base_mask, key):
        if self._tree_fn is None:
            self._tree_fn = jax.jit(self._sharded_tree_fn())
        rec, rec_cat, leaf_id, k, tot = self._tree_fn(
            self.codes_pack, self.codes_row, grad, hess, w, base_mask, key)
        return (rec, rec_cat if self._has_cat else None, leaf_id, k, tot)

    def make_fused_step(self, objective, goss=None, bagging=True):
        """Fused boosting iteration over the feature mesh: one sharded
        whole-tree program per iteration (rows replicated, columns
        sliced), same contract as DeviceTreeLearner.make_fused_step.

        goss = (top_k, other_k, multiply): rows are REPLICATED on every
        shard, so GOSS is the single-chip in-program sampling verbatim
        (global exact top_k by |g*h| + uniform rest + amplification,
        reference src/boosting/goss.hpp) — computed once in the outer
        jit and handed to the shard_map replicated."""
        from ..models.device_learner import leaf_values_from_rec
        cfg = self.config
        n = self.dataset.num_data
        L = int(cfg.num_leaves)
        if goss is not None:
            top_k, other_k, multiply = goss
            bag_on = False
        else:
            bag_on = (bagging and cfg.bagging_freq > 0
                      and cfg.bagging_fraction < 1.0)
            bag_k = max(1, int(n * cfg.bagging_fraction))
        fn = self._sharded_tree_fn()

        has_cat = self._has_cat

        obj_keys = objective_buffer_names(objective)

        @jax.jit
        def step_impl(codes_pack, codes_row, obj_bufs, score_row,
                      base_mask, tree_key, bag_key, shrinkage):
            # codes + objective buffers as args, not closure constants —
            # see the serial make_fused_step note (compile payload)
            with swapped_attrs(objective, obj_keys, obj_bufs):
                g, h = objective.get_gradients(score_row)
            if goss is not None:
                from ..models.device_learner import goss_sample
                g, h, w, _, _ = goss_sample(
                    g, h, bag_key, n, top_k, other_k, multiply)
            elif bag_on:
                from ..models.device_learner import exact_k_bag_weights
                w = exact_k_bag_weights(bag_key, n, bag_k)
            else:
                w = jnp.ones((n,), jnp.float32)
            rec, rec_cat, leaf_id, k, _ = fn(codes_pack, codes_row,
                                             g, h, w, base_mask, tree_key)
            lv = leaf_values_from_rec(rec, k, L)
            delta = jnp.take(lv, jnp.clip(leaf_id, 0, L - 1)) * shrinkage
            new_score = score_row + delta
            # in-program sentry reduction (see the serial step contract)
            finite = jnp.all(jnp.isfinite(new_score))
            return (new_score, rec, rec_cat if has_cat else None,
                    leaf_id, k, finite)

        def step(score_row, base_mask, tree_key, bag_key, shrinkage):
            obj_bufs = tuple(getattr(objective, k) for k in obj_keys)
            return step_impl(self.codes_pack, self.codes_row, obj_bufs,
                             score_row, base_mask, tree_key, bag_key,
                             shrinkage)

        # contract surface for tests/tools (program-size pinning)
        step.impl = step_impl
        step.obj_keys = obj_keys
        return step


def create_tree_learner(config: Config, dataset: Dataset,
                        mesh: Optional[Mesh] = None):
    """Factory: {serial, feature, data, voting} (reference:
    src/treelearner/tree_learner.cpp:13-36 CreateTreeLearner). Each mode
    prefers its whole-tree-on-device variant (the reference composes device
    x parallelism the same way, tree_learner.cpp:24-33 GPU templates) and
    falls back to the host-loop learner for unsupported configs."""
    import os
    from ..models.device_learner import DeviceTreeLearner
    from ..utils.log import LightGBMError
    host_only = os.environ.get("LGBM_TPU_HOST_LEARNER", "0") == "1"
    name = config.tree_learner
    stream = str(getattr(config, "stream_mode", "off") or "off")
    rows_sharded = getattr(dataset, "row_shard", None) is not None
    stream_matrix = (
        "supported combinations: stream_mode=chunked|goss with "
        "tree_learner=serial (any quant_bits), and stream_mode=chunked "
        "with tree_learner=data (float path, quant_bits=0)")
    if rows_sharded and name not in ("data", "data_parallel"):
        raise LightGBMError(
            "this dataset is row-sharded (dist_shard_mode=rows): each "
            "host holds only its own row block, which only tree_learner"
            "=data can train on (per-leaf histograms are the cross-host "
            f"exchange); tree_learner={name} would silently train on a "
            "fraction of the data — use tree_learner=data or "
            "dist_shard_mode=replicated")
    if rows_sharded and host_only:
        raise LightGBMError(
            "dist_shard_mode=rows is incompatible with "
            "LGBM_TPU_HOST_LEARNER=1: the host-loop data-parallel "
            "learner needs the full binned matrix on every rank")
    if stream != "off":
        # streaming exists in the serial device chunk learner and (for
        # the float chunked mode) the device data-parallel learner; a
        # silent fallback to a resident learner would defeat the whole
        # point of the mode, so misconfigurations fail loudly
        if name in ("data", "data_parallel"):
            if stream != "chunked":
                raise LightGBMError(
                    f"stream_mode={stream} with tree_learner={name} is "
                    "not supported: the GOSS working-set compaction is "
                    "a single-program optimisation with no sharded "
                    f"counterpart; {stream_matrix}")
            if config.quant_bits:
                raise LightGBMError(
                    f"quant_bits={config.quant_bits} with stream_mode="
                    f"{stream} and tree_learner={name} is not "
                    "supported: the streamed assembly derives "
                    "quantization scales from local gradient maxima "
                    "while the distributed resident core psums them "
                    "globally, so the two would grow different trees; "
                    f"set quant_bits=0 or stream_mode=off; "
                    f"{stream_matrix}")
            if host_only:
                raise LightGBMError(
                    f"stream_mode={stream} is incompatible with "
                    "LGBM_TPU_HOST_LEARNER=1 (the host-loop learners "
                    "have no streaming path)")
            if not DeviceTreeLearner.supports(config, dataset,
                                              strategy="chunk"):
                raise LightGBMError(
                    f"stream_mode={stream} with tree_learner={name} "
                    "needs the device chunk learner but this config is "
                    "unsupported by it (forced splits / CEGB / pool "
                    "budget); fix the config or set stream_mode=off")
            return DeviceDataParallelTreeLearner(config, dataset, mesh)
        if name not in ("serial",):
            raise LightGBMError(
                f"stream_mode={stream} with tree_learner={name} has no "
                "streaming path (the feature/voting learners shard or "
                "elect by feature and need resident codes); "
                f"{stream_matrix}")
        if host_only:
            raise LightGBMError(
                f"stream_mode={stream} is incompatible with "
                "LGBM_TPU_HOST_LEARNER=1 (the host-loop learner has no "
                "streaming path)")
        if not DeviceTreeLearner.supports(config, dataset):
            raise LightGBMError(
                f"stream_mode={stream} needs the device chunk learner "
                "but this config is unsupported by it (forced splits / "
                "CEGB / pool budget); fix the config or set "
                "stream_mode=off")
        return DeviceTreeLearner(config, dataset)
    if name in ("serial",):
        if not host_only and DeviceTreeLearner.supports(config, dataset):
            return DeviceTreeLearner(config, dataset)
        return SerialTreeLearner(config, dataset)
    if name in ("feature", "feature_parallel"):
        # whole-tree device FP needs the identity feature->column mapping
        # (no EFB bundles), no by-node sampling and the float row layout
        # (quantized packed rows gate to serial/DP; the host FP learner
        # below carries the quantized pipeline via GSPMD shardings)
        if (not host_only
                and dataset.bundle_arrays() is None
                and not config.quant_bits
                and not (0.0 < config.feature_fraction_bynode < 1.0)
                and DeviceTreeLearner.supports(config, dataset,
                                               strategy="compact")):
            return DeviceFeatureParallelTreeLearner(config, dataset, mesh)
        return FeatureParallelTreeLearner(config, dataset, mesh)
    if name in ("data", "data_parallel"):
        # the DP device learner always runs the compact strategy; check
        # the learner that will actually be built
        if not host_only and DeviceTreeLearner.supports(
                config, dataset, strategy="compact"):
            return DeviceDataParallelTreeLearner(config, dataset, mesh)
        if rows_sharded:
            raise LightGBMError(
                "dist_shard_mode=rows needs the device data-parallel "
                "learner, but this config is unsupported by it (forced "
                "splits / CEGB / pool budget); fix the config or use "
                "dist_shard_mode=replicated")
        return DataParallelTreeLearner(config, dataset, mesh)
    if name in ("voting", "voting_parallel"):
        # device PV-Tree needs the identity mapping and a feature count
        # the 2k election actually reduces
        n_shards = (mesh.devices.size if mesh is not None
                    else len(jax.devices()))
        if (not host_only
                and dataset.bundle_arrays() is None
                and not config.quant_bits
                and not (0.0 < config.feature_fraction_bynode < 1.0)
                and dataset.num_features > 2 * max(1, int(config.top_k))
                and n_shards > 1
                and DeviceTreeLearner.supports(config, dataset,
                                               strategy="compact")):
            return DeviceVotingParallelTreeLearner(config, dataset, mesh)
        return VotingParallelTreeLearner(config, dataset, mesh)
    log.fatal("Unknown tree learner %s", name)
