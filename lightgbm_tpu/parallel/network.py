"""Distributed process-group bootstrap (thin delegate).

Replaces the reference's socket/MPI transport stack
(reference: src/network/linkers_socket.cpp full-mesh TCP handshake,
network.cpp Bruck/recursive-halving collectives). On TPU the transport
IS the platform: `jax.distributed.initialize` joins the multi-host
ICI/DCN domain and all collectives are XLA ops emitted inside jitted
programs (see parallel/*.py) — there is no userspace collective code.

This module keeps the reference's *bootstrap* API surface
(`machines=host:port,...`, Booster.set_network) for CLI/Python driver
compatibility; the actual bring-up, env overrides, mesh, and barrier
live in `lightgbm_tpu.distributed.bootstrap`. The one extra state kept
here is the externally-injected identity (`init_external`) for hosts
like Spark/Dask that own the process group themselves.
"""
from __future__ import annotations

from ..distributed import bootstrap
from ..utils import log

_external = {"set": False, "num_machines": 1, "rank": 0}


def init_from_params(machines: str, local_listen_port: int = 12400,
                     num_machines: int = 1, machine_rank: int = -1,
                     coordinator: str = "", supervise: bool = False) -> None:
    """machines='ip1:port1,ip2:port2,...' -> jax.distributed.initialize.

    Rank = `machine_rank` when >= 0, else the index of our address in
    the machine list (the reference derives rank the same way,
    linkers_socket.cpp:80); coordinator defaults to entry 0. Env trio
    LGBM_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID wins over all of it.
    ``supervise`` (from ``dist_heartbeat_ms > 0``) selects the
    supervised bring-up so rank liveness is owned by
    distributed/supervisor.py instead of the platform's abort path."""
    bootstrap.initialize_from_config(
        machines, local_listen_port=local_listen_port,
        num_machines=num_machines, machine_rank=machine_rank,
        coordinator=coordinator, supervise=supervise)


def num_machines() -> int:
    if _external["set"]:
        return _external["num_machines"]
    return bootstrap.process_count()


def rank() -> int:
    if _external["set"]:
        return _external["rank"]
    return bootstrap.rank()


def init_external(num_machines: int, rank: int) -> None:
    """reference: LGBM_NetworkInitWithFunctions (c_api.h:1018) — hosts like
    Spark/Dask inject collectives. Collectives here are XLA ops over the
    mesh, so only the (num_machines, rank) identity is recorded for the
    host-side coordination paths (rank-partitioned loading, logging)."""
    _external["set"] = True
    _external["num_machines"] = int(num_machines)
    _external["rank"] = int(rank)
    log.info("Network initialized externally: rank %d/%d", rank,
             num_machines)


def free() -> None:
    _external["set"] = False
    _external["num_machines"] = 1
    _external["rank"] = 0
    bootstrap.shutdown()
