"""Distributed process-group bootstrap.

Replaces the reference's socket/MPI transport stack
(reference: src/network/linkers_socket.cpp full-mesh TCP handshake,
network.cpp Bruck/recursive-halving collectives). On TPU the transport IS the
platform: `jax.distributed.initialize` joins the multi-host ICI/DCN domain
and all collectives are XLA ops emitted inside jitted programs
(see parallel/*.py) — there is no userspace collective code to run.

This module keeps the reference's *bootstrap* API surface
(`machines=host:port,...`, Booster.set_network) mapped onto
jax.distributed, so CLI/Python driver code ports unchanged.
"""
from __future__ import annotations

from typing import Optional

from ..utils import log

_initialized = False
_num_machines = 1
_rank = 0


def init_from_params(machines: str, local_listen_port: int = 12400,
                     num_machines: int = 1) -> None:
    """machines='ip1:port1,ip2:port2,...' -> jax.distributed.initialize.

    Rank = index of our address in the machine list, coordinator = entry 0
    (the reference derives rank the same way, linkers_socket.cpp:80)."""
    global _initialized, _num_machines, _rank
    if isinstance(machines, (list, tuple)):
        machines = ",".join(machines)
    entries = [m.strip() for m in str(machines).split(",") if m.strip()]
    if len(entries) <= 1:
        _num_machines = 1
        return
    import socket
    my_names = {socket.gethostname(), "localhost", "127.0.0.1"}
    try:
        my_names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    rank = None
    for i, e in enumerate(entries):
        host = e.split(":")[0]
        if host in my_names:
            rank = i
            break
    if rank is None:
        log.fatal("Could not find local machine in machine list: %s", machines)
    import jax
    from ..resilience import faults
    # bootstrap is the other host-collective boundary: joining the
    # process group retries transient failures with the same bounded
    # backoff as the in-training collectives (resilience/faults.py)
    faults.run_collective(
        lambda: jax.distributed.initialize(
            coordinator_address=entries[0],
            num_processes=len(entries), process_id=rank),
        site="bootstrap")
    _initialized = True
    _num_machines = len(entries)
    _rank = rank
    log.info("jax.distributed initialized: rank %d of %d", rank, len(entries))


def num_machines() -> int:
    return _num_machines


def rank() -> int:
    return _rank


def init_external(num_machines: int, rank: int) -> None:
    """reference: LGBM_NetworkInitWithFunctions (c_api.h:1018) — hosts like
    Spark/Dask inject collectives. Collectives here are XLA ops over the
    mesh, so only the (num_machines, rank) identity is recorded for the
    host-side coordination paths (rank-partitioned loading, logging)."""
    global _initialized, _num_machines, _rank
    _initialized = True
    _num_machines = int(num_machines)
    _rank = int(rank)
    log.info("Network initialized externally: rank %d/%d", _rank,
             _num_machines)


def free() -> None:
    global _initialized, _num_machines, _rank
    if _initialized:
        import jax
        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover
            pass
    _initialized = False
    _num_machines = 1
    _rank = 0
