"""Fleet manifest: one versioned deploy artifact N replicas converge on.

The PR 11 canary router spans one process; the fleet needs its state
machine to span N of them without an external control plane. The
mechanism is a small versioned JSON file:

    {"format": "lgbm_tpu_fleet_manifest", "version": 1, "rev": 7,
     "models":  {"v1": "/models/m1.txt", "v2": "/models/m2.txt"},
     "stable":  "v1",
     "canary":  {"version": "v2", "weight": 0.1, "shadow": false},
     "replicas": [{"url": "http://h0:8080", "weight": 1.0}, ...],
     "updated_unix": 1722... }

* ``rev`` is a monotonically increasing write counter — followers
  apply a manifest exactly once per rev, so polling is idempotent.
* ``models`` maps version tags to model files; followers load tags
  they don't have yet (warm-before-publish via ModelRegistry.load).
* ``stable``/``canary`` mirror the router state machine. A follower
  whose current canary equals the manifest's ``stable`` *promotes* —
  that is how one replica's counter-gated promotion propagates to the
  whole fleet, each replica recording the transition in its own audit
  log, no restarts.
* ``replicas`` is the gateway's serving set + selection weights.

Writers: `ManifestPublisher` — seeded by the deploy tooling
(`tools/rollout.py --demo`) and bound to `CanaryRouter.on_transition`
on the deciding replica, so promote/demote decisions flow *back into*
the artifact. Writes are atomic (temp file + os.replace) and skipped
when the computed state is unchanged, which is what keeps a
publisher+follower replica from ping-ponging revs.

Readers: `ManifestFollower` — polls the file and converges a
ServingApp onto it (load missing models, stable/deploy/promote/demote
as diffs dictate). Every apply bumps ``manifest_applies`` /
``manifest_rev`` and emits a ``manifest_apply`` event.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..utils import log

__all__ = ["MANIFEST_FORMAT", "new_manifest", "load_manifest",
           "save_manifest", "ManifestPublisher", "ManifestFollower"]

MANIFEST_FORMAT = "lgbm_tpu_fleet_manifest"


def new_manifest(models: Optional[Dict[str, str]] = None,
                 stable: Optional[str] = None,
                 canary: Optional[dict] = None,
                 replicas: Optional[List[dict]] = None) -> dict:
    return {"format": MANIFEST_FORMAT, "version": 1, "rev": 0,
            "models": dict(models or {}), "stable": stable,
            "canary": canary, "replicas": list(replicas or []),
            "updated_unix": time.time()}


def load_manifest(path: str) -> Optional[dict]:
    """None (not an error) on missing/unreadable/foreign files — a
    follower keeps polling through a mid-write race or an empty path.

    A file that READS but does not PARSE is a different animal: our own
    writes are atomic (save_manifest), so truncated JSON means a
    non-atomic writer or a torn copy landed in the artifact's place.
    Still None — the follower keeps the previously applied revision,
    which is the safe state — but counted (``manifest_torn``) and
    evented so the fleet operator sees the corruption instead of a
    silently frozen rollout."""
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return None
    try:
        m = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        telem_counters.incr("manifest_torn")
        telem_events.emit("manifest_torn", path=str(path),
                          size_bytes=len(raw))
        log.warning("manifest: %s is torn/unparseable (%d bytes); "
                    "keeping the previously applied revision", path,
                    len(raw))
        return None
    if not isinstance(m, dict) or m.get("format") != MANIFEST_FORMAT:
        return None
    return m


def save_manifest(manifest: dict, path: str) -> str:
    """Atomic publish: temp file in the same directory + os.replace, so
    a poll never reads a torn manifest."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".manifest_", dir=directory)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, sort_keys=True, indent=1)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


class ManifestPublisher:
    """Single-writer side: read-modify-write with rev bump, bound to a
    router's `on_transition` so canary decisions become fleet state."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def seed(self, models: Dict[str, str], stable: Optional[str] = None,
             replicas: Optional[List[dict]] = None) -> dict:
        """Create/overwrite the artifact (the deploy tool's one write)."""
        manifest = new_manifest(models=models, stable=stable,
                                replicas=replicas)
        manifest["rev"] = 1
        with self._lock:
            save_manifest(manifest, self.path)
        telem_counters.incr("manifest_publishes")
        return manifest

    def update(self, fn) -> Optional[dict]:
        """Apply `fn(manifest)` (mutating in place); bump rev and write
        only when the state actually changed — idempotent updates don't
        spin follower revs."""
        with self._lock:
            manifest = load_manifest(self.path)
            if manifest is None:
                manifest = new_manifest()
            before = json.dumps({k: v for k, v in manifest.items()
                                 if k not in ("rev", "updated_unix")},
                                sort_keys=True)
            fn(manifest)
            after = json.dumps({k: v for k, v in manifest.items()
                                if k not in ("rev", "updated_unix")},
                               sort_keys=True)
            if after == before:
                return None
            manifest["rev"] = int(manifest.get("rev", 0)) + 1
            manifest["updated_unix"] = time.time()
            save_manifest(manifest, self.path)
        telem_counters.incr("manifest_publishes")
        log.info("manifest: published rev %d (stable=%s canary=%s)",
                 manifest["rev"], manifest.get("stable"),
                 (manifest.get("canary") or {}).get("version"))
        return manifest

    def add_model(self, version: str, source: str) -> Optional[dict]:
        """Record a model source so followers can load `version` —
        ship the file reference first, then canary it via the router."""
        def _apply(m: dict) -> None:
            m.setdefault("models", {})[version] = str(source)
        return self.update(_apply)

    def bind_router(self, router, registry=None) -> None:
        """Subscribe to the router's transitions. `registry` (when
        given) lets the publisher record model *sources* for versions it
        learns about, so followers can load them."""
        self._registry = registry
        router.on_transition = self.on_transition

    # router hook: action in stable/deploy/promote/demote
    def on_transition(self, action: str, version: str, **detail) -> None:
        def _apply(m: dict) -> None:
            if action == "stable":
                m["stable"] = version
            elif action == "deploy":
                m["canary"] = {"version": version,
                               "weight": float(detail.get("weight", 0.1)),
                               "shadow": bool(detail.get("shadow", False))}
            elif action == "promote":
                m["stable"] = version
                m["canary"] = None
            elif action == "demote":
                m["canary"] = None
        self.update(_apply)


class ManifestFollower:
    """Reader side: poll the artifact, converge a ServingApp onto it.

    Convergence is a diff against the app's *current* router state, so
    applying the same manifest twice is a no-op and a replica that
    already took a transition locally (e.g. the publisher's own
    follower) doesn't repeat it."""

    def __init__(self, app, path: str, poll_s: float = 0.5):
        self.app = app
        self.path = path
        self.poll_s = float(poll_s)
        self._applied_rev = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one convergence step -------------------------------------------
    def poll_once(self) -> bool:
        """Apply the manifest if its rev is new; True when applied."""
        manifest = load_manifest(self.path)
        if manifest is None:
            return False
        rev = int(manifest.get("rev", 0))
        if rev <= self._applied_rev:
            return False
        self._apply(manifest)
        self._applied_rev = rev
        telem_counters.incr("manifest_applies")
        telem_counters.set_gauge("manifest_rev", rev)
        telem_events.emit("manifest_apply", rev=rev,
                          stable=manifest.get("stable"),
                          canary=(manifest.get("canary") or {}
                                  ).get("version"))
        return True

    def _apply(self, manifest: dict) -> None:
        registry, router = self.app.registry, self.app.router
        loaded = {v["version"] for v in registry.versions()}
        for ver, source in (manifest.get("models") or {}).items():
            if ver in loaded:
                continue
            try:
                registry.load(source, version=ver)
            except Exception as exc:   # noqa: BLE001 — converge the rest
                log.warning("manifest: loading %s from %s failed: %s",
                            ver, source, exc)
        stable = manifest.get("stable")
        canary = manifest.get("canary") or None
        if stable:
            if router.canary == stable:
                # the fleet promoted our canary: take the transition
                # locally so this replica's audit log records it
                router.promote(missing_ok=True)
            elif router.stable != stable:
                router.set_stable(stable)
        if canary and canary.get("version") != stable:
            want = canary["version"]
            if router.canary != want:
                if router.canary is not None:
                    router.demote("manifest_replaced", missing_ok=True)
                try:
                    router.deploy(want,
                                  weight=float(canary.get("weight", 0.1)),
                                  shadow=bool(canary.get("shadow", False)))
                except Exception as exc:   # noqa: BLE001
                    log.warning("manifest: deploy %s failed: %s",
                                want, exc)
        elif canary is None and router.canary is not None:
            router.demote("manifest_demote", missing_ok=True)

    # -- polling loop ----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="lgbm-tpu-manifest")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception as exc:   # noqa: BLE001 — keep polling
                log.warning("manifest: poll failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
