"""Multi-model mesh placement: pin model versions to distinct devices.

One process serving a fleet of boosters wants each version's tensors
AND compiled executables resident on its own device — co-locating them
on device 0 (the jax default) serializes every request behind one
queue and makes the predictor cache thrash between ensembles. A
PlacementPlan hands each version a sticky device; the PreparedModel
carries it into `device_put` and into the executable family key, so
two placed versions never contend for the same cache entries.

Assignment is deliberately dumb and predictable:

* explicit — a spec like ``"stable=0,canary=1"`` pins versions to
  device ordinals (the operator's escape hatch);
* round-robin — unassigned versions take the least-loaded device,
  ties broken by ordinal, so N versions over D devices spread evenly
  and a re-loaded version keeps its slot (sticky until `release`).

The plan is a host-side bookkeeping object — it never touches jax
until a device is actually resolved, so it is constructible (and
testable) before any backend is initialized.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils import log

__all__ = ["PlacementPlan", "parse_placement_spec"]


def parse_placement_spec(spec: str) -> Dict[str, int]:
    """``"stable=0,canary=1"`` -> {"stable": 0, "canary": 1}.
    Empty / "auto" -> {} (pure round-robin)."""
    out: Dict[str, int] = {}
    spec = (spec or "").strip()
    if spec in ("", "auto", "round_robin"):
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"placement spec entry {part!r} is not version=ordinal")
        version, ordinal = part.split("=", 1)
        out[version.strip()] = int(ordinal)
    return out


class PlacementPlan:
    """version -> device assignment, sticky and thread-safe."""

    def __init__(self, spec: str = "", devices: Optional[List] = None):
        self._explicit = parse_placement_spec(spec)
        self._devices = devices          # resolved lazily
        self._assigned: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _resolve_devices(self) -> List:
        if self._devices is None:
            import jax
            self._devices = list(jax.devices())
        return self._devices

    # ------------------------------------------------------------------
    def assign(self, version: str):
        """The device for `version`, assigning one if new. Explicit spec
        entries win; otherwise least-loaded round-robin."""
        devices = self._resolve_devices()
        with self._lock:
            if version in self._assigned:
                return devices[self._assigned[version]]
            if version in self._explicit:
                ordinal = self._explicit[version] % len(devices)
            else:
                load = [0] * len(devices)
                for o in self._assigned.values():
                    load[o % len(devices)] += 1
                for o in self._explicit.values():
                    load[o % len(devices)] += 1
                ordinal = min(range(len(devices)), key=lambda i: load[i])
            self._assigned[version] = ordinal
            log.info("placement: version %s -> device %d (%s)",
                     version, ordinal,
                     getattr(devices[ordinal], "platform", "?"))
            return devices[ordinal]

    def device_for(self, version: str):
        """Assigned device or None — never assigns."""
        with self._lock:
            ordinal = self._assigned.get(version)
        if ordinal is None:
            return None
        return self._resolve_devices()[ordinal]

    def release(self, version: str) -> None:
        """Free the slot (version retired) so round-robin rebalances."""
        with self._lock:
            self._assigned.pop(version, None)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._assigned)
