"""Canary/shadow traffic router: weighted split, counter-gated promotion.

PR 9 built the measurement half of canary deployment — per-version
request/error/latency series in ServingStats. This is the missing
half: a router that decides, per request, which model version answers,
and moves versions through the canary state machine on the evidence of
their own counters.

State machine (one stable, at most one canary):

    deploy(v, weight)        stable answers 1-w of traffic, canary w
      |                      (or 0 in shadow mode: canary only sees
      |                      mirrored copies, responses discarded)
      +-- promote            canary becomes stable (auto when its
      |                      counters clear the health gate, or forced)
      +-- demote(reason)     canary dropped (auto on error spike /
                             latency blowout / watchdog fire, or forced)

The split is deterministic, not random: request n goes to the canary
iff ``floor(n*w) > floor((n-1)*w)``, which hits the weight exactly on
every prefix — reproducible in tests and drift-free in production.

Promotion gate (evaluated per request, O(dict reads)):

* at least `min_requests` canary requests since deploy;
* canary error rate <= `max_error_rate`;
* canary p99 <= `p99_ratio` x stable p99 (skipped when the stable has
  no latency history);
* no watchdog fire since deploy (`telemetry.counters` watchdog_fires);
* labeled-feedback quality (when a `serving.feedback.FeedbackStore` is
  attached with `feedback_min_labels > 0`): hold until the canary has
  accrued `feedback_min_labels` labels via `POST /feedback`, then
  demote if its AUC trails the stable's by more than
  `feedback_auc_epsilon` (stable AUC only compared once the stable has
  enough labels of its own — counters prove the canary is not
  *erroring*, labels prove it is not *wrong*).

Demotion fires immediately — before min_requests — on an absolute
error burst (`demote_errors`), a watchdog fire, or (when an SLO
monitor is attached via `slo=`) a fast-window SLO burn on the canary's
own latency/error series: a bleeding canary is cut, not averaged out.

Every transition (stable/deploy/promote/demote) is recorded in a
bounded audit log together with the exact gate snapshot — the counter
deltas and thresholds the decision was made on — queryable via
`audit_snapshot()` (`GET /router/audit` over HTTP) and attached to the
router_promote/router_demote events for `tools/run_report.py`.

Both routed versions are pinned in the predictor cache for as long as
they hold a slot (ModelRegistry.pin_version), so LRU eviction under
multi-model load can never drop an executable that live traffic needs.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..utils import log

__all__ = ["CanaryRouter", "RouterState"]


class RouterState:
    STABLE_ONLY = "stable_only"
    CANARY = "canary"
    SHADOW = "shadow"


class CanaryRouter:
    """Per-request version routing over a ModelRegistry + ServingStats."""

    AUDIT_MAX = 200

    def __init__(self, registry, stats, min_requests: int = 50,
                 max_error_rate: float = 0.02, p99_ratio: float = 3.0,
                 demote_errors: int = 3, slo=None, feedback=None,
                 feedback_min_labels: int = 0,
                 feedback_auc_epsilon: float = 0.02):
        self.registry = registry
        self.stats = stats
        self.min_requests = int(min_requests)
        self.max_error_rate = float(max_error_rate)
        self.p99_ratio = float(p99_ratio)
        self.demote_errors = int(demote_errors)
        self.slo = slo                      # optional serving.slo.SloMonitor
        self.feedback = feedback            # optional FeedbackStore
        self.feedback_min_labels = int(feedback_min_labels)
        self.feedback_auc_epsilon = float(feedback_auc_epsilon)
        self._lock = threading.Lock()
        self._stable: Optional[str] = None
        self._canary: Optional[str] = None
        self._weight = 0.0
        self._shadow = False
        self._route_n = 0
        self._canary_routed = 0
        self._baseline: Dict[str, float] = {}
        self.history: List[dict] = []
        self.audit: List[dict] = []
        self._last_eval: Optional[dict] = None
        # transition hook: callable(action, version, **detail) invoked
        # after every stable/deploy/promote/demote lands (outside the
        # lock). fleet/manifest.py binds the ManifestPublisher here so
        # this router's decisions propagate to every replica.
        self.on_transition = None

    # -- configuration ---------------------------------------------------
    def set_stable(self, version: str) -> None:
        """Install/replace the stable version (pinned against eviction)."""
        with self._lock:
            previous = self._stable
            self._stable = version
            self._audit_locked("stable", version, previous=previous)
        self.registry.pin_version(version)
        if previous and previous != version:
            self.registry.unpin_version(previous)
        telem_events.emit("router_stable", version=version,
                          previous=previous)
        self._notify("stable", version, previous=previous)

    def deploy(self, version: str, weight: float = 0.10,
               shadow: bool = False) -> None:
        """Start canarying `version` at `weight` of traffic (shadow mode
        mirrors instead of splitting). Baselines the canary's counters
        and the process watchdog counter so the gate judges only what
        happens AFTER this deploy."""
        if not (0.0 < weight <= 1.0) and not shadow:
            raise ValueError(f"canary weight {weight} not in (0, 1]")
        self.registry.get(version)          # raises on unknown version
        with self._lock:
            if self._stable is None:
                raise RuntimeError("deploy a stable version first")
            if self._canary is not None:
                raise RuntimeError(
                    f"canary {self._canary!r} already in flight")
            self._canary = version
            self._weight = 0.0 if shadow else float(weight)
            self._shadow = bool(shadow)
            self._route_n = 0
            self._canary_routed = 0
            self._baseline = self._counters_for(version)
            self._baseline["watchdog_fires"] = telem_counters.get(
                "watchdog_fires")
            self._audit_locked("deploy", version, weight=weight,
                               shadow=shadow)
        self.registry.pin_version(version)
        telem_counters.set_gauge("router_canary_weight",
                                 0.0 if shadow else weight)
        telem_events.emit("router_deploy", version=version, weight=weight,
                          shadow=shadow)
        self._notify("deploy", version, weight=weight, shadow=shadow)
        log.info("router: canary %s at %.0f%%%s", version, weight * 100,
                 " (shadow)" if shadow else "")

    # -- routing ---------------------------------------------------------
    def route(self) -> Optional[str]:
        """The version that should answer the next request (None when no
        stable is installed — caller falls back to registry latest)."""
        with self._lock:
            if self._stable is None:
                return None
            if self._canary is None or self._shadow:
                return self._stable
            self._route_n += 1
            n, w = self._route_n, self._weight
            if math.floor(n * w) > math.floor((n - 1) * w):
                self._canary_routed += 1
                return self._canary
            return self._stable

    def shadow_target(self) -> Optional[str]:
        """The version to mirror this request to (None = no mirroring)."""
        with self._lock:
            return self._canary if (self._shadow and self._canary) else None

    @property
    def active(self) -> bool:
        with self._lock:
            return self._stable is not None

    @property
    def stable(self) -> Optional[str]:
        with self._lock:
            return self._stable

    @property
    def canary(self) -> Optional[str]:
        with self._lock:
            return self._canary

    # -- the gate --------------------------------------------------------
    def _counters_for(self, version: str) -> Dict[str, float]:
        snap = self.stats.snapshot()["versions"].get(version) or {}
        return {"requests": snap.get("requests", 0),
                "errors": snap.get("errors", 0)}

    def _p99_ms(self, version: str) -> float:
        snap = self.stats.snapshot()["versions"].get(version) or {}
        lat = snap.get("latency") or {}
        return float(lat.get("p99_ms", 0.0))

    def _gate_snapshot(self, canary: str, stable: Optional[str],
                       baseline: dict) -> dict:
        """The exact evidence one evaluate() decides on: counter deltas
        since deploy, both p99s, the SLO verdict, and the thresholds in
        force. One snapshot per evaluation — the audit log and the
        router_* events carry it verbatim."""
        now = self._counters_for(canary)
        requests = now["requests"] - baseline.get("requests", 0)
        errors = now["errors"] - baseline.get("errors", 0)
        gate = {"canary": canary, "stable": stable,
                "requests": int(requests), "errors": int(errors),
                "error_rate": (round(errors / requests, 6)
                               if requests > 0 else 0.0),
                "canary_p99_ms": round(self._p99_ms(canary), 3),
                "stable_p99_ms": (round(self._p99_ms(stable), 3)
                                  if stable else 0.0),
                "watchdog_fires": int(
                    telem_counters.get("watchdog_fires")
                    - baseline.get("watchdog_fires", 0)),
                "thresholds": {"min_requests": self.min_requests,
                               "max_error_rate": self.max_error_rate,
                               "p99_ratio": self.p99_ratio,
                               "demote_errors": self.demote_errors}}
        if self.slo is not None:
            gate["slo_violation"] = self.slo.version_violation(canary)
        if self._feedback_gated():
            c_auc, c_n = self.feedback.auc(canary)
            s_auc, s_n = self.feedback.auc(stable)
            gate["thresholds"]["feedback_min_labels"] = \
                self.feedback_min_labels
            gate["thresholds"]["feedback_auc_epsilon"] = \
                self.feedback_auc_epsilon
            gate["feedback"] = {
                "canary_labels": c_n, "stable_labels": s_n,
                "canary_auc": (round(c_auc, 6) if c_auc is not None
                               else None),
                "stable_auc": (round(s_auc, 6) if s_auc is not None
                               else None)}
        return gate

    def _feedback_gated(self) -> bool:
        return self.feedback is not None and self.feedback_min_labels > 0

    def evaluate(self) -> str:
        """Apply the state machine once: returns "promoted", "demoted",
        or "hold". Called per request by the serving app (cheap) or on a
        timer by embedders."""
        with self._lock:
            canary = self._canary
            stable = self._stable
            baseline = dict(self._baseline)
        if canary is None:
            return "hold"
        gate = self._gate_snapshot(canary, stable, baseline)

        def _hold() -> str:
            with self._lock:
                self._last_eval = {"result": "hold", "t": time.time(),
                                   "gate": gate}
            return "hold"

        if gate["watchdog_fires"] > 0:
            self.demote("watchdog_fire", missing_ok=True, gate=gate)
            return "demoted"
        requests, errors = gate["requests"], gate["errors"]
        if errors >= self.demote_errors:
            self.demote(f"error_spike ({int(errors)} errors in "
                        f"{int(requests)} requests)", missing_ok=True,
                        gate=gate)
            return "demoted"
        slo_reason = gate.get("slo_violation")
        if slo_reason:
            self.demote(f"slo_burn ({slo_reason})", missing_ok=True,
                        gate=gate)
            return "demoted"
        if requests < self.min_requests:
            return _hold()
        if requests > 0 and errors / requests > self.max_error_rate:
            self.demote(f"error_rate {errors / requests:.3f}",
                        missing_ok=True, gate=gate)
            return "demoted"
        stable_p99 = gate["stable_p99_ms"]
        canary_p99 = gate["canary_p99_ms"]
        if stable_p99 > 0 and canary_p99 > self.p99_ratio * stable_p99:
            self.demote(f"p99 {canary_p99:.1f}ms > {self.p99_ratio:g}x "
                        f"stable {stable_p99:.1f}ms", missing_ok=True,
                        gate=gate)
            return "demoted"
        fb = gate.get("feedback")
        if fb is not None:
            # quality gate: counters above proved the canary answers
            # fast and without erroring; labels prove the answers are
            # RIGHT. Hold (not demote) while labels accrue — absence of
            # evidence is not a regression.
            if fb["canary_labels"] < self.feedback_min_labels:
                return _hold()
            c_auc, s_auc = fb["canary_auc"], fb["stable_auc"]
            if (c_auc is not None and s_auc is not None
                    and fb["stable_labels"] >= self.feedback_min_labels
                    and c_auc < s_auc - self.feedback_auc_epsilon):
                self.demote(
                    f"feedback_auc {c_auc:.3f} < stable {s_auc:.3f} - "
                    f"{self.feedback_auc_epsilon:g}", missing_ok=True,
                    gate=gate)
                return "demoted"
        self.promote(missing_ok=True, gate=gate)
        return "promoted"

    # -- transitions -----------------------------------------------------
    def promote(self, missing_ok: bool = False,
                gate: Optional[dict] = None) -> None:
        """Canary becomes stable; the old stable is unpinned (it stays
        loaded in the registry for instant rollback until unload).
        `missing_ok` is the auto-transition path: concurrent evaluate()
        calls may race to the same verdict, and the loser finds the slot
        already empty — a no-op, not an error. `gate` is the evaluation
        snapshot that justified an auto-promotion (None = forced)."""
        with self._lock:
            canary, old_stable = self._canary, self._stable
            if canary is None:
                if missing_ok:
                    return
                raise RuntimeError("no canary to promote")
            self._stable, self._canary = canary, None
            self._weight, self._shadow = 0.0, False
            self._record_locked("promote", canary, old=old_stable)
            self._audit_locked("promote", canary, old=old_stable,
                               gate=gate)
        if old_stable and old_stable != canary:
            self.registry.unpin_version(old_stable)
        telem_counters.incr("router_promotions")
        telem_counters.set_gauge("router_canary_weight", 0.0)
        telem_events.emit("router_promote", version=canary,
                          previous=old_stable, gate=gate)
        self._notify("promote", canary, previous=old_stable)
        log.info("router: promoted %s (was %s)", canary, old_stable)

    def demote(self, reason: str = "manual", missing_ok: bool = False,
               gate: Optional[dict] = None) -> None:
        """Cut the canary: all traffic back to stable, pin released."""
        with self._lock:
            canary = self._canary
            if canary is None:
                if missing_ok:
                    return
                raise RuntimeError("no canary to demote")
            self._canary = None
            self._weight, self._shadow = 0.0, False
            self._record_locked("demote", canary, reason=reason)
            self._audit_locked("demote", canary, reason=reason, gate=gate)
        self.registry.unpin_version(canary)
        telem_counters.incr("router_demotions")
        telem_counters.set_gauge("router_canary_weight", 0.0)
        telem_events.emit("router_demote", version=canary, reason=reason,
                          gate=gate)
        self._notify("demote", canary, reason=reason)
        log.warning("router: demoted %s (%s)", canary, reason)

    def _notify(self, action: str, version: str, **detail) -> None:
        """Fire the on_transition hook; a failing subscriber must never
        take the routing path down with it."""
        cb = self.on_transition
        if cb is None:
            return
        try:
            cb(action, version, **detail)
        except Exception as exc:   # noqa: BLE001 — hook is advisory
            log.warning("router: on_transition hook failed for %s %s: %s",
                        action, version, exc)

    def audit_note(self, action: str, version: Optional[str] = None,
                   **detail) -> None:
        """Append a non-transition decision to the audit channel — the
        one bounded log for everything that reroutes traffic. The load
        shedder logs brownout level changes here so `GET /router/audit`
        explains shed traffic next to canary transitions."""
        with self._lock:
            self._audit_locked(action, version, **detail)

    def _record_locked(self, action: str, version: str, **detail) -> None:
        self.history.append({"action": action, "version": version,
                             "t": time.time(), **detail})

    def _audit_locked(self, action: str, version: str, **detail) -> None:
        self.audit.append({"action": action, "version": version,
                           "t": time.time(), **detail})
        if len(self.audit) > self.AUDIT_MAX:
            del self.audit[:len(self.audit) - self.AUDIT_MAX]

    # -- introspection ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            state = (RouterState.SHADOW if self._shadow and self._canary
                     else RouterState.CANARY if self._canary
                     else RouterState.STABLE_ONLY)
            return {"state": state, "stable": self._stable,
                    "canary": self._canary, "weight": self._weight,
                    "shadow": self._shadow, "routed": self._route_n,
                    "canary_routed": self._canary_routed,
                    "min_requests": self.min_requests,
                    "max_error_rate": self.max_error_rate,
                    "p99_ratio": self.p99_ratio,
                    "history": list(self.history[-20:])}

    def audit_snapshot(self, limit: int = 100) -> dict:
        """The decision log (GET /router/audit): every recorded
        transition with the gate snapshot it was decided on, plus the
        most recent "hold" evaluation so a stuck canary is explainable
        before any transition happens."""
        with self._lock:
            last = dict(self._last_eval) if self._last_eval else None
            return {"decisions": list(self.audit[-int(limit):]),
                    "last_evaluation": last}
