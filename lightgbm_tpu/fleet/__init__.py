"""Fleet control plane: the layer above `serving/` that runs MANY
models on MANY devices for MANY replicas.

- `export_cache` — persistent compiled-predictor cache: serialized warm
  executables next to the model file, zero-compile process restarts.
- `placement` — multi-model mesh placement: pin model versions to
  distinct devices, no eviction thrash between co-resident boosters.
- `router` — canary/shadow traffic router over the registry's version
  pinning: weighted split, shadow mirroring, counter-gated promotion,
  watchdog-triggered demotion.
- `manifest` — the versioned fleet deploy artifact: replicas poll and
  converge on it, and the router's promote/demote decisions publish
  back into it, so one canary rollout spans N processes.
- `gateway` — stdlib HTTP front over the replica set: deterministic
  weighted selection, health-aware ejection, retry-with-backoff, edge
  feature transforms (raw CSV/JSON in, predictions out).

Rolling-restart tooling that drives this plane lives in
`tools/rollout.py`; the capacity curve tooling in
`tools/serve_storm.py`.
"""
from .export_cache import ExportCache, cache_dir_for_model
from .gateway import (FleetGateway, Replica, make_gateway_server,
                      run_gateway_server)
from .manifest import (ManifestFollower, ManifestPublisher, load_manifest,
                       new_manifest, save_manifest)
from .placement import PlacementPlan
from .router import CanaryRouter, RouterState

__all__ = ["ExportCache", "cache_dir_for_model", "PlacementPlan",
           "CanaryRouter", "RouterState",
           "ManifestFollower", "ManifestPublisher", "load_manifest",
           "new_manifest", "save_manifest",
           "FleetGateway", "Replica", "make_gateway_server",
           "run_gateway_server"]
