"""Fleet control plane: the layer above `serving/` that runs MANY
models on MANY devices for MANY replicas.

- `export_cache` — persistent compiled-predictor cache: serialized warm
  executables next to the model file, zero-compile process restarts.
- `placement` — multi-model mesh placement: pin model versions to
  distinct devices, no eviction thrash between co-resident boosters.
- `router` — canary/shadow traffic router over the registry's version
  pinning: weighted split, shadow mirroring, counter-gated promotion,
  watchdog-triggered demotion.

Rolling-restart tooling that drives this plane lives in
`tools/rollout.py`.
"""
from .export_cache import ExportCache, cache_dir_for_model
from .placement import PlacementPlan
from .router import CanaryRouter, RouterState

__all__ = ["ExportCache", "cache_dir_for_model", "PlacementPlan",
           "CanaryRouter", "RouterState"]
