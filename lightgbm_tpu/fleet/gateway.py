"""Fleet gateway: one HTTP front over N replica servers (stdlib-only).

The layer the ROADMAP's "millions of users" story was missing: clients
talk to ONE endpoint; the gateway owns replica selection, health, and
retries. Same dependency discipline as serving/server.py — JSON over
ThreadingHTTPServer, nothing outside the stdlib, so it runs anywhere a
replica runs.

* **Deterministic weighted selection** — smooth weighted round-robin
  over the manifest's replica weights: each pick adds every routable
  replica's weight to its accumulator, takes the max, and subtracts
  the total from the winner. Exact proportions on every prefix, no
  RNG, reproducible in tests (the same discipline as the canary
  router's error-diffusion split).
* **Health-aware ejection** — a background loop polls each replica's
  ``/healthz``; non-ok answers (draining, degraded — the body carries
  the PR 13 SLO reason + shed level) eject the replica from rotation
  until it reports ok again. Connect failures on the request path
  eject immediately.
* **Retry with backoff** — a connect-level failure is retried against
  the next replica in the rotation after a short backoff; replica
  *application* errors (4xx/5xx with a JSON body) pass through
  untouched — a 429 shed decision is load signal, not retry fodder.
* **Tail-latency hedging** — with ``gateway_hedge_ms`` set, a
  ``/predict`` still unanswered after that delay is duplicated to a
  second replica (deterministically the next WRR pick) and the FIRST
  answer wins; the loser is discarded. Counted as
  ``gateway_hedged_requests`` / ``gateway_hedge_wins`` (wins = the
  backup answered first — the straggler-shielding signal).
* **Edge transforms** — with a `serving.transforms.EdgeTransform`
  attached (auto-discovered from the manifest stable model's
  ``.transform.json`` sidecar), ``POST /predict`` additionally accepts
  ``{"csv": "raw,rows\\n..."}`` or a ``text/csv`` body, and JSON rows
  may carry nulls for missing values — clients send raw features.

Endpoints: ``POST /predict`` (forwarded), ``GET /healthz`` (gateway +
per-replica rollup), ``GET /stats`` (selection/retry/ejection counters,
replica states, manifest rev), ``GET /gateway`` (config snapshot).
"""
from __future__ import annotations

import json
import queue
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..utils import log
from .manifest import load_manifest

__all__ = ["FleetGateway", "Replica", "make_gateway_server",
           "run_gateway_server"]


class Replica:
    """One backend in the rotation (all mutation under the gateway lock)."""

    def __init__(self, url: str, weight: float = 1.0):
        self.url = url.rstrip("/")
        self.weight = float(weight)
        self.current = 0.0              # smooth-WRR accumulator
        self.healthy = True
        self.ejected_until = 0.0
        self.picks = 0
        self.failures = 0
        self.last_status = "unknown"
        self.last_reason: Optional[str] = None

    def routable(self, now: float) -> bool:
        return self.healthy or now >= self.ejected_until

    def snapshot(self, now: float) -> dict:
        return {"url": self.url, "weight": self.weight,
                "healthy": self.healthy,
                "ejected_for_s": max(0.0, round(self.ejected_until - now,
                                                3)),
                "picks": self.picks, "failures": self.failures,
                "last_status": self.last_status,
                "last_reason": self.last_reason}


class FleetGateway:
    """Replica selection + health + retry; transport-agnostic core with
    an HTTP adapter below (mirrors the ServingApp/_Handler split)."""

    def __init__(self, replicas: Optional[List] = None,
                 manifest_path: Optional[str] = None,
                 transform=None, retries: int = 1,
                 backoff_s: float = 0.05, eject_s: float = 2.0,
                 health_period_s: float = 0.5, timeout_s: float = 10.0,
                 hedge_s: float = 0.0):
        self.manifest_path = manifest_path
        self.transform = transform
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.eject_s = float(eject_s)
        self.health_period_s = float(health_period_s)
        self.timeout_s = float(timeout_s)
        self.hedge_s = float(hedge_s)
        self.manifest_rev = 0
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        for rep in replicas or []:
            if isinstance(rep, str):
                self.add_replica(rep)
            else:
                self.add_replica(rep["url"], float(rep.get("weight", 1.0)))
        if manifest_path:
            self.refresh_manifest()

    # -- replica set -----------------------------------------------------
    def add_replica(self, url: str, weight: float = 1.0) -> None:
        with self._lock:
            url = url.rstrip("/")
            if url in self._replicas:
                self._replicas[url].weight = float(weight)
            else:
                self._replicas[url] = Replica(url, weight)

    def refresh_manifest(self) -> bool:
        """Adopt the manifest's replica set/weights (and discover the
        stable model's edge-transform sidecar on first sight)."""
        manifest = load_manifest(self.manifest_path)
        if manifest is None:
            return False
        rev = int(manifest.get("rev", 0))
        for rep in manifest.get("replicas") or []:
            if isinstance(rep, str):
                self.add_replica(rep)
            else:
                self.add_replica(rep["url"], float(rep.get("weight", 1.0)))
        if self.transform is None:
            self._discover_transform(manifest)
        if rev != self.manifest_rev:
            self.manifest_rev = rev
            telem_counters.set_gauge("gateway_manifest_rev", rev)
        return True

    def _discover_transform(self, manifest: dict) -> None:
        from ..serving.transforms import EdgeTransform, load_transform
        stable = manifest.get("stable")
        source = (manifest.get("models") or {}).get(stable)
        if not source or "\n" in str(source):
            return
        spec = load_transform(str(source) + ".transform.json")
        if spec is not None:
            self.transform = EdgeTransform(spec)
            log.info("gateway: edge transform discovered for %s (%d "
                     "mapped features)", stable,
                     len(self.transform.mappers))

    # -- selection -------------------------------------------------------
    def pick(self, exclude=()) -> Optional[Replica]:
        """Smooth weighted round-robin over routable replicas: exact
        weight proportions on every prefix, deterministic."""
        now = time.monotonic()
        with self._lock:
            pool = [r for r in self._replicas.values()
                    if r.routable(now) and r.url not in exclude]
            if not pool:
                return None
            total = sum(r.weight for r in pool) or 1.0
            for r in pool:
                r.current += r.weight
            best = max(pool, key=lambda r: (r.current, r.url))
            best.current -= total
            best.picks += 1
            return best

    # -- request path ----------------------------------------------------
    def predict(self, payload: dict) -> tuple:
        """Forward one predict; returns (http_status, body_dict). Only
        connect-level failures are retried (against a different
        replica, after backoff); application errors pass through. With
        ``hedge_s > 0`` a slow answer is raced against a second
        replica (first answer wins)."""
        telem_counters.incr("gateway_requests")
        payload = self._transform_payload(payload)
        data = json.dumps(payload).encode()
        if self.hedge_s > 0:
            return self._predict_hedged(data)
        return self._predict_serial(data)

    def _dispatch_one(self, replica: Replica, data: bytes) -> tuple:
        """One POST to one replica. ('answer', status, body) covers
        everything the replica actually said — 429 (shed) / 5xx are its
        call and pass through; ('connect_error', replica, reason) means
        the replica never answered."""
        try:
            req = urllib.request.Request(
                replica.url + "/predict", data=data,
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                return "answer", resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            try:
                return "answer", exc.code, json.loads(exc.read())
            except Exception:   # noqa: BLE001
                return "answer", exc.code, {"error": f"http_{exc.code}"}
        except Exception as exc:   # noqa: BLE001 — connect failure
            return "connect_error", replica, str(exc)

    def _predict_serial(self, data: bytes, tried=None) -> tuple:
        tried = set(tried or ())
        last_error = "no replica available"
        for attempt in range(self.retries + 1):
            replica = self.pick(exclude=tried)
            if replica is None and tried:
                replica = self.pick()      # all tried: any routable one
            if replica is None:
                telem_counters.incr("gateway_no_replica")
                return 503, {"error": f"no routable replica "
                                      f"({last_error})"}
            if attempt > 0:
                telem_counters.incr("gateway_retries")
                time.sleep(self.backoff_s * attempt)
            kind, a, b = self._dispatch_one(replica, data)
            if kind == "answer":
                return a, b
            last_error = f"{replica.url}: {b}"
            tried.add(replica.url)
            self._eject(replica, f"connect_error: {b}")
        return 502, {"error": f"all replicas failed ({last_error})"}

    def _predict_hedged(self, data: bytes) -> tuple:
        """Hedged dispatch: primary pick fires immediately; if no
        answer lands within hedge_s, the NEXT deterministic pick gets a
        duplicate and the first answer wins. Lanes always report (a
        connect failure is a report, and ejects), so the collect loop
        terminates without its own deadline; if every lane connect-
        fails, fall back to the serial retry path with those replicas
        excluded."""
        primary = self.pick()
        if primary is None:
            telem_counters.incr("gateway_no_replica")
            return 503, {"error": "no routable replica"}
        answers: queue.Queue = queue.Queue()

        def _lane(which: str, replica: Replica) -> None:
            answers.put((which, replica, self._dispatch_one(replica,
                                                            data)))

        threading.Thread(target=_lane, args=("primary", primary),
                         daemon=True, name="lgbm-tpu-gw-hedge0").start()
        outstanding, hedged, tried = 1, False, set()
        while outstanding:
            try:
                which, replica, res = answers.get(
                    timeout=None if hedged else self.hedge_s)
            except queue.Empty:
                # the hedge fires exactly once: duplicate to the next
                # deterministic pick (None when only one replica is
                # routable — then just keep waiting on the primary)
                hedged = True
                backup = self.pick(exclude={primary.url})
                if backup is not None:
                    telem_counters.incr("gateway_hedged_requests")
                    telem_events.emit("gateway_hedge", primary=primary.url,
                                      backup=backup.url)
                    threading.Thread(
                        target=_lane, args=("backup", backup),
                        daemon=True, name="lgbm-tpu-gw-hedge1").start()
                    outstanding += 1
                continue
            outstanding -= 1
            if res[0] == "answer":
                if which == "backup":
                    telem_counters.incr("gateway_hedge_wins")
                return res[1], res[2]
            tried.add(replica.url)
            self._eject(replica, f"connect_error: {res[2]}")
        return self._predict_serial(data, tried=tried)

    def _transform_payload(self, payload: dict) -> dict:
        """Edge featurization: raw CSV text / JSON rows (with nulls)
        become bin-canonical numeric rows via the model's own training
        mappers, so what the replica scores is bit-identical to
        client-side pre-binning (Dataset.real_threshold grid)."""
        if self.transform is None:
            return payload
        out = dict(payload)
        if "csv" in out:
            rows = self.transform.parse_csv(out.pop("csv"))
        elif out.get("rows") and any(
                v is None for row in out["rows"] for v in row):
            rows = self.transform.parse_rows(out["rows"])
        else:
            return out
        out["rows"] = [[float(v) for v in row]
                       for row in self.transform.prebin_rows(rows)]
        return out

    # -- health ----------------------------------------------------------
    def _eject(self, replica: Replica, reason: str) -> None:
        with self._lock:
            was_healthy = replica.healthy
            replica.healthy = False
            replica.failures += 1
            replica.ejected_until = time.monotonic() + self.eject_s
            replica.last_reason = reason
        if was_healthy:
            telem_counters.incr("gateway_ejections")
            telem_events.emit("gateway_eject", url=replica.url,
                              reason=reason)
            log.warning("gateway: ejected %s (%s)", replica.url, reason)
        self._gauge_healthy()

    def _restore(self, replica: Replica) -> None:
        with self._lock:
            was_healthy = replica.healthy
            replica.healthy = True
            replica.ejected_until = 0.0
            replica.last_reason = None
        if not was_healthy:
            telem_events.emit("gateway_restore", url=replica.url)
            log.info("gateway: restored %s", replica.url)
        self._gauge_healthy()

    def _gauge_healthy(self) -> None:
        with self._lock:
            n = sum(1 for r in self._replicas.values() if r.healthy)
        telem_counters.set_gauge("gateway_healthy_replicas", n)

    def check_health(self) -> None:
        """One health sweep (the background loop's body, callable
        directly by tests): poll every replica's /healthz and eject/
        restore on the answer — the degrade *reason* in the body is
        kept so `GET /stats` explains every ejection."""
        if self.manifest_path:
            self.refresh_manifest()
        with self._lock:
            replicas = list(self._replicas.values())
        for replica in replicas:
            status, body = self._healthz(replica)
            replica.last_status = status
            if status == "ok":
                self._restore(replica)
            else:
                reason = (body.get("reason") or status) if body else status
                self._eject(replica, str(reason))

    def _healthz(self, replica: Replica) -> tuple:
        try:
            with urllib.request.urlopen(
                    replica.url + "/healthz", timeout=self.timeout_s) as r:
                body = json.loads(r.read())
                return str(body.get("status", "ok")), body
        except urllib.error.HTTPError as exc:      # 503 carries a body
            try:
                body = json.loads(exc.read())
                return str(body.get("status", f"http_{exc.code}")), body
            except Exception:   # noqa: BLE001
                return f"http_{exc.code}", None
        except Exception as exc:   # noqa: BLE001
            return f"unreachable: {exc}", None

    def start_health_loop(self) -> None:
        if self._health_thread is not None:
            return
        self._stop.clear()
        self._health_thread = threading.Thread(
            target=self._health_run, daemon=True, name="lgbm-tpu-gw-health")
        self._health_thread.start()

    def _health_run(self) -> None:
        while not self._stop.wait(self.health_period_s):
            try:
                self.check_health()
            except Exception as exc:   # noqa: BLE001 — keep sweeping
                log.warning("gateway: health sweep failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None

    # -- introspection ---------------------------------------------------
    def health(self) -> dict:
        now = time.monotonic()
        with self._lock:
            reps = [r.snapshot(now) for r in self._replicas.values()]
        healthy = sum(1 for r in reps if r["healthy"])
        return {"status": "ok" if healthy else "no_replicas",
                "replicas": len(reps), "healthy_replicas": healthy}

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            reps = [r.snapshot(now) for r in
                    sorted(self._replicas.values(), key=lambda r: r.url)]
        return {"replicas": reps, "manifest_rev": self.manifest_rev,
                "counters": {
                    "gateway_requests":
                        telem_counters.get("gateway_requests"),
                    "gateway_retries":
                        telem_counters.get("gateway_retries"),
                    "gateway_ejections":
                        telem_counters.get("gateway_ejections"),
                    "gateway_no_replica":
                        telem_counters.get("gateway_no_replica"),
                    "gateway_hedged_requests":
                        telem_counters.get("gateway_hedged_requests"),
                    "gateway_hedge_wins":
                        telem_counters.get("gateway_hedge_wins")},
                "transform": (self.transform.describe()
                              if self.transform is not None else None)}

    def config(self) -> dict:
        return {"manifest_path": self.manifest_path,
                "retries": self.retries, "backoff_s": self.backoff_s,
                "eject_s": self.eject_s,
                "health_period_s": self.health_period_s,
                "timeout_s": self.timeout_s, "hedge_s": self.hedge_s}


class _GatewayHandler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-gateway/1.0"
    protocol_version = "HTTP/1.1"

    @property
    def gw(self) -> FleetGateway:
        return self.server.gateway

    def log_message(self, fmt, *args):
        log.debug("gateway http: " + fmt, *args)

    def _reply(self, code: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path in ("/healthz", "/health"):
            body = self.gw.health()
            self._reply(200 if body["status"] == "ok" else 503, body)
        elif self.path == "/stats":
            self._reply(200, self.gw.stats())
        elif self.path == "/gateway":
            self._reply(200, self.gw.config())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        if self.path != "/predict":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b"{}"
            if (self.headers.get("Content-Type") or "").startswith(
                    "text/csv"):
                payload = {"csv": raw.decode()}
            else:
                payload = json.loads(raw or b"{}")
            code, body = self.gw.predict(payload)
            self._reply(code, body)
        except ValueError as exc:
            self._reply(400, {"error": str(exc)})
        except Exception as exc:   # noqa: BLE001 — keep serving
            log.warning("gateway: internal error: %s", exc)
            self._reply(500, {"error": str(exc)})


def make_gateway_server(gateway: FleetGateway, host: str = "127.0.0.1",
                        port: int = 8080) -> ThreadingHTTPServer:
    httpd = ThreadingHTTPServer((host, port), _GatewayHandler)
    httpd.gateway = gateway
    httpd.daemon_threads = True
    return httpd


def run_gateway_server(gateway: FleetGateway, host: str = "127.0.0.1",
                       port: int = 8080, background: bool = False):
    httpd = make_gateway_server(gateway, host, port)
    gateway.start_health_loop()
    log.info("gateway: listening on http://%s:%d over %d replica(s)",
             *httpd.server_address[:2], len(gateway.stats()["replicas"]))
    if background:
        t = threading.Thread(target=httpd.serve_forever,
                             name="lgbm-tpu-gw-http", daemon=True)
        t.start()
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:   # pragma: no cover
        pass
    finally:
        gateway.stop()
        httpd.server_close()
    return httpd
