"""Persistent compiled-predictor cache: zero-compile process restarts.

The in-memory PredictorCache makes the first request after warm-up a
pure cache hit — but every process start pays the full warm-up compile
bill again. For a fleet rollout ("restart 200 replicas") that bill is
the difference between a zero-error rolling restart and minutes of cold
replicas. This module persists warm executables on disk, next to the
model file, so a restart skips the compiles entirely.

Every entry carries TWO serialization layers:

* **native** — the XLA executable itself
  (`jax.experimental.serialize_executable`). Loading it is pure
  deserialization: zero trace, zero lower, zero backend compile — the
  `telemetry.counters.compile_events` listener records NOTHING on a
  cache-hit restart (the acceptance property). Valid only when the
  environment fingerprint (jax + jaxlib version, backend, donation
  flag) matches exactly.
* **stablehlo** — the `jax.export` serialized StableHLO module. Survives
  a jaxlib upgrade (the native layer's main invalidation): restoring
  from it skips the Python retrace but pays one backend compile per
  bucket ("rebuilt", counted separately from hits).

Entry identity (the file name) is the sha256 of the executable family —
the registry's ensemble shape signature, feature count, objective
convert key, placement device — plus the batch bucket. The environment
fingerprint deliberately lives INSIDE the entry, not in the key: a
jaxlib bump overwrites entries in place instead of stranding stale
files.

Writes are atomic (tmp + os.replace) and torn/corrupt entries are
treated as misses, mirroring the checkpoint discipline of
resilience/checkpoint.py.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import counters as telem_counters
from ..utils import log

__all__ = ["ExportCache", "cache_dir_for_model", "env_fingerprint"]

_MAGIC = b"LGBMTPUXC1\n"
_registered = {"done": False}


def _register_pytrees() -> None:
    """jax.export serializes the argument pytree structure; custom
    NamedTuples must be registered once per process or export() refuses
    the whole function (the stablehlo layer would silently vanish)."""
    if _registered["done"]:
        return
    try:
        from jax import export as jax_export
        from ..ops.predict import EnsembleArrays
        jax_export.register_namedtuple_serialization(
            EnsembleArrays,
            serialized_name="lightgbm_tpu.ops.predict.EnsembleArrays")
    except Exception as exc:   # noqa: BLE001 — double-register / old jax
        log.debug("export cache: pytree registration skipped: %s", exc)
    _registered["done"] = True


def _jaxlib_version() -> str:
    try:
        import jaxlib
        return getattr(jaxlib, "__version__", "") or \
            getattr(getattr(jaxlib, "version", None), "__version__", "?")
    except Exception:                      # pragma: no cover - no jaxlib
        return "?"


def _cpu_runtime() -> str:
    """Which XLA:CPU runtime compiled this process's executables. The
    thunk runtime (the jax 0.4.37 default) JIT-resolves fusion-kernel
    symbols in-memory, so its serialized executables only reload in the
    process that built them; the legacy runtime
    (``--xla_cpu_use_thunk_runtime=false``) emits self-contained object
    code that survives a process restart. Part of the fingerprint so a
    runtime mismatch degrades to the StableHLO rebuild instead of a
    confusing native-load failure."""
    flags = os.environ.get("XLA_FLAGS", "")
    return "legacy" if "xla_cpu_use_thunk_runtime=false" in flags \
        else "thunks"


def env_fingerprint(donate: bool) -> Dict[str, str]:
    """The native layer's validity domain: an executable deserializes
    safely only into the exact runtime that serialized it."""
    import jax
    backend = jax.default_backend()
    fp = {"jax": jax.__version__,
          "jaxlib": _jaxlib_version(),
          "backend": backend,
          "donate": "1" if donate else "0"}
    if backend == "cpu":
        fp["cpu_runtime"] = _cpu_runtime()
    return fp


def cache_dir_for_model(model_file: str) -> str:
    """The on-disk location convention: `<model_file>.xcache/` — the
    cache travels with the model artifact through a rollout."""
    return str(model_file) + ".xcache"


class ExportCache:
    """One on-disk directory of serialized predictor executables."""

    def __init__(self, cache_dir: str):
        self.cache_dir = str(cache_dir)
        self.last_restore: Dict[str, int] = {}

    # -- keys -----------------------------------------------------------
    @staticmethod
    def entry_name(family: Tuple, bucket: int) -> str:
        digest = hashlib.sha256(
            repr((family, int(bucket))).encode()).hexdigest()[:32]
        return f"{digest}.xc"

    def _path(self, family: Tuple, bucket: int) -> str:
        return os.path.join(self.cache_dir, self.entry_name(family, bucket))

    # -- write ----------------------------------------------------------
    def save(self, model, predictor, overwrite: bool = False) -> int:
        """Serialize every warm executable belonging to `model` (matched
        by ensemble shape signature + device) into the cache dir.
        Returns the number of entries written; existing entries are kept
        unless `overwrite` (their native layer is already valid here —
        this process just loaded them)."""
        entries = [(fam, bucket, compiled)
                   for fam, bucket, compiled in predictor.entries()
                   if fam[0] == model.shape_sig
                   and fam[6] == model.device_key]
        if not entries:
            return 0
        os.makedirs(self.cache_dir, exist_ok=True)
        written = 0
        for family, bucket, compiled in entries:
            path = self._path(family, bucket)
            if not overwrite and os.path.exists(path):
                continue
            try:
                self._write_entry(path, family, bucket, model, predictor,
                                  compiled)
                written += 1
                telem_counters.incr("export_cache_saves")
            except Exception as exc:   # noqa: BLE001 — cache is best-effort
                log.warning("export cache: serialize bucket=%d failed: %s",
                            bucket, exc)
        if written:
            log.info("export cache: wrote %d executable(s) to %s",
                     written, self.cache_dir)
        return written

    def _write_entry(self, path, family, bucket, model, predictor,
                     compiled) -> None:
        from jax.experimental import serialize_executable
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        trees = pickle.dumps((in_tree, out_tree))
        hlo = self._export_stablehlo(family, bucket, model, predictor)
        header = json.dumps({
            "env": env_fingerprint(predictor.donate_input),
            "bucket": int(bucket),
            "n_features": int(family[1]),
            "raw_score": bool(family[4]),
            "device": family[6],
            "version": model.version,
            "created_unix": round(time.time(), 3),
            "native_len": len(payload),
            "trees_len": len(trees),
            "hlo_len": len(hlo),
        }).encode()
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(_MAGIC)
            fh.write(struct.pack(">I", len(header)))
            fh.write(header)
            fh.write(payload)
            fh.write(trees)
            fh.write(hlo)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def _export_stablehlo(self, family, bucket, model, predictor) -> bytes:
        """The portable layer: re-export the same scoring function as
        serialized StableHLO. Best-effort — an export failure degrades
        the entry to native-only."""
        try:
            from jax import export as jax_export
            import jax
            _register_pytrees()
            fn = predictor._make_fn(model, raw_score=bool(family[4]))
            x_ex = np.zeros((int(bucket), int(family[1])), dtype=np.float32)
            exp = jax_export.export(jax.jit(fn))(
                x_ex, model.arrays, model.tree_class, model.denom)
            return exp.serialize()
        except Exception as exc:   # noqa: BLE001 — optional layer
            log.debug("export cache: stablehlo export failed: %s", exc)
            return b""

    # -- read -----------------------------------------------------------
    def restore(self, model, predictor, buckets: Sequence[int],
                raw_flags: Sequence[bool] = (False,)) -> Dict[str, int]:
        """Install cached executables for every (bucket, raw_score) pair
        into `predictor`. Exact-environment entries load natively (zero
        compiles); stale-environment entries rebuild from StableHLO (one
        backend compile, no Python retrace); anything else is a miss the
        caller warms the ordinary way. Returns {restored, rebuilt,
        missed} and remembers it in `last_restore`."""
        from ..ops.predict import _bucket_up
        stats = {"restored": 0, "rebuilt": 0, "missed": 0}
        want_env = env_fingerprint(predictor.donate_input)
        for raw in raw_flags:
            family = predictor.family(model, model.num_features, bool(raw))
            for bucket_rows in buckets:
                bucket = min(_bucket_up(max(1, int(bucket_rows))),
                             predictor.max_batch_rows)
                entry = self._read_entry(self._path(family, bucket))
                if entry is None:
                    stats["missed"] += 1
                    telem_counters.incr("export_cache_misses")
                    continue
                header, payload, trees, hlo = entry
                if header["env"] == want_env and self._install_native(
                        predictor, family, bucket, payload, trees):
                    stats["restored"] += 1
                    telem_counters.incr("export_cache_hits")
                elif hlo and self._install_rebuilt(
                        predictor, model, family, bucket, hlo):
                    stats["rebuilt"] += 1
                    telem_counters.incr("export_cache_rebuilds")
                else:
                    stats["missed"] += 1
                    telem_counters.incr("export_cache_misses")
        self.last_restore = dict(stats)
        telem_counters.set_gauge(
            "export_cache_last_restored", stats["restored"])
        return stats

    def _read_entry(self, path: str):
        try:
            with open(path, "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    return None
                (hlen,) = struct.unpack(">I", fh.read(4))
                header = json.loads(fh.read(hlen))
                payload = fh.read(header["native_len"])
                trees = fh.read(header["trees_len"])
                hlo = fh.read(header["hlo_len"])
                if (len(payload), len(trees), len(hlo)) != (
                        header["native_len"], header["trees_len"],
                        header["hlo_len"]):
                    return None                     # torn write
                return header, payload, trees, hlo
        except (OSError, ValueError, KeyError, struct.error):
            return None

    def _install_native(self, predictor, family, bucket, payload,
                        trees) -> bool:
        try:
            from jax.experimental import serialize_executable
            in_tree, out_tree = pickle.loads(trees)
            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
            predictor.install(family, bucket, compiled)
            return True
        except Exception as exc:   # noqa: BLE001 — fall through to hlo
            log.warning("export cache: native load bucket=%d failed: %s",
                        bucket, exc)
            return False

    def _install_rebuilt(self, predictor, model, family, bucket,
                         hlo: bytes) -> bool:
        try:
            from jax import export as jax_export
            import jax
            _register_pytrees()
            exp = jax_export.deserialize(hlo)
            x_ex = np.zeros((int(bucket), int(family[1])), dtype=np.float32)
            compiled = jax.jit(exp.call).lower(
                x_ex, model.arrays, model.tree_class,
                model.denom).compile()
            predictor.install(family, bucket, compiled)
            return True
        except Exception as exc:   # noqa: BLE001 — degrade to a miss
            log.warning("export cache: stablehlo rebuild bucket=%d "
                        "failed: %s", bucket, exc)
            return False

    # -- introspection ---------------------------------------------------
    def info(self) -> Dict[str, object]:
        try:
            files = [f for f in os.listdir(self.cache_dir)
                     if f.endswith(".xc")]
            size = sum(os.path.getsize(os.path.join(self.cache_dir, f))
                       for f in files)
        except OSError:
            files, size = [], 0
        return {"dir": self.cache_dir, "entries": len(files),
                "bytes": size, "last_restore": dict(self.last_restore)}
