"""Serial (single-device) leaf-wise tree learner.

Equivalent of the reference SerialTreeLearner (reference:
src/treelearner/serial_tree_learner.cpp:173-893): leaf-wise growth with
histogram subtraction. TPU-native execution model: the tree loop runs on
host (tiny bookkeeping), while each step dispatches three jitted device
programs — partition (stable-sort window), histogram build (MXU one-hot
contraction, smaller child only), and the vectorized split scan. Dynamic
leaf sizes are handled by padding windows to power-of-two buckets so XLA
sees a small, fixed set of shapes.

Histogram-cache choreography (parent moved to larger child, smaller built
fresh, larger = parent - smaller) matches serial_tree_learner.cpp:400-605.
"""
from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config
from ..io.binning import BIN_CATEGORICAL
from ..io.dataset import Dataset
from ..ops import fused as fused_ops
from ..ops import histogram as hist_ops
from ..ops import partition as part_ops
from ..ops import split as split_ops
from .. import telemetry
from ..telemetry import recorder as telem
from ..utils import log
from ..utils.envs import use_pallas_env
from .tree import Tree

_MIN_BUCKET = 256


def _bucket(count: int, cap: int) -> int:
    b = _MIN_BUCKET
    while b < count:
        b *= 2
    return min(b, cap)


class _LeafState:
    __slots__ = ("begin", "count", "sum_grad", "sum_hess", "depth",
                 "hist", "split", "min_c", "max_c")

    def __init__(self, begin, count, sum_grad, sum_hess, depth,
                 min_c=-np.inf, max_c=np.inf):
        self.begin = begin
        self.count = count
        self.sum_grad = sum_grad
        self.sum_hess = sum_hess
        self.depth = depth
        self.hist = None         # device (F, B, 3)
        self.split = None        # host dict of the best split, or None
        self.min_c = min_c
        self.max_c = max_c


class SerialTreeLearner:
    def __init__(self, config: Config, dataset: Dataset):
        self.config = config
        self.dataset = dataset
        self.binned = dataset.device_binned()
        (self.f_numbins, self.f_missing, self.f_default,
         self.f_categorical, self.f_monotone) = dataset.feature_meta_arrays()
        self.num_features = dataset.num_features
        self.num_bins = int(dataset.max_num_bins)
        # pad bin axis to a lane-friendly size
        b = 1 << max(4, (self.num_bins - 1).bit_length())
        self.device_bins = min(b, 256) if self.num_bins <= 256 else b
        n = dataset.num_data
        self.max_bucket = _bucket(n, 1 << 30)
        self._has_categorical = any(
            dataset.bin_mappers[f].bin_type == BIN_CATEGORICAL
            for f in dataset.used_features)
        # XLA's fused one-hot contraction measured faster than the Pallas
        # kernel on v5e (tools/microbench_injit.py); opt-in only.
        self._use_pallas = use_pallas_env() and jax.default_backend() == "tpu"
        # quantized-gradient training (ops/quantize.py): per-iteration
        # int discretization, exact integer histograms, bit-exact sibling
        # subtraction; 0 = float path (default, unchanged)
        self._quant_bits = config.quant_bits
        self._hist_chunk = int(config.hist_chunk_size or 0)
        self._gh_packed = None
        self._gh_scales = None
        # per-tree hoisted device masks (reset at every train() entry)
        self._meta_cache = None
        self._cat_mask_cache = None
        self._mono_enabled = bool(np.any(np.asarray(self.f_monotone) != 0))
        # feature_contri gain multipliers (reference FeatureMetainfo penalty)
        contri = config.feature_contri or []
        if contri:
            pen = np.array(
                [contri[f] if f < len(contri) else 1.0
                 for f in dataset.used_features], dtype=np.float32)
            self._feature_penalty = jnp.asarray(pen)
        else:
            self._feature_penalty = None
        # CEGB (reference cost_effective_gradient_boosting.hpp): coupled
        # penalties are charged once per feature across the whole model;
        # lazy per-row costs are approximated per-leaf by count.
        self._cegb_enabled = (config.cegb_tradeoff > 0 and (
            config.cegb_penalty_split > 0
            or bool(config.cegb_penalty_feature_coupled)
            or bool(config.cegb_penalty_feature_lazy)))
        if self._cegb_enabled:
            nf = self.num_features
            coupled = config.cegb_penalty_feature_coupled or []
            lazy = config.cegb_penalty_feature_lazy or []
            self._cegb_coupled = np.array(
                [coupled[f] if f < len(coupled) else 0.0
                 for f in dataset.used_features])
            self._cegb_lazy = np.array(
                [lazy[f] if f < len(lazy) else 0.0
                 for f in dataset.used_features])
            self._cegb_feature_used = np.zeros(nf, dtype=bool)
        # forced splits: BFS JSON replayed at the top of every tree
        # (reference: serial_tree_learner.cpp:607-769 ForceSplits)
        self._forced_splits = None
        if config.forcedsplits_filename:
            import json
            with open(config.forcedsplits_filename) as fh:
                self._forced_splits = json.load(fh)

    # ------------------------------------------------------------------
    def _scan_args(self):
        cfg = self.config
        return dict(
            num_bins=self.device_bins,
            l1=float(cfg.lambda_l1), l2=float(cfg.lambda_l2),
            max_delta_step=float(cfg.max_delta_step),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            min_sum_hessian=float(cfg.min_sum_hessian_in_leaf),
            min_gain_to_split=float(cfg.min_gain_to_split),
        )

    def _feature_mask(self, rng: np.random.RandomState) -> np.ndarray:
        frac = self.config.feature_fraction
        mask = np.ones(self.num_features, dtype=bool)
        if 0.0 < frac < 1.0:
            k = max(1, int(self.num_features * frac))
            chosen = rng.choice(self.num_features, k, replace=False)
            mask[:] = False
            mask[chosen] = True
        return mask

    def _node_feature_mask(self, base_mask: np.ndarray,
                           rng: np.random.RandomState) -> jax.Array:
        frac = self.config.feature_fraction_bynode
        if 0.0 < frac < 1.0:
            k = max(1, int(self.num_features * frac))
            chosen = rng.choice(self.num_features, k, replace=False)
            node_mask = np.zeros(self.num_features, dtype=bool)
            node_mask[chosen] = True
            return jnp.asarray(base_mask & node_mask)
        return jnp.asarray(base_mask)

    # ------------------------------------------------------------------
    def _build_hist(self, indices_buf, grad, hess, begin: int, count: int):
        return hist_ops.gather_and_build(
            self.binned, indices_buf, grad, hess,
            jnp.int32(begin), jnp.int32(count),
            num_bins=self.device_bins, bucket=_bucket(count, self.max_bucket),
            chunk_size=self._hist_chunk)

    def _hist_f32(self, hist):
        """Leaf histogram as f32 for scan consumers: identity on the
        float path, scale-rescaled dequantization on the quantized path
        (the pool itself stays exact int32)."""
        if self._quant_bits and hist is not None:
            from ..ops.quantize import dequantize_histogram
            return dequantize_histogram(hist, *self._gh_scales)
        return hist

    def _scan_leaf(self, leaf: _LeafState, feature_mask) -> dict:
        """Run the split scan for a leaf; returns a host-side split record."""
        res = split_ops.find_best_split(
            self._hist_f32(leaf.hist), jnp.float32(leaf.sum_grad),
            jnp.float32(leaf.sum_hess),
            jnp.float32(leaf.count), self.f_numbins, self.f_missing,
            self.f_default, feature_mask & (self.f_categorical == 0),
            self.f_monotone, jnp.float32(leaf.min_c), jnp.float32(leaf.max_c),
            **self._scan_args())
        rec = self._fetch_split(res)
        if self._has_categorical:
            cres = split_ops.find_best_split_categorical(
                self._hist_f32(leaf.hist), jnp.float32(leaf.sum_grad),
                jnp.float32(leaf.sum_hess), jnp.float32(leaf.count),
                self.f_numbins, self.f_missing,
                feature_mask & (self.f_categorical == 1),
                jnp.float32(leaf.min_c), jnp.float32(leaf.max_c),
                **self._cat_scan_args())
            crec = self._fetch_split(cres, categorical=True)
            if crec["gain"] > rec["gain"]:
                rec = crec
        return rec

    def _cat_scan_args(self):
        cfg = self.config
        return dict(
            num_bins=self.device_bins,
            l1=float(cfg.lambda_l1), l2=float(cfg.lambda_l2),
            cat_l2=float(cfg.cat_l2), cat_smooth=float(cfg.cat_smooth),
            max_delta_step=float(cfg.max_delta_step),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            min_sum_hessian=float(cfg.min_sum_hessian_in_leaf),
            min_gain_to_split=float(cfg.min_gain_to_split),
            max_cat_threshold=int(cfg.max_cat_threshold),
            max_cat_to_onehot=int(cfg.max_cat_to_onehot),
            min_data_per_group=int(cfg.min_data_per_group),
        )

    @staticmethod
    def _fetch_split(res, categorical: bool = False) -> dict:
        with telem.phase("host_sync"):
            vals = jax.device_get(res)
        rec = {
            "gain": float(vals.gain),
            "feature": int(vals.feature),
            "threshold": 0 if categorical else int(vals.threshold),
            "default_left": False if categorical else bool(vals.default_left),
            "left_sum_grad": float(vals.left_sum_grad),
            "left_sum_hess": float(vals.left_sum_hess),
            "left_count": int(round(float(vals.left_count))),
            "right_sum_grad": float(vals.right_sum_grad),
            "right_sum_hess": float(vals.right_sum_hess),
            "right_count": int(round(float(vals.right_count))),
            "left_output": float(vals.left_output),
            "right_output": float(vals.right_output),
            "categorical": categorical,
        }
        if categorical:
            mask = np.asarray(vals.left_mask)
            rec["cat_bitset_inner"] = _make_bitset(
                [int(i) for i in np.nonzero(mask)[0]])
        return rec

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              bag_indices: Optional[np.ndarray] = None,
              iter_seed: int = 0) -> Tree:
        """Grow one tree. Per split: ONE fused device program (partition +
        left-child histogram + sibling subtraction + both child scans) and
        ONE small host fetch — see ops/fused.py."""
        cfg = self.config
        ds = self.dataset
        n = ds.num_data
        bag_cnt = n if bag_indices is None else len(bag_indices)
        indices_buf = part_ops.make_indices_buffer(n, self.max_bucket, bag_indices)
        rng = np.random.RandomState(
            (cfg.feature_fraction_seed + iter_seed) % (2**31 - 1))
        base_mask = self._feature_mask(rng)
        self._numerical_mask_np = base_mask  # node-level resample below

        tree = Tree(cfg.num_leaves)
        # per-tree hoisted caches (base_mask changes per tree)
        self._meta_cache = None
        self._cat_mask_cache = None
        root_cost = self._cegb_cost(bag_cnt)
        if self._quant_bits:
            # per-iteration (per-class: each class's tree quantizes its
            # own gradient vector) discretization with stochastic
            # rounding; one packed int32 lane per row rides the whole
            # tree, histograms are exact int32
            from ..ops import quantize as quant_ops
            qkey = jax.random.PRNGKey(
                (cfg.feature_fraction_seed * 9973 + 2 * iter_seed + 1)
                % (2**31 - 1))
            with telem.phase("quantize"):
                self._gh_packed, s_g, s_h = quant_ops.quantize_gh(
                    grad, hess, qkey, grad_bits=self._quant_bits)
            self._gh_scales = (s_g, s_h)
            self._scales_vec = jnp.stack([s_g, s_h])
            with telem.phase("hist"):
                root_hist, totals_dev, root_res = \
                    fused_ops.fused_root_step_q(
                        indices_buf, self.binned, self._gh_packed,
                        self._scales_vec, jnp.int32(bag_cnt),
                        self._fused_meta(base_mask, rng),
                        None if root_cost is None
                        else jnp.asarray(root_cost),
                        bucket=_bucket(bag_cnt, self.max_bucket),
                        grad_bits=self._quant_bits,
                        hist_chunk=self._hist_chunk,
                        use_pallas=self._use_pallas, **self._scan_args())
        else:
            with telem.phase("hist"):
                root_hist, totals_dev, root_res = fused_ops.fused_root_step(
                    indices_buf, self.binned, grad, hess,
                    jnp.int32(bag_cnt), self._fused_meta(base_mask, rng),
                    None if root_cost is None else jnp.asarray(root_cost),
                    bucket=_bucket(bag_cnt, self.max_bucket),
                    hist_chunk=self._hist_chunk,
                    use_pallas=self._use_pallas, **self._scan_args())
        telemetry.note_grow_dispatches(1.0)
        with telem.phase("host_sync"):
            totals = jax.device_get(totals_dev)
        root = _LeafState(0, bag_cnt, float(totals[0]), float(totals[1]), 0)
        root.hist = root_hist
        root.split = self._fetch_split(jax.device_get(root_res))
        if self._has_categorical:
            self._merge_categorical(root, base_mask, rng)
        leaves: Dict[int, _LeafState] = {0: root}

        if self._forced_splits is not None:
            indices_buf = self._replay_forced_splits(
                tree, leaves, indices_buf, grad, hess, base_mask, rng)

        for _split_idx in range(cfg.num_leaves - 1):
            # pick the splittable leaf with max gain (leaf-wise growth)
            best_leaf, best_gain = -1, 1e-10
            for li, st in leaves.items():
                if st.split is not None and st.split["gain"] > best_gain:
                    best_leaf, best_gain = li, st.split["gain"]
            if best_leaf < 0:
                if _split_idx == 0:
                    log.warning(
                        "No further splits with positive gain, best gain: %f",
                        best_gain)
                break
            indices_buf = self._apply_split(
                tree, leaves, best_leaf, indices_buf, grad, hess,
                base_mask, rng)

        self.indices_buf = indices_buf
        self.leaves = leaves
        # the host loop pays ~num_leaves growth-program dispatches per
        # tree — the O(leaves) baseline the fused device program beats
        telemetry.note_grow_dispatches(0.0, trees=1.0)
        return tree

    def _fused_meta(self, base_mask, rng):
        # per-tree constant unless per-node feature resampling is on:
        # rebuilding it per split paid a fresh base-mask H2D plus two
        # device mask ops for every split in the tree. Caching is
        # rng-neutral — _node_feature_mask only draws from rng when
        # feature_fraction_bynode is active, exactly when we skip the
        # cache. train() clears the cache at tree start.
        if self._meta_cache is not None:
            return self._meta_cache
        mask = self._node_feature_mask(base_mask, rng) & (self.f_categorical == 0)
        meta = (self.f_numbins, self.f_missing, self.f_default, mask,
                self.f_monotone, self._feature_penalty)
        if not (0.0 < self.config.feature_fraction_bynode < 1.0):
            self._meta_cache = meta
        return meta

    def _cegb_cost(self, count: int) -> Optional[np.ndarray]:
        if not self._cegb_enabled:
            return None
        cfg = self.config
        cost = np.full(self.num_features,
                       cfg.cegb_tradeoff * cfg.cegb_penalty_split * count)
        cost += np.where(self._cegb_feature_used, 0.0,
                         cfg.cegb_tradeoff * self._cegb_coupled)
        cost += cfg.cegb_tradeoff * self._cegb_lazy * count
        return cost.astype(np.float32)

    def _merge_categorical(self, st: "_LeafState", base_mask, rng) -> None:
        """Categorical split search runs as a separate (rarer) program and
        merges with the numerical winner on host."""
        # base_mask is fixed for the whole tree, so the categorical
        # device mask is too (hoisted out of the split loop; train()
        # clears the cache at tree start)
        if self._cat_mask_cache is None:
            self._cat_mask_cache = (jnp.asarray(base_mask)
                                    & (self.f_categorical == 1))
        feature_mask = self._cat_mask_cache
        telemetry.note_grow_dispatches(1.0)
        cres = split_ops.find_best_split_categorical(
            self._hist_f32(st.hist), jnp.float32(st.sum_grad),
            jnp.float32(st.sum_hess),
            jnp.float32(st.count), self.f_numbins, self.f_missing,
            feature_mask, jnp.float32(st.min_c), jnp.float32(st.max_c),
            **self._cat_scan_args())
        crec = self._fetch_split(jax.device_get(cres), categorical=True)
        if st.split is None or crec["gain"] > st.split["gain"]:
            st.split = crec

    def _apply_split(self, tree: Tree, leaves: Dict[int, _LeafState],
                     leaf_id: int, indices_buf, grad, hess,
                     base_mask, rng):
        ds = self.dataset
        st = leaves[leaf_id]
        sp = st.split
        inner_f = sp["feature"]
        real_f = ds.inner_to_real(inner_f)
        mapper = ds.bin_mappers[real_f]
        bucket = _bucket(st.count, self.max_bucket)

        # children constraints; monotone propagation (basic mode,
        # reference serial_tree_learner.cpp:771-852)
        lmin, lmax, rmin, rmax = st.min_c, st.max_c, st.min_c, st.max_c
        mono = int(np.asarray(self.f_monotone)[inner_f]) if self._mono_enabled else 0
        if mono != 0:
            mid = (sp["left_output"] + sp["right_output"]) / 2.0
            if mono > 0:
                lmax, rmin = min(lmax, mid), max(rmin, mid)
            else:
                lmin, rmax = max(lmin, mid), min(rmax, mid)

        bits = np.zeros(8, dtype=np.uint32)
        if sp["categorical"]:
            src = sp["cat_bitset_inner"][:8]
            bits[: len(src)] = src
        iparams = np.zeros(15, dtype=np.int32)
        iparams[:9] = [st.begin, st.count, inner_f, sp["threshold"],
                       int(sp["default_left"]), mapper.missing_type,
                       mapper.default_bin, mapper.num_bin,
                       int(sp["categorical"])]
        fparams = np.asarray(
            [sp["left_sum_grad"], sp["left_sum_hess"], sp["left_count"],
             sp["right_sum_grad"], sp["right_sum_hess"], sp["right_count"],
             lmin, lmax, rmin, rmax], dtype=np.float32)
        if self._cegb_enabled:
            child_costs = jnp.asarray(np.stack([
                self._cegb_cost(sp["left_count"]),
                self._cegb_cost(sp["right_count"])]))
            self._cegb_feature_used[inner_f] = True
        else:
            child_costs = None
        telemetry.note_grow_dispatches(1.0)
        with telem.phase("partition"):
            if self._quant_bits:
                out = fused_ops.fused_split_step_q(
                    indices_buf, self.binned, self._gh_packed,
                    jnp.asarray(iparams), jnp.asarray(bits.view(np.int32)),
                    jnp.asarray(fparams), st.hist, self._scales_vec,
                    self._fused_meta(base_mask, rng), child_costs,
                    bucket=bucket, grad_bits=self._quant_bits,
                    hist_chunk=self._hist_chunk,
                    use_pallas=self._use_pallas, **self._scan_args())
            else:
                out = fused_ops.fused_split_step(
                    indices_buf, self.binned, grad, hess,
                    jnp.asarray(iparams), jnp.asarray(bits.view(np.int32)),
                    jnp.asarray(fparams), st.hist,
                    self._fused_meta(base_mask, rng), child_costs,
                    bucket=bucket, hist_chunk=self._hist_chunk,
                    use_pallas=self._use_pallas, **self._scan_args())

        # ONE host fetch per split: left_count + the two winner tuples
        with telem.phase("host_sync"):
            left_cnt, left_rec_raw, right_rec_raw = jax.device_get(
                (out.left_count, out.left_res, out.right_res))
        left_cnt = int(left_cnt)
        if left_cnt != sp["left_count"]:
            log.debug("partition/scan count mismatch: %d vs %d",
                      left_cnt, sp["left_count"])

        # tree bookkeeping (leaf_id keeps left, new leaf is right)
        if not sp["categorical"]:
            thr_real = ds.real_threshold(inner_f, sp["threshold"])
            new_leaf = tree.split(
                leaf_id, inner_f, real_f, sp["threshold"], thr_real,
                sp["left_output"], sp["right_output"], sp["left_count"],
                sp["right_count"], sp["left_sum_hess"], sp["right_sum_hess"],
                sp["gain"], mapper.missing_type, sp["default_left"])
        else:
            inner_bits = sp["cat_bitset_inner"]
            cats = [mapper.bin_2_categorical[b]
                    for b in _bits_set(inner_bits)
                    if b < len(mapper.bin_2_categorical)]
            real_bits = _make_bitset(cats)
            new_leaf = tree.split_categorical(
                leaf_id, inner_f, real_f,
                [int(w) for w in inner_bits], [int(w) for w in real_bits],
                sp["left_output"], sp["right_output"], sp["left_count"],
                sp["right_count"], sp["left_sum_hess"], sp["right_sum_hess"],
                sp["gain"], mapper.missing_type)

        left = _LeafState(st.begin, sp["left_count"], sp["left_sum_grad"],
                          sp["left_sum_hess"], st.depth + 1, lmin, lmax)
        right = _LeafState(st.begin + sp["left_count"], sp["right_count"],
                           sp["right_sum_grad"], sp["right_sum_hess"],
                           st.depth + 1, rmin, rmax)
        left.hist = out.left_hist
        right.hist = out.right_hist
        left.split = (self._fetch_split(left_rec_raw)
                      if self._splittable(left, tree) else None)
        right.split = (self._fetch_split(right_rec_raw)
                       if self._splittable(right, tree) else None)
        if self._has_categorical:
            if left.split is not None:
                self._merge_categorical(left, base_mask, rng)
            if right.split is not None:
                self._merge_categorical(right, base_mask, rng)
        st.hist = None  # release parent histogram
        if left.split is None:
            left.hist = None
        if right.split is None:
            right.hist = None

        leaves[leaf_id] = left
        leaves[tree.num_leaves - 1] = right
        assert tree.num_leaves - 1 == new_leaf
        return out.indices_buf

    def _replay_forced_splits(self, tree, leaves, indices_buf, grad, hess,
                              base_mask, rng):
        """Apply the forced-split JSON breadth-first before normal growth."""
        cfg = self.config
        ds = self.dataset
        queue = [(0, self._forced_splits)]
        while queue and tree.num_leaves < cfg.num_leaves:
            leaf_id, node = queue.pop(0)
            if node is None or "feature" not in node:
                continue
            real_f = int(node["feature"])
            if real_f not in ds.used_features:
                log.warning("Forced split feature %d unavailable; skipping",
                            real_f)
                continue
            inner_f = ds.used_features.index(real_f)
            mapper = ds.bin_mappers[real_f]
            bin_thr = mapper.value_to_bin(float(node["threshold"]))
            bin_thr = min(bin_thr, mapper.num_bin - 2)
            st = leaves[leaf_id]
            sp = self._gather_split_at(st, inner_f, bin_thr)
            if sp is None:
                continue
            st.split = sp
            indices_buf = self._apply_split(
                tree, leaves, leaf_id, indices_buf, grad, hess,
                base_mask, rng)
            right_leaf = tree.num_leaves - 1
            if "left" in node:
                queue.append((leaf_id, node["left"]))
            if "right" in node:
                queue.append((right_leaf, node["right"]))
        return indices_buf

    def _gather_split_at(self, st: _LeafState, inner_f: int,
                         bin_thr: int) -> Optional[dict]:
        """Split record for a FIXED (feature, bin) from the leaf histogram
        (reference: feature_histogram.hpp:281-419 GatherInfoForThreshold)."""
        cfg = self.config
        hrow = np.asarray(
            jax.device_get(self._hist_f32(st.hist)[inner_f]),
            dtype=np.float64)
        nb = int(np.asarray(self.f_numbins)[inner_f])
        lg, lh, lc = hrow[: bin_thr + 1].sum(axis=0)
        rg, rh, rc = st.sum_grad - lg, st.sum_hess - lh, st.count - lc
        if lc < 1 or rc < 1:
            return None

        def tl1(s):
            return np.sign(s) * max(0.0, abs(s) - cfg.lambda_l1)

        def output(g, h):
            o = -tl1(g) / (h + cfg.lambda_l2)
            if cfg.max_delta_step > 0:
                o = float(np.clip(o, -cfg.max_delta_step, cfg.max_delta_step))
            return float(np.clip(o, st.min_c, st.max_c))

        def gain_part(g, h, o):
            return -(2.0 * tl1(g) * o + (h + cfg.lambda_l2) * o * o)

        lo, ro = output(lg, lh), output(rg, rh)
        gain_shift = gain_part(
            st.sum_grad, st.sum_hess,
            output(st.sum_grad, st.sum_hess))
        gain = gain_part(lg, lh, lo) + gain_part(rg, rh, ro) - gain_shift
        return {
            "gain": float(gain), "feature": inner_f, "threshold": int(bin_thr),
            "default_left": False,
            "left_sum_grad": float(lg), "left_sum_hess": float(lh),
            "left_count": int(round(lc)),
            "right_sum_grad": float(rg), "right_sum_hess": float(rh),
            "right_count": int(round(rc)),
            "left_output": lo, "right_output": ro, "categorical": False,
        }

    def _splittable(self, leaf: _LeafState, tree: Tree) -> bool:
        cfg = self.config
        if leaf.count < 2 * cfg.min_data_in_leaf:
            return False
        if leaf.sum_hess < 2 * cfg.min_sum_hessian_in_leaf:
            return False
        if cfg.max_depth > 0 and leaf.depth >= cfg.max_depth:
            return False
        return True

    # ------------------------------------------------------------------
    def leaf_rows(self, leaf_id: int) -> np.ndarray:
        """Row indices of a leaf after training (for leaf renewal)."""
        st = self.leaves[leaf_id]
        window = jax.device_get(
            jax.lax.dynamic_slice(self.indices_buf, (st.begin,),
                                  (max(st.count, 1),)))
        return window[: st.count]


def _env(name, default):
    import os
    return os.environ.get(name, default)


def _bits_set(words: np.ndarray):
    out = []
    for wi, w in enumerate(np.asarray(words, dtype=np.uint32)):
        w = int(w)
        for b in range(32):
            if (w >> b) & 1:
                out.append(wi * 32 + b)
    return out


def _make_bitset(values) -> np.ndarray:
    if not values:
        return np.zeros(1, dtype=np.uint32)
    n_words = max(values) // 32 + 1
    out = np.zeros(n_words, dtype=np.uint32)
    for v in values:
        out[v // 32] |= np.uint32(1 << (v % 32))
    return out
