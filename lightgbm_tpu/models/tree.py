"""Tree model: flat-array binary decision tree.

Behavioral equivalent of the reference Tree (reference:
include/LightGBM/tree.h:25-535, src/io/tree.cpp). Node numbering matches the
reference exactly: internal node created by split #s has index s; leaves are
referenced as ~leaf_index (negative) in the child arrays; splitting leaf L
keeps L as the left child's leaf index and appends the right child as a new
leaf. Text/JSON serialization is format-compatible with LightGBM v2.3.1 model
files.

The tree is grown on host (tiny arrays); batch prediction runs on device via
ops/predict.py using the tensorized (split_feature, threshold, children)
arrays this class maintains.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

K_ZERO_THRESHOLD = 1e-35


def _array_to_str(arr, high_precision=False) -> str:
    out = []
    for v in arr:
        if isinstance(v, (float, np.floating)):
            if high_precision:
                out.append(repr(float(v)))
            else:
                out.append(f"{float(v):g}")
        else:
            out.append(str(int(v)))
    return " ".join(out)


class Tree:
    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        m = max(max_leaves, 2)
        self.num_leaves = 1
        self.num_cat = 0
        self.split_feature_inner = np.zeros(m - 1, dtype=np.int32)
        self.split_feature = np.zeros(m - 1, dtype=np.int32)
        self.split_gain = np.zeros(m - 1, dtype=np.float32)
        self.threshold_in_bin = np.zeros(m - 1, dtype=np.int32)
        self.threshold = np.zeros(m - 1, dtype=np.float64)
        self.decision_type = np.zeros(m - 1, dtype=np.int8)
        self.left_child = np.zeros(m - 1, dtype=np.int32)
        self.right_child = np.zeros(m - 1, dtype=np.int32)
        self.leaf_parent = np.full(m, -1, dtype=np.int32)
        self.leaf_value = np.zeros(m, dtype=np.float64)
        self.leaf_weight = np.zeros(m, dtype=np.float64)
        self.leaf_count = np.zeros(m, dtype=np.int64)
        self.internal_value = np.zeros(m - 1, dtype=np.float64)
        self.internal_weight = np.zeros(m - 1, dtype=np.float64)
        self.internal_count = np.zeros(m - 1, dtype=np.int64)
        self.leaf_depth = np.zeros(m, dtype=np.int32)
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []
        self.shrinkage = 1.0
        self.max_depth = -1
        # binned routing (threshold_in_bin / *_inner bitsets) is valid for
        # trees built by a learner; deserialized trees carry raw values
        # only until rebin_inner() reconstructs the binned side
        self.inner_valid = True

    # ------------------------------------------------------------------
    def _split_common(self, leaf: int, feature: int, real_feature: int,
                      left_value: float, right_value: float,
                      left_cnt: int, right_cnt: int,
                      left_weight: float, right_weight: float,
                      gain: float) -> int:
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = gain
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_weight[new_node] = self.leaf_weight[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = 0.0 if math.isnan(left_value) else left_value
        self.leaf_weight[leaf] = left_weight
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[self.num_leaves] = 0.0 if math.isnan(right_value) else right_value
        self.leaf_weight[self.num_leaves] = right_weight
        self.leaf_count[self.num_leaves] = right_cnt
        self.leaf_depth[self.num_leaves] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        return new_node

    def split(self, leaf: int, feature: int, real_feature: int,
              threshold_bin: int, threshold_double: float,
              left_value: float, right_value: float,
              left_cnt: int, right_cnt: int,
              left_weight: float, right_weight: float, gain: float,
              missing_type: int, default_left: bool) -> int:
        """Numerical split; returns the new (right) leaf index."""
        node = self._split_common(leaf, feature, real_feature, left_value,
                                  right_value, left_cnt, right_cnt,
                                  left_weight, right_weight, gain)
        dt = 0
        if default_left:
            dt |= K_DEFAULT_LEFT_MASK
        dt |= (missing_type & 3) << 2
        self.decision_type[node] = dt
        self.threshold_in_bin[node] = threshold_bin
        self.threshold[node] = threshold_double
        self.num_leaves += 1
        return self.num_leaves - 1

    def split_categorical(self, leaf: int, feature: int, real_feature: int,
                          threshold_bins: List[int], thresholds: List[int],
                          left_value: float, right_value: float,
                          left_cnt: int, right_cnt: int,
                          left_weight: float, right_weight: float, gain: float,
                          missing_type: int) -> int:
        """Categorical (bitset) split; thresholds are uint32 bitset words."""
        node = self._split_common(leaf, feature, real_feature, left_value,
                                  right_value, left_cnt, right_cnt,
                                  left_weight, right_weight, gain)
        dt = K_CATEGORICAL_MASK | ((missing_type & 3) << 2)
        self.decision_type[node] = dt
        self.threshold_in_bin[node] = self.num_cat
        self.threshold[node] = self.num_cat
        self.num_cat += 1
        self.cat_boundaries.append(self.cat_boundaries[-1] + len(thresholds))
        self.cat_threshold.extend(int(t) for t in thresholds)
        self.cat_boundaries_inner.append(
            self.cat_boundaries_inner[-1] + len(threshold_bins))
        self.cat_threshold_inner.extend(int(t) for t in threshold_bins)
        self.num_leaves += 1
        return self.num_leaves - 1

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        self.leaf_value[: self.num_leaves] *= rate
        self.internal_value[: max(self.num_leaves - 1, 0)] *= rate
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        self.leaf_value[: self.num_leaves] += val
        self.internal_value[: max(self.num_leaves - 1, 0)] += val
        self.shrinkage = 1.0

    def as_constant_tree(self, val: float) -> None:
        self.num_leaves = 1
        self.leaf_value[0] = val

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = value

    # ------------------------------------------------------------------
    def _is_categorical(self, node: int) -> bool:
        return bool(self.decision_type[node] & K_CATEGORICAL_MASK)

    def _default_left(self, node: int) -> bool:
        return bool(self.decision_type[node] & K_DEFAULT_LEFT_MASK)

    def _missing_type(self, node: int) -> int:
        return (int(self.decision_type[node]) >> 2) & 3

    def _cat_contains(self, cat_idx: int, val: int) -> bool:
        lo = self.cat_boundaries[cat_idx]
        hi = self.cat_boundaries[cat_idx + 1]
        word = val // 32
        if word >= hi - lo:
            return False
        return bool((self.cat_threshold[lo + word] >> (val % 32)) & 1)

    def _decision(self, fval: float, node: int) -> int:
        """Raw-value traversal (reference tree.h:221-293 Decision)."""
        if self._is_categorical(node):
            if math.isnan(fval):
                return self.right_child[node]
            ival = int(fval)
            if ival < 0:
                return self.right_child[node]
            if self._cat_contains(int(self.threshold[node]), ival):
                return self.left_child[node]
            return self.right_child[node]
        mt = self._missing_type(node)
        if math.isnan(fval) and mt != MISSING_NAN:
            fval = 0.0
        if ((mt == MISSING_ZERO and abs(fval) <= K_ZERO_THRESHOLD)
                or (mt == MISSING_NAN and math.isnan(fval))):
            return (self.left_child[node] if self._default_left(node)
                    else self.right_child[node])
        return (self.left_child[node] if fval <= self.threshold[node]
                else self.right_child[node])

    def predict_row(self, row: np.ndarray) -> float:
        if self.num_leaves <= 1:
            return float(self.leaf_value[0])
        node = 0
        while node >= 0:
            node = self._decision(float(row[self.split_feature[node]]), node)
        return float(self.leaf_value[~node])

    def predict_leaf_row(self, row: np.ndarray) -> int:
        if self.num_leaves <= 1:
            return 0
        node = 0
        while node >= 0:
            node = self._decision(float(row[self.split_feature[node]]), node)
        return ~node

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        """Model text block (reference src/io/tree.cpp:209 Tree::ToString)."""
        nl = self.num_leaves
        lines = [f"num_leaves={nl}", f"num_cat={self.num_cat}"]
        n_int = max(nl - 1, 0)
        lines.append("split_feature=" + _array_to_str(self.split_feature[:n_int]))
        lines.append("split_gain=" + _array_to_str(self.split_gain[:n_int]))
        lines.append("threshold=" + _array_to_str(
            [float(t) for t in self.threshold[:n_int]], high_precision=True))
        lines.append("decision_type=" + _array_to_str(self.decision_type[:n_int]))
        lines.append("left_child=" + _array_to_str(self.left_child[:n_int]))
        lines.append("right_child=" + _array_to_str(self.right_child[:n_int]))
        lines.append("leaf_value=" + _array_to_str(
            [float(v) for v in self.leaf_value[:nl]], high_precision=True))
        lines.append("leaf_weight=" + _array_to_str(
            [float(v) for v in self.leaf_weight[:nl]], high_precision=True))
        lines.append("leaf_count=" + _array_to_str(self.leaf_count[:nl]))
        lines.append("internal_value=" + _array_to_str(
            [float(v) for v in self.internal_value[:n_int]]))
        lines.append("internal_weight=" + _array_to_str(
            [float(v) for v in self.internal_weight[:n_int]]))
        lines.append("internal_count=" + _array_to_str(self.internal_count[:n_int]))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + _array_to_str(self.cat_boundaries))
            lines.append("cat_threshold=" + _array_to_str(self.cat_threshold))
        lines.append(f"shrinkage={self.shrinkage:g}")
        lines.append("")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        """Parse a Tree= block (reference src/io/tree.cpp:481 parse ctor)."""
        kv = {}
        for line in text.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        nl = int(kv["num_leaves"])
        t = cls(max(nl, 2))
        t.num_leaves = nl
        t.num_cat = int(kv.get("num_cat", 0))
        t.shrinkage = float(kv.get("shrinkage", 1.0))

        def ints(key, n):
            if n == 0 or key not in kv or not kv[key].strip():
                return np.zeros(n, dtype=np.int64)
            return np.fromstring(kv[key], dtype=np.float64, sep=" ").astype(np.int64)[:n]

        def floats(key, n):
            if n == 0 or key not in kv or not kv[key].strip():
                return np.zeros(n, dtype=np.float64)
            return np.fromstring(kv[key], dtype=np.float64, sep=" ")[:n]

        n_int = max(nl - 1, 0)
        t.split_feature[:n_int] = ints("split_feature", n_int)
        t.split_feature_inner[:n_int] = t.split_feature[:n_int]
        t.split_gain[:n_int] = floats("split_gain", n_int)
        t.threshold[:n_int] = floats("threshold", n_int)
        t.decision_type[:n_int] = ints("decision_type", n_int)
        t.left_child[:n_int] = ints("left_child", n_int)
        t.right_child[:n_int] = ints("right_child", n_int)
        t.leaf_value[:nl] = floats("leaf_value", nl)
        t.leaf_weight[:nl] = floats("leaf_weight", nl)
        t.leaf_count[:nl] = ints("leaf_count", nl)
        t.internal_value[:n_int] = floats("internal_value", n_int)
        t.internal_weight[:n_int] = floats("internal_weight", n_int)
        t.internal_count[:n_int] = ints("internal_count", n_int)
        if t.num_cat > 0:
            t.cat_boundaries = list(ints("cat_boundaries", t.num_cat + 1))
            ncat_words = t.cat_boundaries[-1]
            t.cat_threshold = [int(x) for x in ints("cat_threshold", ncat_words)]
            # inner thresholds unavailable after load; raw-value traversal
            # only, until rebin_inner() runs against a dataset
            t.cat_boundaries_inner = list(t.cat_boundaries)
            t.cat_threshold_inner = list(t.cat_threshold)
        t.inner_valid = False
        t.recompute_depths()
        return t

    def rebin_inner(self, dataset) -> None:
        """Reconstruct the binned routing of a deserialized tree from the
        dataset's bin mappers, so score replay over binned data
        (ScoreUpdater.add_tree) routes identically to raw traversal.

        Model text stores raw thresholds (the bin upper bound,
        BinMapper.bin_to_value) and real category values; the inverse maps
        are exact: value_to_bin(upper_bound[b]) == b and
        categorical_2_bin[real_cat] == bin. The reference never needs this
        (its Predictor replays over raw rows, predictor.hpp); our replay
        path runs on the device-resident binned matrix instead."""
        n_int = max(self.num_leaves - 1, 0)
        cat_bounds = [0]
        cat_words: List[int] = []
        for node in range(n_int):
            mapper = dataset.bin_mappers[int(self.split_feature[node])]
            if self.decision_type[node] & K_CATEGORICAL_MASK:
                # for a deserialized tree the cat index rides threshold
                # (split_categorical stores it in both fields)
                ci = int(self.threshold[node])
                self.threshold_in_bin[node] = ci
                lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                bins = []
                for w, word in enumerate(self.cat_threshold[lo:hi]):
                    for b in range(32):
                        if (int(word) >> b) & 1:
                            cat = w * 32 + b
                            bin_i = mapper.categorical_2_bin.get(cat)
                            if bin_i is not None:
                                bins.append(bin_i)
                n_words = (max(bins) // 32 + 1) if bins else 1
                words = [0] * n_words
                for b in bins:
                    words[b // 32] |= 1 << (b % 32)
                cat_words.extend(words)
                cat_bounds.append(cat_bounds[-1] + n_words)
            else:
                self.threshold_in_bin[node] = mapper.value_to_bin(
                    float(self.threshold[node]))
        if self.num_cat > 0:
            self.cat_boundaries_inner = cat_bounds
            self.cat_threshold_inner = cat_words
        self.inner_valid = True

    def recompute_depths(self) -> None:
        """Rebuild leaf_depth from the children arrays (reference
        Tree::RecomputeMaxDepth)."""
        if self.num_leaves <= 1:
            self.leaf_depth[0] = 0
            return
        stack = [(0, 0)]
        while stack:
            node, d = stack.pop()
            for child in (self.left_child[node], self.right_child[node]):
                if child >= 0:
                    stack.append((child, d + 1))
                else:
                    self.leaf_depth[~child] = d + 1

    def _node_to_json(self, node: int, feature_names=None) -> dict:
        if node >= 0:
            d = {
                "split_index": int(node),
                "split_feature": int(self.split_feature[node]),
                "split_gain": float(self.split_gain[node]),
                "threshold": (float(self.threshold[node])
                              if not self._is_categorical(node)
                              else "||".join(
                                  str(c) for c in self._cats_for_node(node))),
                "decision_type": ("==" if self._is_categorical(node) else "<="),
                "default_left": self._default_left(node),
                "missing_type": ["None", "Zero", "NaN"][self._missing_type(node)],
                "internal_value": float(self.internal_value[node]),
                "internal_weight": float(self.internal_weight[node]),
                "internal_count": int(self.internal_count[node]),
            }
            d["left_child"] = self._node_to_json(self.left_child[node])
            d["right_child"] = self._node_to_json(self.right_child[node])
            return d
        leaf = ~node
        return {
            "leaf_index": int(leaf),
            "leaf_value": float(self.leaf_value[leaf]),
            "leaf_weight": float(self.leaf_weight[leaf]),
            "leaf_count": int(self.leaf_count[leaf]),
        }

    def _cats_for_node(self, node: int) -> List[int]:
        cat_idx = int(self.threshold[node])
        lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
        cats = []
        for w in range(lo, hi):
            word = self.cat_threshold[w]
            for b in range(32):
                if (word >> b) & 1:
                    cats.append((w - lo) * 32 + b)
        return cats

    def to_json(self) -> dict:
        out = {"num_leaves": int(self.num_leaves), "num_cat": int(self.num_cat),
               "shrinkage": float(self.shrinkage)}
        if self.num_leaves == 1:
            out["tree_structure"] = {"leaf_value": float(self.leaf_value[0])}
        else:
            out["tree_structure"] = self._node_to_json(0)
        return out

    # ------------------------------------------------------------------
    def depth(self) -> int:
        return int(self.leaf_depth[: self.num_leaves].max()) if self.num_leaves > 1 else 0
