"""Whole-tree-on-device leaf-wise learner.

The host-loop learner (serial_learner.py) mirrors the reference's phase
structure (serial_tree_learner.cpp:173-237) and pays one host round-trip per
split — ruinous through a tunneled TPU, and every distinct leaf size
recompiles a bucket shape. This learner is the TPU-native answer flagged in
SURVEY.md §7 ("leaf-wise growth is inherently dynamic-shape"): grow the
ENTIRE tree inside one jitted `lax.while_loop` with static shapes.

Design deltas vs the reference's DataPartition/HistogramPool machinery:

* No permutation buffer. Row membership is a dense (N,) `leaf_id` vector;
  a split rewrites it with a masked `where` — O(N) elementwise, no sort.
* Histograms are built over the FULL row set with per-row weights
  `gh * (leaf_id == leaf)`. O(N) per split instead of O(leaf), but the
  histogram path runs at HBM speed on the MXU (ops/pallas), so N x (L-1)
  work is orders of magnitude cheaper than L-1 host syncs.
* The histogram pool (feature_histogram.hpp:654-831) becomes a dense
  (L, F, B, 3) device array: parent slot is overwritten by the left child,
  the right child is parent - left (FeatureHistogram::Subtract semantics).
* Per-split records (split leaf, feature, bin, gain, child stats) are
  written into (L-1,) arrays; the host replays them into a `Tree` after the
  loop — one device->host transfer per tree.
* Leaf-wise leaf selection = argmax over the (L,) per-leaf best-gain array,
  exactly the `best_split_per_leaf_` argmax of the reference.

Monotone constraints propagate like serial_tree_learner.cpp:771-852 (basic
mode); depth limits gate stored gains. Categorical splits, forced splits and
CEGB fall back to the host-loop learner (create_tree_learner picks).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config
from ..io.binning import BIN_CATEGORICAL
from ..io.dataset import Dataset
from ..ops import bundle as bundle_ops
from ..ops import split as split_ops
from ..ops.partition import decide_left
from ..ops.pallas.histogram_kernel import build_histogram_pallas_t
from ..utils import log
from ..utils.envs import use_pallas_env
from .tree import Tree

NEG_INF = split_ops.NEG_INF
_POOL_BYTE_LIMIT = 2 << 30


def _env(name, default):
    import os
    return os.environ.get(name, default)


class _Best(NamedTuple):
    """Per-leaf best-split state, all (L,) arrays (the device analog of the
    reference's best_split_per_leaf_)."""
    gain: jax.Array
    feat: jax.Array
    thr: jax.Array
    dleft: jax.Array
    lsg: jax.Array
    lsh: jax.Array
    lcnt: jax.Array
    rsg: jax.Array
    rsh: jax.Array
    rcnt: jax.Array
    lout: jax.Array
    rout: jax.Array


class _Rec(NamedTuple):
    """Per-split records, all (L-1,) arrays, replayed on host into a Tree."""
    leaf: jax.Array
    feat: jax.Array
    thr: jax.Array
    dleft: jax.Array
    gain: jax.Array
    lsg: jax.Array
    lsh: jax.Array
    lcnt: jax.Array
    rsg: jax.Array
    rsh: jax.Array
    rcnt: jax.Array
    lout: jax.Array
    rout: jax.Array


class _Carry(NamedTuple):
    k: jax.Array
    leaf_id: jax.Array
    pool: jax.Array
    depth: jax.Array
    leaf_min: jax.Array
    leaf_max: jax.Array
    best: _Best
    rec: _Rec
    key: jax.Array


def _hist_t(codes_t, gh, num_bins, use_pallas):
    if use_pallas:
        return build_histogram_pallas_t(codes_t, gh, num_bins)
    from ..ops.histogram import build_histogram
    return build_histogram(jnp.swapaxes(codes_t, 0, 1), gh, num_bins,
                           use_pallas=False)


def _tree_helpers(base_mask, f_numbins, f_missing, f_default, f_monotone,
                  f_penalty, f_elide, hist_idx, *, num_bins, max_depth,
                  l1, l2, max_delta_step, min_data_in_leaf, min_sum_hessian,
                  min_gain_to_split, bynode_k):
    """Shared pieces of both growth strategies: per-node feature sampling,
    the (expand + scan + materialize) split search, and per-leaf best-state
    stores with depth gating."""
    f = f_numbins.shape[0]
    scan_kwargs = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)

    def node_mask(key):
        if bynode_k <= 0:
            return base_mask
        u = jnp.where(base_mask, jax.random.uniform(key, (f,)), jnp.inf)
        kth = jnp.sort(u)[bynode_k - 1]
        return base_mask & (u <= kth)

    def scan(col_hist, sg, sh, cnt, mn, mx, fmask):
        hist = bundle_ops.expand_column_hist(
            col_hist, jnp.stack([sg, sh, cnt]), hist_idx, f_elide, f_default)
        rel, t, use_m1, prefix = split_ops.per_feature_best(
            hist, sg, sh, cnt, f_numbins, f_missing, f_default, fmask,
            f_monotone, mn, mx, f_penalty, None, **scan_kwargs)
        feat = jnp.argmax(rel).astype(jnp.int32)
        return split_ops.materialize_split(
            feat, rel, t, use_m1, prefix, sg, sh, cnt, mn, mx,
            l1=l1, l2=l2, max_delta_step=max_delta_step)

    def store_best(best: _Best, i, res: split_ops.SplitResult,
                   child_depth) -> _Best:
        gain = res.gain
        if max_depth > 0:
            gain = jnp.where(child_depth >= max_depth, NEG_INF, gain)
        return _Best(
            best.gain.at[i].set(gain), best.feat.at[i].set(res.feature),
            best.thr.at[i].set(res.threshold),
            best.dleft.at[i].set(res.default_left),
            best.lsg.at[i].set(res.left_sum_grad),
            best.lsh.at[i].set(res.left_sum_hess),
            best.lcnt.at[i].set(res.left_count),
            best.rsg.at[i].set(res.right_sum_grad),
            best.rsh.at[i].set(res.right_sum_hess),
            best.rcnt.at[i].set(res.right_count),
            best.lout.at[i].set(res.left_output),
            best.rout.at[i].set(res.right_output))

    return node_mask, scan, store_best


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "col_bins", "max_depth",
                     "l1", "l2",
                     "max_delta_step", "min_data_in_leaf", "min_sum_hessian",
                     "min_gain_to_split", "bynode_k", "use_pallas"))
def grow_tree(codes_t: jax.Array,         # (C, N) column codes (EFB view)
              grad: jax.Array, hess: jax.Array,   # (N,)
              w: jax.Array,               # (N,) bagging weight (0/1)
              base_mask: jax.Array,       # (F,) bool feature sample
              f_numbins, f_missing, f_default, f_monotone,  # (F,) int32
              f_penalty,                  # (F,) f32 gain multipliers
              f_col, f_base, f_elide,     # (F,) int32 EFB maps
              hist_idx,                   # (F, B) int32 expansion gather
              rng_key,                    # PRNG key for by-node sampling
              *, num_leaves: int, num_bins: int, col_bins: int,
              max_depth: int,
              l1: float, l2: float, max_delta_step: float,
              min_data_in_leaf: int, min_sum_hessian: float,
              min_gain_to_split: float, bynode_k: int, use_pallas: bool):
    c_cols, n = codes_t.shape
    f = f_numbins.shape[0]
    L = num_leaves
    gh = jnp.stack([grad * w, hess * w, w], axis=1)     # (N, 3)
    node_mask, scan, store_best = _tree_helpers(
        base_mask, f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_elide, hist_idx,
        num_bins=num_bins, max_depth=max_depth, l1=l1, l2=l2,
        max_delta_step=max_delta_step, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian=min_sum_hessian, min_gain_to_split=min_gain_to_split,
        bynode_k=bynode_k)

    # ---- root ------------------------------------------------------------
    hist0 = _hist_t(codes_t, gh, col_bins, use_pallas)
    totals = hist0[0].sum(axis=0)                       # (3,): sum_g, sum_h, cnt
    root_key, loop_key = jax.random.split(rng_key)
    root_res = scan(hist0, totals[0], totals[1], totals[2],
                    jnp.float32(-np.inf), jnp.float32(np.inf),
                    node_mask(root_key))

    zf = functools.partial(jnp.zeros, dtype=jnp.float32)
    zi = functools.partial(jnp.zeros, dtype=jnp.int32)
    best = _Best(jnp.full((L,), NEG_INF, jnp.float32), zi(L), zi(L),
                 jnp.zeros(L, bool), zf(L), zf(L), zf(L), zf(L), zf(L),
                 zf(L), zf(L), zf(L))
    # the depth argument is the stored leaf's own depth (a leaf at depth d
    # may split iff d < max_depth, reference _splittable); root sits at 0
    best = store_best(best, 0, root_res, jnp.int32(0))
    pool = jnp.zeros((L, c_cols, col_bins, 3), jnp.float32).at[0].set(hist0)
    rec = _Rec(zi(L - 1), zi(L - 1), zi(L - 1), jnp.zeros(L - 1, bool),
               zf(L - 1), zf(L - 1), zf(L - 1), zf(L - 1), zf(L - 1),
               zf(L - 1), zf(L - 1), zf(L - 1), zf(L - 1))
    carry = _Carry(
        k=jnp.int32(0), leaf_id=jnp.zeros(n, jnp.int32), pool=pool,
        depth=zi(L),
        leaf_min=jnp.full((L,), -np.inf, jnp.float32),
        leaf_max=jnp.full((L,), np.inf, jnp.float32),
        best=best, rec=rec, key=loop_key)

    def cond(c: _Carry):
        return (c.k < L - 1) & (jnp.max(c.best.gain) > 1e-10)

    def body(c: _Carry) -> _Carry:
        b = c.best
        l = jnp.argmax(b.gain).astype(jnp.int32)
        new_id = c.k + 1
        feat = b.feat[l]
        thr = b.thr[l]
        dleft = b.dleft[l]

        col = jax.lax.dynamic_slice_in_dim(codes_t, f_col[feat], 1, axis=0)[0]
        fbins = bundle_ops.logical_bins_for_feature(
            col.astype(jnp.int32), f_base[feat], f_default[feat],
            f_numbins[feat], f_elide[feat])
        go_left = decide_left(fbins, thr, dleft,
                              f_missing[feat], f_default[feat], f_numbins[feat])
        parent = c.leaf_id == l
        lmask = parent & go_left
        leaf_id = jnp.where(parent & ~go_left, new_id, c.leaf_id)

        ghl = gh * lmask[:, None].astype(jnp.float32)
        hist_l = _hist_t(codes_t, ghl, col_bins, use_pallas)
        hist_r = c.pool[l] - hist_l
        pool = c.pool.at[l].set(hist_l).at[new_id].set(hist_r)

        # monotone constraint propagation (basic mode)
        mono_f = f_monotone[feat]
        mid = (b.lout[l] + b.rout[l]) * 0.5
        pmin, pmax = c.leaf_min[l], c.leaf_max[l]
        lmin = jnp.where(mono_f < 0, jnp.maximum(pmin, mid), pmin)
        lmax = jnp.where(mono_f > 0, jnp.minimum(pmax, mid), pmax)
        rmin = jnp.where(mono_f > 0, jnp.maximum(pmin, mid), pmin)
        rmax = jnp.where(mono_f < 0, jnp.minimum(pmax, mid), pmax)
        leaf_min = c.leaf_min.at[l].set(lmin).at[new_id].set(rmin)
        leaf_max = c.leaf_max.at[l].set(lmax).at[new_id].set(rmax)
        child_depth = c.depth[l] + 1
        depth = c.depth.at[l].set(child_depth).at[new_id].set(child_depth)

        rec = _Rec(
            c.rec.leaf.at[c.k].set(l), c.rec.feat.at[c.k].set(feat),
            c.rec.thr.at[c.k].set(thr), c.rec.dleft.at[c.k].set(dleft),
            c.rec.gain.at[c.k].set(b.gain[l]),
            c.rec.lsg.at[c.k].set(b.lsg[l]), c.rec.lsh.at[c.k].set(b.lsh[l]),
            c.rec.lcnt.at[c.k].set(b.lcnt[l]),
            c.rec.rsg.at[c.k].set(b.rsg[l]), c.rec.rsh.at[c.k].set(b.rsh[l]),
            c.rec.rcnt.at[c.k].set(b.rcnt[l]),
            c.rec.lout.at[c.k].set(b.lout[l]),
            c.rec.rout.at[c.k].set(b.rout[l]))

        key, kl, kr = jax.random.split(c.key, 3)
        res_l = scan(hist_l, b.lsg[l], b.lsh[l], b.lcnt[l], lmin, lmax,
                     node_mask(kl))
        res_r = scan(hist_r, b.rsg[l], b.rsh[l], b.rcnt[l], rmin, rmax,
                     node_mask(kr))
        best = store_best(b, l, res_l, child_depth)
        best = store_best(best, new_id, res_r, child_depth)
        return _Carry(new_id, leaf_id, pool, depth, leaf_min, leaf_max,
                      best, rec, key)

    out = jax.lax.while_loop(cond, body, carry)
    return out.rec, out.leaf_id, out.k, totals


class _CarryC(NamedTuple):
    k: jax.Array
    perm: jax.Array          # (N + Wmax,) row ids grouped by leaf window
    pos_leaf: jax.Array      # (N + Wmax,) leaf id per PERM POSITION
    leaf_begin: jax.Array    # (L,)
    leaf_phys: jax.Array     # (L,) physical rows in the window
    pool: jax.Array
    depth: jax.Array
    leaf_min: jax.Array
    leaf_max: jax.Array
    best: "_Best"
    rec: "_Rec"
    key: jax.Array


def _size_classes(n: int, min_bucket: int = 4096, step: int = 4):
    ws = []
    wcur = min_bucket
    while wcur < n:
        ws.append(wcur)
        wcur *= step
    ws.append(n)
    return ws


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "col_bins", "max_depth",
                     "l1", "l2", "max_delta_step", "min_data_in_leaf",
                     "min_sum_hessian", "min_gain_to_split", "bynode_k",
                     "use_pallas"))
def grow_tree_compact(
        codes: jax.Array,            # (N, C) row-major for window gathers
        codes_t: jax.Array,          # (C, N) for the root pass
        grad: jax.Array, hess: jax.Array, w: jax.Array,
        base_mask: jax.Array,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_col, f_base, f_elide, hist_idx, rng_key,
        *, num_leaves: int, num_bins: int, col_bins: int, max_depth: int,
        l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: int, min_sum_hessian: float,
        min_gain_to_split: float, bynode_k: int, use_pallas: bool):
    """Compaction-based whole-tree growth: O(leaf-size) work per split.

    The masked strategy in grow_tree pays a full O(N) histogram pass per
    split — ruinous at Higgs scale. This variant keeps the reference's
    DataPartition idea (data_partition.hpp:20-205) on device: a permutation
    buffer groups rows by leaf, each split gathers ONLY the split leaf's
    window, partitions it with a stable 2-bit-key sort, and builds the
    SMALLER child's histogram from the gathered window (sibling =
    parent - smaller, FeatureHistogram::Subtract). Dynamic leaf sizes meet
    XLA's static shapes through a small ladder of padded window classes
    (x4 steps) dispatched with lax.switch — each class is traced once.
    """
    c_cols, n = codes_t.shape
    L = num_leaves
    gh = jnp.stack([grad * w, hess * w, w], axis=1)
    node_mask, scan, store_best = _tree_helpers(
        base_mask, f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_elide, hist_idx,
        num_bins=num_bins, max_depth=max_depth, l1=l1, l2=l2,
        max_delta_step=max_delta_step, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian=min_sum_hessian, min_gain_to_split=min_gain_to_split,
        bynode_k=bynode_k)

    classes = _size_classes(n)
    wmax = classes[-1]
    thresholds = jnp.asarray(np.array(classes[:-1], np.int32))

    # ---- root ------------------------------------------------------------
    hist0 = _hist_t(codes_t, gh, col_bins, use_pallas)
    totals = hist0[0].sum(axis=0)
    root_key, loop_key = jax.random.split(rng_key)
    root_res = scan(hist0, totals[0], totals[1], totals[2],
                    jnp.float32(-np.inf), jnp.float32(np.inf),
                    node_mask(root_key))

    zf = functools.partial(jnp.zeros, dtype=jnp.float32)
    zi = functools.partial(jnp.zeros, dtype=jnp.int32)
    best = _Best(jnp.full((L,), NEG_INF, jnp.float32), zi(L), zi(L),
                 jnp.zeros(L, bool), zf(L), zf(L), zf(L), zf(L), zf(L),
                 zf(L), zf(L), zf(L))
    best = store_best(best, 0, root_res, jnp.int32(0))
    pool = jnp.zeros((L, c_cols, col_bins, 3), jnp.float32).at[0].set(hist0)
    rec = _Rec(zi(L - 1), zi(L - 1), zi(L - 1), jnp.zeros(L - 1, bool),
               zf(L - 1), zf(L - 1), zf(L - 1), zf(L - 1), zf(L - 1),
               zf(L - 1), zf(L - 1), zf(L - 1), zf(L - 1))
    carry = _CarryC(
        k=jnp.int32(0),
        perm=jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                              jnp.zeros(wmax, jnp.int32)]),
        pos_leaf=jnp.zeros(n + wmax, jnp.int32),
        leaf_begin=zi(L), leaf_phys=zi(L).at[0].set(n),
        pool=pool, depth=zi(L),
        leaf_min=jnp.full((L,), -np.inf, jnp.float32),
        leaf_max=jnp.full((L,), np.inf, jnp.float32),
        best=best, rec=rec, key=loop_key)

    def cond(c: _CarryC):
        return (c.k < L - 1) & (jnp.max(c.best.gain) > 1e-10)

    def make_branch(wsz: int):
        def branch(c: _CarryC) -> _CarryC:
            b = c.best
            l = jnp.argmax(b.gain).astype(jnp.int32)
            new_id = c.k + 1
            feat = b.feat[l]
            begin = c.leaf_begin[l]
            pcount = c.leaf_phys[l]

            window = jax.lax.dynamic_slice(c.perm, (begin,), (wsz,))
            valid = jnp.arange(wsz, dtype=jnp.int32) < pcount
            rows = jnp.take(codes, window, axis=0)        # (W, C)
            col = jax.lax.dynamic_slice_in_dim(
                rows, f_col[feat], 1, axis=1)[:, 0].astype(jnp.int32)
            fbins = bundle_ops.logical_bins_for_feature(
                col, f_base[feat], f_default[feat], f_numbins[feat],
                f_elide[feat])
            go_left = decide_left(fbins, b.thr[l], b.dleft[l],
                                  f_missing[feat], f_default[feat],
                                  f_numbins[feat]) & valid

            # stable partition of the window (reference DataPartition::Split)
            key3 = jnp.where(valid, jnp.where(go_left, 0, 1), 2)
            order = jnp.argsort(key3.astype(jnp.int8), stable=True)
            new_window = window[order]
            perm = jax.lax.dynamic_update_slice(c.perm, new_window, (begin,))
            lphys = jnp.sum(go_left.astype(jnp.int32))

            pos = jnp.arange(wsz, dtype=jnp.int32)
            old_slice = jax.lax.dynamic_slice(c.pos_leaf, (begin,), (wsz,))
            new_slice = jnp.where(pos < lphys, l,
                                  jnp.where(pos < pcount, new_id, old_slice))
            pos_leaf = jax.lax.dynamic_update_slice(
                c.pos_leaf, new_slice, (begin,))

            leaf_begin = c.leaf_begin.at[new_id].set(begin + lphys)
            leaf_phys = c.leaf_phys.at[l].set(lphys).at[new_id].set(
                pcount - lphys)

            # smaller child's histogram from the (unsorted) gathered window
            left_small = lphys * 2 <= pcount
            small_mask = jnp.where(left_small, go_left, valid & ~go_left)
            gh_w = jnp.take(gh, window, axis=0) * small_mask[:, None]
            hist_small = _hist_t(jnp.swapaxes(rows, 0, 1), gh_w, col_bins,
                                 use_pallas)
            parent = c.pool[l]
            hist_l = jnp.where(left_small, hist_small, parent - hist_small)
            hist_r = jnp.where(left_small, parent - hist_small, hist_small)
            pool = c.pool.at[l].set(hist_l).at[new_id].set(hist_r)

            # monotone propagation + depth (same as masked strategy)
            mono_f = f_monotone[feat]
            mid = (b.lout[l] + b.rout[l]) * 0.5
            pmin, pmax = c.leaf_min[l], c.leaf_max[l]
            lmin = jnp.where(mono_f < 0, jnp.maximum(pmin, mid), pmin)
            lmax = jnp.where(mono_f > 0, jnp.minimum(pmax, mid), pmax)
            rmin = jnp.where(mono_f > 0, jnp.maximum(pmin, mid), pmin)
            rmax = jnp.where(mono_f < 0, jnp.minimum(pmax, mid), pmax)
            leaf_min = c.leaf_min.at[l].set(lmin).at[new_id].set(rmin)
            leaf_max = c.leaf_max.at[l].set(lmax).at[new_id].set(rmax)
            child_depth = c.depth[l] + 1
            depth = c.depth.at[l].set(child_depth).at[new_id].set(child_depth)

            rec2 = _Rec(
                c.rec.leaf.at[c.k].set(l), c.rec.feat.at[c.k].set(feat),
                c.rec.thr.at[c.k].set(b.thr[l]),
                c.rec.dleft.at[c.k].set(b.dleft[l]),
                c.rec.gain.at[c.k].set(b.gain[l]),
                c.rec.lsg.at[c.k].set(b.lsg[l]),
                c.rec.lsh.at[c.k].set(b.lsh[l]),
                c.rec.lcnt.at[c.k].set(b.lcnt[l]),
                c.rec.rsg.at[c.k].set(b.rsg[l]),
                c.rec.rsh.at[c.k].set(b.rsh[l]),
                c.rec.rcnt.at[c.k].set(b.rcnt[l]),
                c.rec.lout.at[c.k].set(b.lout[l]),
                c.rec.rout.at[c.k].set(b.rout[l]))

            key, kl, kr = jax.random.split(c.key, 3)
            res_l = scan(hist_l, b.lsg[l], b.lsh[l], b.lcnt[l], lmin, lmax,
                         node_mask(kl))
            res_r = scan(hist_r, b.rsg[l], b.rsh[l], b.rcnt[l], rmin, rmax,
                         node_mask(kr))
            best2 = store_best(b, l, res_l, child_depth)
            best2 = store_best(best2, new_id, res_r, child_depth)
            return _CarryC(new_id, perm, pos_leaf, leaf_begin, leaf_phys,
                           pool, depth, leaf_min, leaf_max, best2, rec2, key)
        return branch

    branches = [make_branch(wsz) for wsz in classes]

    def body(c: _CarryC) -> _CarryC:
        l = jnp.argmax(c.best.gain).astype(jnp.int32)
        pcount = c.leaf_phys[l]
        j = jnp.sum((pcount > thresholds).astype(jnp.int32))
        return jax.lax.switch(j, branches, c)

    out = jax.lax.while_loop(cond, body, carry)
    # final row -> leaf map: scatter window-position leaves onto row ids
    leaf_id = jnp.zeros(n, jnp.int32).at[out.perm[:n]].set(
        out.pos_leaf[:n], unique_indices=True)
    return out.rec, leaf_id, out.k, totals


class DeviceTreeLearner:
    """Drop-in TreeLearner whose Train runs one jitted program per tree."""

    def __init__(self, config: Config, dataset: Dataset):
        self.config = config
        self.dataset = dataset
        (self.f_numbins, self.f_missing, self.f_default,
         self.f_categorical, self.f_monotone) = dataset.feature_meta_arrays()
        self.num_features = dataset.num_features
        self.num_bins = int(dataset.max_num_bins)
        b = 1 << max(4, (self.num_bins - 1).bit_length())
        self.device_bins = min(b, 256) if self.num_bins <= 256 else b
        bundle = dataset.bundle_arrays()
        if bundle is not None:
            codes, f_col, f_base, f_elide, hist_idx, col_bins = bundle
            self.codes_t = jnp.asarray(jnp.swapaxes(codes, 0, 1))  # (C, N)
            self.f_col, self.f_base, self.f_elide = f_col, f_base, f_elide
            cb = 1 << max(4, (int(col_bins) - 1).bit_length())
            self.col_device_bins = min(cb, 256) if col_bins <= 256 else cb
            # pad hist_idx bin axis to device_bins; pad slots hit the
            # trailing zero entry of the flattened column histogram
            zero_slot = len(dataset.columns) * self.col_device_bins
            hi = np.asarray(hist_idx)
            # re-space flat indices for the padded column bin count
            raw_cb = int(col_bins)
            cols_i = hi // raw_cb
            bins_i = hi % raw_cb
            invalid = hi == (len(dataset.columns) * raw_cb)
            hi2 = np.where(invalid, zero_slot,
                           cols_i * self.col_device_bins + bins_i)
            pad = self.device_bins - hi2.shape[1]
            if pad > 0:
                hi2 = np.concatenate(
                    [hi2, np.full((hi2.shape[0], pad), zero_slot, np.int32)],
                    axis=1)
            self.hist_idx = jnp.asarray(hi2.astype(np.int32))
        else:
            binned = dataset.device_binned()
            self.codes_t = jnp.asarray(jnp.swapaxes(binned, 0, 1))  # (F, N)
            nf = self.num_features
            self.f_col = jnp.arange(nf, dtype=jnp.int32)
            self.f_base = jnp.zeros(nf, jnp.int32)
            self.f_elide = jnp.zeros(nf, jnp.int32)
            self.col_device_bins = self.device_bins
            zero_slot = nf * self.device_bins
            hi = (np.arange(nf, dtype=np.int64)[:, None] * self.device_bins
                  + np.arange(self.device_bins)[None, :])
            nb = np.asarray(self.f_numbins)[:, None]
            hi = np.where(np.arange(self.device_bins)[None, :] < nb,
                          hi, zero_slot)
            self.hist_idx = jnp.asarray(hi.astype(np.int32))
        contri = config.feature_contri or []
        pen = np.array([contri[fr] if fr < len(contri) else 1.0
                        for fr in dataset.used_features], dtype=np.float32)
        self.f_penalty = jnp.asarray(pen)
        # Measured on v5e (tools/microbench_injit.py): the XLA one-hot
        # contraction beats the Pallas kernel ~2.4x (XLA fuses the one-hot
        # build into the matmul pipeline better than Mosaic schedules it),
        # so the fused XLA path is the default even on TPU.
        self._use_pallas = use_pallas_env() and jax.default_backend() == "tpu"
        # strategy: compaction pays off once O(N)-per-split masked passes
        # dominate; small data stays on the simpler masked program
        strat = _env("LGBM_TPU_STRATEGY", "auto")
        if strat == "auto":
            strat = "compact" if dataset.num_data >= 65536 else "masked"
        self.strategy = strat
        if self.strategy == "compact":
            host_codes = (dataset.bundled if dataset.bundled is not None
                          else dataset.binned)
            self.codes_row = jnp.asarray(host_codes)      # (N, C)
        else:
            self.codes_row = None
        self._ones_w = None
        self.last_leaf_id: Optional[jax.Array] = None
        self._leaf_id_host: Optional[np.ndarray] = None
        self._bag_mask_host: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    @staticmethod
    def supports(config: Config, dataset: Dataset) -> bool:
        """Static capability check; unsupported configs use the host-loop
        learner (create_tree_learner falls back)."""
        if any(dataset.bin_mappers[fr].bin_type == BIN_CATEGORICAL
               for fr in dataset.used_features):
            return False
        if config.forcedsplits_filename:
            return False
        if config.cegb_tradeoff > 0 and (
                config.cegb_penalty_split > 0
                or bool(config.cegb_penalty_feature_coupled)
                or bool(config.cegb_penalty_feature_lazy)):
            return False
        # mirror __init__'s pool sizing exactly: bundled column count when
        # EFB is active, and the same pow2 bin padding (only clamped to 256
        # when the logical bin count itself is <= 256)
        if dataset.columns:
            ncols = max(1, len(dataset.columns))
            raw_bins = max(c.num_bins for c in dataset.columns)
        else:
            ncols = max(1, dataset.num_features)
            raw_bins = int(dataset.max_num_bins)
        nb = 1 << max(4, (raw_bins - 1).bit_length())
        device_bins = min(nb, 256) if raw_bins <= 256 else nb
        pool_bytes = config.num_leaves * ncols * device_bins * 3 * 4
        if pool_bytes > _POOL_BYTE_LIMIT:
            return False
        return True

    def _statics(self):
        cfg = self.config
        bynode_k = 0
        if 0.0 < cfg.feature_fraction_bynode < 1.0:
            bynode_k = max(1, int(self.num_features * cfg.feature_fraction_bynode))
        return dict(
            num_leaves=int(cfg.num_leaves), num_bins=self.device_bins,
            col_bins=self.col_device_bins,
            max_depth=int(cfg.max_depth), l1=float(cfg.lambda_l1),
            l2=float(cfg.lambda_l2),
            max_delta_step=float(cfg.max_delta_step),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            min_sum_hessian=float(cfg.min_sum_hessian_in_leaf),
            min_gain_to_split=float(cfg.min_gain_to_split),
            bynode_k=bynode_k, use_pallas=self._use_pallas)

    def _feature_mask(self, rng: np.random.RandomState) -> np.ndarray:
        frac = self.config.feature_fraction
        mask = np.ones(self.num_features, dtype=bool)
        if 0.0 < frac < 1.0:
            k = max(1, int(self.num_features * frac))
            chosen = rng.choice(self.num_features, k, replace=False)
            mask[:] = False
            mask[chosen] = True
        return mask

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              bag_indices: Optional[np.ndarray] = None,
              iter_seed: int = 0) -> Tree:
        cfg = self.config
        ds = self.dataset
        n = ds.num_data
        if bag_indices is None:
            if self._ones_w is None:
                self._ones_w = jnp.ones(n, jnp.float32)
            w = self._ones_w
            self._bag_mask_host = None
        else:
            wv = np.zeros(n, dtype=np.float32)
            wv[bag_indices] = 1.0
            w = jnp.asarray(wv)
            self._bag_mask_host = wv > 0
        rng = np.random.RandomState(
            (cfg.feature_fraction_seed + iter_seed) % (2**31 - 1))
        base_mask = jnp.asarray(self._feature_mask(rng)
                                & np.asarray(self.f_categorical == 0))
        key = jax.random.PRNGKey(iter_seed)

        if self.strategy == "compact":
            rec, leaf_id, n_splits, _ = grow_tree_compact(
                self.codes_row, self.codes_t, grad, hess, w, base_mask,
                self.f_numbins, self.f_missing, self.f_default,
                self.f_monotone, self.f_penalty, self.f_col, self.f_base,
                self.f_elide, self.hist_idx, key, **self._statics())
        else:
            rec, leaf_id, n_splits, _ = grow_tree(
                self.codes_t, grad, hess, w, base_mask,
                self.f_numbins, self.f_missing, self.f_default,
                self.f_monotone, self.f_penalty, self.f_col, self.f_base,
                self.f_elide, self.hist_idx, key, **self._statics())

        self.last_leaf_id = leaf_id
        self._leaf_id_host = None
        rec_h, k = jax.device_get((rec, n_splits))
        k = int(k)
        if k == 0:
            log.warning("No further splits with positive gain")
        tree = Tree(cfg.num_leaves)
        for i in range(k):
            inner_f = int(rec_h.feat[i])
            real_f = ds.inner_to_real(inner_f)
            mapper = ds.bin_mappers[real_f]
            thr_bin = int(rec_h.thr[i])
            tree.split(
                int(rec_h.leaf[i]), inner_f, real_f, thr_bin,
                ds.real_threshold(inner_f, thr_bin),
                float(rec_h.lout[i]), float(rec_h.rout[i]),
                int(round(float(rec_h.lcnt[i]))),
                int(round(float(rec_h.rcnt[i]))),
                float(rec_h.lsh[i]), float(rec_h.rsh[i]),
                float(rec_h.gain[i]), mapper.missing_type,
                bool(rec_h.dleft[i]))
        return tree

    # ------------------------------------------------------------------
    def leaf_rows(self, leaf: int) -> np.ndarray:
        """IN-BAG row indices of a leaf after training (leaf renewal path).

        last_leaf_id routes every row (out-of-bag included), but leaf
        renewal must use in-bag rows only, matching the reference's
        RenewTreeOutput over the data partition (serial_tree_learner.cpp:
        855-893) and SerialTreeLearner.leaf_rows."""
        if self._leaf_id_host is None:
            self._leaf_id_host = np.asarray(jax.device_get(self.last_leaf_id))
        in_leaf = self._leaf_id_host == leaf
        if self._bag_mask_host is not None:
            in_leaf = in_leaf & self._bag_mask_host
        return np.nonzero(in_leaf)[0]
