"""Whole-tree-on-device leaf-wise learner.

The host-loop learner (serial_learner.py) mirrors the reference's phase
structure (serial_tree_learner.cpp:173-237) and pays one host round-trip per
split — ruinous through a tunneled TPU, and every distinct leaf size
recompiles a bucket shape. This learner is the TPU-native answer flagged in
SURVEY.md §7 ("leaf-wise growth is inherently dynamic-shape"): grow the
ENTIRE tree inside one jitted `lax.while_loop` with static shapes.

Design deltas vs the reference's DataPartition/HistogramPool machinery:

* No permutation buffer. Row membership is a dense (N,) `leaf_id` vector;
  a split rewrites it with a masked `where` — O(N) elementwise, no sort.
* Histograms are built over the FULL row set with per-row weights
  `gh * (leaf_id == leaf)`. O(N) per split instead of O(leaf), but the
  histogram path runs at HBM speed on the MXU (ops/pallas), so N x (L-1)
  work is orders of magnitude cheaper than L-1 host syncs.
* The histogram pool (feature_histogram.hpp:654-831) becomes a dense
  (L, F, B, 3) device array: parent slot is overwritten by the left child,
  the right child is parent - left (FeatureHistogram::Subtract semantics).
* Per-split records (split leaf, feature, bin, gain, child stats) are
  written into (L-1,) arrays; the host replays them into a `Tree` after the
  loop — one device->host transfer per tree.
* Leaf-wise leaf selection = argmax over the (L,) per-leaf best-gain array,
  exactly the `best_split_per_leaf_` argmax of the reference.

Monotone constraints propagate like serial_tree_learner.cpp:771-852 (basic
mode); depth limits gate stored gains. Categorical splits run INSIDE the
whole-tree program (one-hot and sorted k-vs-rest, the device analog of
feature_histogram.hpp:118-279): each leaf's scan merges the numerical and
categorical winners, the winning left-bin mask lives in a (L, B) store and
is recorded per split for host replay into bitset tree nodes. The sharded
modes carry categoricals too: psum/voting scan replicated reduced
histograms (masks replicate for free), and the sliced scatter/feature-
parallel elections transport the winner's mask inside the candidate
payload. Forced splits and CEGB fall back to the host-loop learner
(create_tree_learner picks).
"""
from __future__ import annotations

import contextlib
import functools
from typing import List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config
from ..io.binning import BIN_CATEGORICAL
from ..io.dataset import Dataset
from ..io.stream import DeviceDataShard
from ..ops import bundle as bundle_ops
from ..ops import quantize as quant_ops
from ..ops import split as split_ops
from ..ops.fused import run_split_loop
from ..ops.partition import decide_left
from ..ops.pallas.histogram_kernel import build_histogram_pallas_t
from .. import telemetry
from ..telemetry import recorder as telem
from ..utils import log
from ..utils.log import LightGBMError
from ..utils.envs import (flag, partition_mode_env, strategy_env,
                          use_pallas_env)
from .tree import Tree

NEG_INF = split_ops.NEG_INF
_POOL_BYTE_LIMIT = 2 << 30


def _env(name, default):
    import os
    return os.environ.get(name, default)


# Per-leaf best-split state lives in ONE (L, 12) f32 array (the device
# analog of the reference's best_split_per_leaf_) so each update is a single
# row write instead of 12 tiny scatters. feat/thr ride as exact small f32.
B_GAIN, B_FEAT, B_THR, B_DLEFT, B_LSG, B_LSH, B_LCNT, B_RSG, B_RSH, \
    B_RCNT, B_LOUT, B_ROUT = range(12)

# Per-split records: ONE (L-1, 13) f32 array fetched to host in a single
# transfer per tree and replayed into a Tree.
R_LEAF, R_FEAT, R_THR, R_DLEFT, R_GAIN, R_LSG, R_LSH, R_LCNT, R_RSG, \
    R_RSH, R_RCNT, R_LOUT, R_ROUT = range(13)


class _Carry(NamedTuple):
    k: jax.Array
    leaf_id: jax.Array
    pool: jax.Array
    depth: jax.Array
    leaf_min: jax.Array
    leaf_max: jax.Array
    best: jax.Array          # (L, 12) f32
    best_cat: jax.Array      # (L, B|1) f32 0/1 left-bin masks
    rec: jax.Array           # (L-1, 13) f32
    rec_cat: jax.Array       # (L-1, B|1) f32
    key: jax.Array


def _merge_num_cat(res: split_ops.SplitResult, cres) -> tuple:
    """Merge the numerical and categorical split candidates of one leaf —
    the in-program analog of SerialTreeLearner._merge_categorical: the
    better gain wins. Returns (merged SplitResult, (B,) f32 left-bin mask)
    where the mask is all-zero when the numerical candidate wins (the
    store/transport convention shared by every growth mode)."""
    cat_wins = cres.gain > res.gain
    merged = split_ops.SplitResult(
        gain=jnp.where(cat_wins, cres.gain, res.gain),
        feature=jnp.where(cat_wins, cres.feature, res.feature),
        threshold=jnp.where(cat_wins, 0, res.threshold),
        default_left=jnp.where(cat_wins, False, res.default_left),
        left_sum_grad=jnp.where(
            cat_wins, cres.left_sum_grad, res.left_sum_grad),
        left_sum_hess=jnp.where(
            cat_wins, cres.left_sum_hess, res.left_sum_hess),
        left_count=jnp.where(cat_wins, cres.left_count, res.left_count),
        right_sum_grad=jnp.where(
            cat_wins, cres.right_sum_grad, res.right_sum_grad),
        right_sum_hess=jnp.where(
            cat_wins, cres.right_sum_hess, res.right_sum_hess),
        right_count=jnp.where(
            cat_wins, cres.right_count, res.right_count),
        left_output=jnp.where(
            cat_wins, cres.left_output, res.left_output),
        right_output=jnp.where(
            cat_wins, cres.right_output, res.right_output))
    cm = jnp.where(cat_wins, cres.left_mask.astype(jnp.float32), 0.0)
    return merged, cm


def _hist_t(codes_t, gh, num_bins, use_pallas, hist_chunk=0):
    if use_pallas:
        return build_histogram_pallas_t(codes_t, gh, num_bins)
    from ..ops.histogram import build_histogram
    return build_histogram(jnp.swapaxes(codes_t, 0, 1), gh, num_bins,
                           chunk_size=hist_chunk, use_pallas=False)


def _hist_t_q(codes_t, ghq, num_bins, use_pallas, hist_chunk=0):
    """Quantized histogram over transposed codes: EXACT int32 sums from
    ONE integer one-hot contraction (no bf16 hi/lo pair)."""
    if use_pallas:
        from ..ops.pallas.histogram_kernel import \
            build_histogram_pallas_quantized_t
        return build_histogram_pallas_quantized_t(codes_t, ghq, num_bins)
    from ..ops.histogram import build_histogram_quantized
    return build_histogram_quantized(jnp.swapaxes(codes_t, 0, 1), ghq,
                                     num_bins, chunk_size=hist_chunk,
                                     use_pallas=False)


def _tree_helpers(base_mask, f_numbins, f_missing, f_default, f_monotone,
                  f_penalty, f_elide, hist_idx, *, num_bins, max_depth,
                  l1, l2, max_delta_step, min_data_in_leaf, min_sum_hessian,
                  min_gain_to_split, bynode_k,
                  f_categorical=None, cat_statics=None, dequant=None):
    """Shared pieces of both growth strategies: per-node feature sampling,
    the (expand + scan + materialize) split search, and per-leaf best-state
    stores with depth gating.

    cat_statics = (cat_l2, cat_smooth, max_cat_threshold,
    max_cat_to_onehot, min_data_per_group) switches the scan into merged
    numerical+categorical mode: each leaf evaluates both searches over the
    same expanded histogram and the better gain wins (the in-program analog
    of SerialTreeLearner._merge_categorical). scan then returns
    (SplitResult, left-bin mask) where the mask is all-zero for a numerical
    winner; without cat_statics the mask is a (1,) placeholder.

    dequant (quantized-grad path): maps an EXACT int32 column histogram
    to f32 with the iteration's scales right before the split scan — the
    integer domain carries construction, pooling and sibling subtraction,
    the gain arithmetic stays f32."""
    f = f_numbins.shape[0]
    has_cat = cat_statics is not None
    cat_b = num_bins if has_cat else 1
    scan_kwargs = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    if has_cat:
        is_cat = f_categorical != 0
        cat_l2, cat_smooth, max_cat_threshold, max_cat_to_onehot, \
            min_data_per_group = cat_statics
        cat_kwargs = dict(
            scan_kwargs, cat_l2=cat_l2, cat_smooth=cat_smooth,
            max_cat_threshold=max_cat_threshold,
            max_cat_to_onehot=max_cat_to_onehot,
            min_data_per_group=min_data_per_group)

    def node_mask(key):
        if bynode_k <= 0:
            return base_mask
        u = jnp.where(base_mask, jax.random.uniform(key, (f,)), jnp.inf)
        kth = jnp.sort(u)[bynode_k - 1]
        return base_mask & (u <= kth)

    def scan(col_hist, sg, sh, cnt, mn, mx, fmask):
        if dequant is not None:
            col_hist = dequant(col_hist)
        hist = bundle_ops.expand_column_hist(
            col_hist, jnp.stack([sg, sh, cnt]), hist_idx, f_elide, f_default)
        rel, t, use_m1, prefix = split_ops.per_feature_best(
            hist, sg, sh, cnt, f_numbins, f_missing, f_default,
            fmask & ~is_cat if has_cat else fmask,
            f_monotone, mn, mx, f_penalty, None, **scan_kwargs)
        feat = jnp.argmax(rel).astype(jnp.int32)
        res = split_ops.materialize_split(
            feat, rel, t, use_m1, prefix, sg, sh, cnt, mn, mx,
            l1=l1, l2=l2, max_delta_step=max_delta_step)
        if not has_cat:
            return res, jnp.zeros((cat_b,), jnp.float32)
        crel, caux = split_ops.per_feature_best_categorical(
            hist, sg, sh, cnt, f_numbins, f_missing, fmask & is_cat,
            mn, mx, f_penalty, **cat_kwargs)
        cfeat = jnp.argmax(crel).astype(jnp.int32)
        cres = split_ops.materialize_cat_split(
            cfeat, crel, caux, hist, sg, sh, cnt, mn, mx,
            l1=l1, l2=l2, cat_l2=cat_l2, max_delta_step=max_delta_step)
        return _merge_num_cat(res, cres)

    def _best_row(res: split_ops.SplitResult, child_depth) -> jax.Array:
        gain = res.gain
        if max_depth > 0:
            gain = jnp.where(child_depth >= max_depth, NEG_INF, gain)
        return jnp.stack([
            gain, res.feature.astype(jnp.float32),
            res.threshold.astype(jnp.float32),
            res.default_left.astype(jnp.float32),
            res.left_sum_grad, res.left_sum_hess, res.left_count,
            res.right_sum_grad, res.right_sum_hess, res.right_count,
            res.left_output, res.right_output])

    def store_best(best: jax.Array, best_cat: jax.Array, i,
                   res: split_ops.SplitResult, cm, child_depth):
        return (best.at[i].set(_best_row(res, child_depth)),
                best_cat.at[i].set(cm))

    def scan2(col_hist2, sg2, sh2, cnt2, mn2, mx2, keys2):
        """Both children's split scans in one vectorized pass."""
        fmask2 = jax.vmap(node_mask)(keys2)
        return jax.vmap(scan)(col_hist2, sg2, sh2, cnt2, mn2, mx2, fmask2)

    return node_mask, scan, store_best, scan2, _best_row


def search2_simple(scan2, best_row):
    """The unsharded 2-child search: scan both children, format best
    rows. Sharded modes replace this with election-aware variants of the
    same signature (search2_rows in grow_tree_compact_core)."""
    def search2(col_hist2, sg2, sh2, cnt2, mn2, mx2, keys2, child_depth):
        res2, cm2 = scan2(col_hist2, sg2, sh2, cnt2, mn2, mx2, keys2)
        rows = jax.vmap(
            functools.partial(best_row, child_depth=child_depth))(res2)
        return rows, cm2
    return search2


def split_epilogue(*, k, key, l, new_id, row, mono_f, best_cat_l,
                   leaf_min, leaf_max, depth, rec, rec_cat, best, best_cat,
                   hist_l, hist_r, search2):
    """The split bookkeeping every growth strategy shares (one copy;
    divergence here silently forks the strategies): monotone-constraint
    propagation (basic mode, serial_tree_learner.cpp:771-852), depth
    update, split-record append, and the two children's re-scan via
    `search2` (which carries the sharded modes' election when present).
    Returns the updated (key, leaf_min, leaf_max, depth, rec, rec_cat,
    best, best_cat)."""
    mid = (row[B_LOUT] + row[B_ROUT]) * 0.5
    pmin, pmax = leaf_min[l], leaf_max[l]
    lmin = jnp.where(mono_f < 0, jnp.maximum(pmin, mid), pmin)
    lmax = jnp.where(mono_f > 0, jnp.minimum(pmax, mid), pmax)
    rmin = jnp.where(mono_f > 0, jnp.maximum(pmin, mid), pmin)
    rmax = jnp.where(mono_f < 0, jnp.minimum(pmax, mid), pmax)
    leaf_min = leaf_min.at[l].set(lmin).at[new_id].set(rmin)
    leaf_max = leaf_max.at[l].set(lmax).at[new_id].set(rmax)
    child_depth = depth[l] + 1
    depth = depth.at[l].set(child_depth).at[new_id].set(child_depth)

    rec_row = jnp.concatenate([
        jnp.stack([l.astype(jnp.float32), row[B_FEAT], row[B_THR],
                   row[B_DLEFT], row[B_GAIN]]),
        row[B_LSG:]])
    rec = rec.at[k].set(rec_row)
    rec_cat = rec_cat.at[k].set(best_cat_l)

    key, kl, kr = jax.random.split(key, 3)
    rows2, cm2 = search2(jnp.stack([hist_l, hist_r]),
                         jnp.stack([row[B_LSG], row[B_RSG]]),
                         jnp.stack([row[B_LSH], row[B_RSH]]),
                         jnp.stack([row[B_LCNT], row[B_RCNT]]),
                         jnp.stack([lmin, rmin]), jnp.stack([lmax, rmax]),
                         jnp.stack([kl, kr]), child_depth)
    i2 = jnp.stack([l, new_id])
    best = best.at[i2].set(rows2)
    best_cat = best_cat.at[i2].set(cm2)
    return key, leaf_min, leaf_max, depth, rec, rec_cat, best, best_cat


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "col_bins", "max_depth",
                     "bynode_k", "use_pallas", "cat_statics", "quant_bits",
                     "hist_chunk", "grow_program"))
def grow_tree(codes_t: jax.Array,         # (C, N) column codes (EFB view)
              grad: jax.Array, hess: jax.Array,   # (N,)
              w: jax.Array,               # (N,) bagging weight (0/1)
              base_mask: jax.Array,       # (F,) bool feature sample
              f_numbins, f_missing, f_default, f_monotone,  # (F,) int32
              f_penalty,                  # (F,) f32 gain multipliers
              f_categorical,              # (F,) int32 1 = categorical
              f_col, f_base, f_elide,     # (F,) int32 EFB maps
              hist_idx,                   # (F, B) int32 expansion gather
              rng_key,                    # PRNG key for by-node sampling
              *, num_leaves: int, num_bins: int, col_bins: int,
              max_depth: int,
              l1: float, l2: float, max_delta_step: float,
              min_data_in_leaf: int, min_sum_hessian: float,
              min_gain_to_split: float, bynode_k: int, use_pallas: bool,
              cat_statics=None, quant_bits: int = 0, hist_chunk: int = 0,
              grow_program: str = "per_split"):
    c_cols, n = codes_t.shape
    f = f_numbins.shape[0]
    L = num_leaves
    has_cat = cat_statics is not None
    cat_b = num_bins if has_cat else 1
    # quant_bits > 0 switches the whole histogram pipeline to the
    # quantized-gradient formulation (ops/quantize.py): the gh operand,
    # the pool and the sibling subtraction are EXACT int32, and the split
    # scans dequantize with the iteration's scales. The jit cache keys on
    # quant_bits (the hist dtype), so the float program is untouched.
    if quant_bits:
        rng_key, qkey = jax.random.split(rng_key)
        packed, s_g, s_h = quant_ops.quantize_gh_core(
            grad * w, hess * w, qkey, grad_bits=quant_bits)
        gh = quant_ops.gh_operand(packed, w > 0, quant_bits)  # (N, 3) int
        scale3 = quant_ops.dequant_scale3(s_g, s_h)

        def dequant(hq):
            return hq.astype(jnp.float32) * scale3

        def hist_fn(ghx):
            return _hist_t_q(codes_t, ghx, col_bins, use_pallas, hist_chunk)
    else:
        gh = jnp.stack([grad * w, hess * w, w], axis=1)     # (N, 3)
        dequant = None

        def hist_fn(ghx):
            return _hist_t(codes_t, ghx, col_bins, use_pallas, hist_chunk)
    node_mask, scan, store_best, scan2, best_row = _tree_helpers(
        base_mask, f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_elide, hist_idx,
        num_bins=num_bins, max_depth=max_depth, l1=l1, l2=l2,
        max_delta_step=max_delta_step, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian=min_sum_hessian, min_gain_to_split=min_gain_to_split,
        bynode_k=bynode_k, f_categorical=f_categorical,
        cat_statics=cat_statics, dequant=dequant)

    # ---- root ------------------------------------------------------------
    hist0 = hist_fn(gh)
    totals = hist0[0].sum(axis=0)                       # (3,): sum_g, sum_h, cnt
    if quant_bits:
        totals = dequant(totals)
    root_key, loop_key = jax.random.split(rng_key)
    root_res, root_cm = scan(hist0, totals[0], totals[1], totals[2],
                             jnp.float32(-np.inf), jnp.float32(np.inf),
                             node_mask(root_key))

    best = jnp.full((L, 12), NEG_INF, jnp.float32) \
        .at[:, B_FEAT:].set(0.0)
    best_cat = jnp.zeros((L, cat_b), jnp.float32)
    # the depth argument is the stored leaf's own depth (a leaf at depth d
    # may split iff d < max_depth, reference _splittable); root sits at 0
    best, best_cat = store_best(best, best_cat, 0, root_res, root_cm,
                                jnp.int32(0))
    # pool dtype follows the histogram dtype: int32 on the quantized path
    # (parent - child below is then bit-exact integer subtraction)
    pool = jnp.zeros((L, c_cols, col_bins, 3), hist0.dtype).at[0].set(hist0)
    rec = jnp.zeros((L - 1, 13), jnp.float32)
    zi = functools.partial(jnp.zeros, dtype=jnp.int32)
    carry = _Carry(
        k=jnp.int32(0), leaf_id=jnp.zeros(n, jnp.int32), pool=pool,
        depth=zi(L),
        leaf_min=jnp.full((L,), -np.inf, jnp.float32),
        leaf_max=jnp.full((L,), np.inf, jnp.float32),
        best=best, best_cat=best_cat, rec=rec,
        rec_cat=jnp.zeros((L - 1, cat_b), jnp.float32), key=loop_key)

    def cond(c: _Carry):
        return (c.k < L - 1) & (jnp.max(c.best[:, B_GAIN]) > 1e-10)

    def body(c: _Carry) -> _Carry:
        b = c.best
        l = jnp.argmax(b[:, B_GAIN]).astype(jnp.int32)
        row = b[l]
        new_id = c.k + 1
        feat = row[B_FEAT].astype(jnp.int32)
        thr = row[B_THR].astype(jnp.int32)
        dleft = row[B_DLEFT] > 0.5

        col = jax.lax.dynamic_slice_in_dim(codes_t, f_col[feat], 1, axis=0)[0]
        fbins = bundle_ops.logical_bins_for_feature(
            col.astype(jnp.int32), f_base[feat], f_default[feat],
            f_numbins[feat], f_elide[feat])
        go_left = decide_left(fbins, thr, dleft,
                              f_missing[feat], f_default[feat], f_numbins[feat])
        if has_cat:
            # categorical routing: left iff the row's logical bin is in
            # the winning left-bin mask (CategoricalDecisionInner)
            cmask = c.best_cat[l]
            cat_left = cmask[jnp.clip(fbins, 0, cat_b - 1)] > 0.5
            go_left = jnp.where(f_categorical[feat] != 0, cat_left, go_left)
        parent = c.leaf_id == l
        lmask = parent & go_left
        leaf_id = jnp.where(parent & ~go_left, new_id, c.leaf_id)

        ghl = gh * lmask[:, None].astype(gh.dtype)
        hist_l = hist_fn(ghl)
        hist_r = c.pool[l] - hist_l
        pool = c.pool.at[l].set(hist_l).at[new_id].set(hist_r)

        (key, leaf_min, leaf_max, depth, rec2, rec_cat2, best2,
         best_cat2) = split_epilogue(
            k=c.k, key=c.key, l=l, new_id=new_id, row=row,
            mono_f=f_monotone[feat], best_cat_l=c.best_cat[l],
            leaf_min=c.leaf_min, leaf_max=c.leaf_max, depth=c.depth,
            rec=c.rec, rec_cat=c.rec_cat, best=b, best_cat=c.best_cat,
            hist_l=hist_l, hist_r=hist_r,
            search2=search2_simple(scan2, best_row))
        return _Carry(new_id, leaf_id, pool, depth, leaf_min, leaf_max,
                      best2, best_cat2, rec2, rec_cat2, key)

    out = run_split_loop(cond, body, carry, L - 1, grow_program)
    return (out.rec, out.rec_cat if has_cat else None,
            out.leaf_id, out.k, totals)


class _CarryC(NamedTuple):
    k: jax.Array
    data: jax.Array          # (N + Wmax, D) u32 packed rows grouped by leaf
    pos_leaf: jax.Array      # (N + Wmax,) leaf id per physical POSITION
    leaf_begin: jax.Array    # (L,)
    leaf_phys: jax.Array     # (L,) physical rows in the window
    pool: jax.Array          # (K, C, B, 3) — K == L unless slot-capped
    slot_of: jax.Array       # (L,) pool slot of each leaf, -1 = evicted
    slot_owner: jax.Array    # (K,) leaf owning each slot, -1 = free
    slot_last: jax.Array     # (K,) last-use step per slot (LRU clock)
    depth: jax.Array
    leaf_min: jax.Array
    leaf_max: jax.Array
    best: jax.Array          # (L, 12) f32
    best_cat: jax.Array      # (L, B|1) f32 0/1 left-bin masks
    rec: jax.Array           # (L-1, 13) f32
    rec_cat: jax.Array       # (L-1, B|1) f32
    key: jax.Array


def _size_classes(n: int, min_bucket: int = 4096, step: int = 4):
    """Padded window-size ladder for the lax.switch dispatch. Smaller
    step = tighter windows (less wasted per-split work, ~step/2 mean
    inflation) but more traced branches (compile time); tunable via
    LGBM_TPU_WINDOW_STEP (read once at learner init, threaded through
    as a static so the jit cache keys on it)."""
    ws = []
    wcur = min_bucket
    while wcur < n:
        ws.append(wcur)
        wcur *= step
    ws.append(n)
    return ws


def _unpack_codes(words: jax.Array, c_cols: int, item_bits: int) -> jax.Array:
    """(W, CW) u32 packed codes -> (W, c_cols) i32."""
    per = 32 // item_bits
    shifts = (jnp.arange(per, dtype=jnp.uint32) * item_bits)[None, None, :]
    u = (words[:, :, None] >> shifts) & jnp.uint32((1 << item_bits) - 1)
    return u.reshape(words.shape[0], words.shape[1] * per)[:, :c_cols] \
            .astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("c_cols", "item_bits",
                     "num_leaves", "num_bins", "col_bins", "max_depth",
                     "bynode_k", "use_pallas", "partition",
                     "pool_slots", "window_step", "trivial_weights",
                     "cat_statics", "quant_bits", "quant_renew",
                     "grow_program"))
def grow_tree_compact(
        codes_pack: jax.Array,       # (N, CW) u32: packed column codes
        codes_row: jax.Array,        # (N, C) u8/u16 for the root pass
        grad: jax.Array, hess: jax.Array, w: jax.Array,
        base_mask: jax.Array,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_categorical, f_col, f_base, f_elide, hist_idx, rng_key,
        *, c_cols: int, item_bits: int,
        num_leaves: int, num_bins: int, col_bins: int, max_depth: int,
        l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: int, min_sum_hessian: float,
        min_gain_to_split: float, bynode_k: int, use_pallas: bool,
        partition: str = "sort",
        pool_slots: int = 0, window_step: int = 4,
        trivial_weights: bool = False, cat_statics=None,
        quant_bits: int = 0, quant_renew: bool = True,
        grow_program: str = "per_split"):
    return grow_tree_compact_core(
        codes_pack, codes_row, grad, hess, w, base_mask,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_categorical, f_col, f_base, f_elide, hist_idx, rng_key,
        c_cols=c_cols, item_bits=item_bits, num_leaves=num_leaves,
        num_bins=num_bins, col_bins=col_bins, max_depth=max_depth,
        l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split, bynode_k=bynode_k,
        use_pallas=use_pallas, partition=partition,
        axis_name=None, pool_slots=pool_slots,
        window_step=window_step, trivial_weights=trivial_weights,
        cat_statics=cat_statics, quant_bits=quant_bits,
        quant_renew=quant_renew, grow_program=grow_program)


def make_voting_search(*, axis_name, voting_k, c_cols, col_bins,
                       base_mask, f_numbins, f_missing, f_default,
                       f_monotone, f_penalty, f_elide, hist_idx,
                       f_categorical, has_cat, cat_statics,
                       helper_kwargs):
    """PV-Tree 2-stage voting reduction + search, shared by the
    compact and chunk growth cores (the voting seam of
    voting_parallel_tree_learner.cpp:170-260): per split, every
    shard scans its LOCAL histograms with 1/D-scaled data gates,
    votes for its top-k features, the vote psum elects 2k global
    candidates, and ONLY the elected features' histograms are
    reduced — O(2k*B) communication per split instead of O(F*B).
    Deterministic and replicated on every shard, so no best-split
    broadcast is needed. Returns (reduce_hist, search_row,
    search2_rows); reduce_hist is the identity (histograms stay
    local until election)."""
    num_bins = helper_kwargs["num_bins"]
    l1 = helper_kwargs["l1"]
    l2 = helper_kwargs["l2"]
    max_delta_step = helper_kwargs["max_delta_step"]
    min_data_in_leaf = helper_kwargs["min_data_in_leaf"]
    min_sum_hessian = helper_kwargs["min_sum_hessian"]
    min_gain_to_split = helper_kwargs["min_gain_to_split"]
    cat_b = num_bins if has_cat else 1
    f_all = int(f_numbins.shape[0])
    assert f_all == c_cols, \
        "voting mode requires identity feature->column mapping"
    n_elect = min(2 * voting_k, f_all)
    # the reference scales the local gates by machine count
    # (voting_parallel_tree_learner.cpp:57-59)
    d_v = jax.lax.psum(1, axis_name)
    (node_mask, _, _, _, best_row) = _tree_helpers(
        base_mask, f_numbins, f_missing, f_default, f_monotone,
        f_penalty, f_elide, hist_idx, **helper_kwargs)
    scan_kwargs_local = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        # integer division for the count gate, exactly the
        # reference's local_config (voting_parallel:58-59)
        min_data_in_leaf=jnp.asarray(min_data_in_leaf,
                                     jnp.int32) // d_v,
        min_sum_hessian=min_sum_hessian / d_v,
        min_gain_to_split=min_gain_to_split)
    scan_kwargs_global = dict(
        num_bins=num_bins, l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split)
    if has_cat:
        # categorical candidates ride the same vote/elect/reduce
        # pipeline: local rel gains merge the categorical search
        # (scaled gates, like the numerical local config) and the
        # elected global scan re-runs both searches on the psum'd
        # histograms. Every shard computes the identical elected
        # scan, so the winning left-bin mask is replicated — no
        # mask transport is needed in voting mode.
        is_cat_v = f_categorical != 0
        cat_l2_v, cat_smooth_v, max_cat_threshold_v, \
            max_cat_to_onehot_v, min_data_per_group_v = cat_statics
        cat_extra = dict(
            cat_l2=cat_l2_v, cat_smooth=cat_smooth_v,
            max_cat_threshold=max_cat_threshold_v,
            max_cat_to_onehot=max_cat_to_onehot_v,
            min_data_per_group=min_data_per_group_v)
        cat_kwargs_local = dict(scan_kwargs_local, **cat_extra)
        cat_kwargs_global = dict(scan_kwargs_global, **cat_extra)

    def _local_rel(col_hist_l, fmask):
        """Per-feature local best gains from the shard's histograms."""
        lt = col_hist_l[0].sum(axis=0)        # local (sg, sh, cnt)
        hist = bundle_ops.expand_column_hist(
            col_hist_l, lt, hist_idx, f_elide, f_default)
        rel, _, _, _ = split_ops.per_feature_best(
            hist, lt[0], lt[1], lt[2], f_numbins, f_missing, f_default,
            fmask & ~is_cat_v if has_cat else fmask, f_monotone,
            jnp.float32(-np.inf),
            jnp.float32(np.inf), f_penalty, None, **scan_kwargs_local)
        if has_cat:
            crel, _ = split_ops.per_feature_best_categorical(
                hist, lt[0], lt[1], lt[2], f_numbins, f_missing,
                fmask & is_cat_v, jnp.float32(-np.inf),
                jnp.float32(np.inf), f_penalty, **cat_kwargs_local)
            rel = jnp.maximum(rel, crel)
        return rel                            # (F,)

    def _vote(rel):
        """Exactly-k vote mask from local rel gains (lax.top_k ties
        break by index, same as the host learner — a >=kth threshold
        would let gain ties cast extra votes)."""
        _, top_idx = jax.lax.top_k(rel, min(voting_k, f_all))
        return jnp.zeros(f_all, jnp.float32).at[top_idx].add(
            jnp.where(rel[top_idx] > NEG_INF / 2, 1.0, 0.0))

    def _elected_scan(col_hist_l, elect, sg, sh, cnt, mn, mx, fmask,
                      child_depth):
        """Reduce elected features' histograms and find the winner."""
        hist_e = jax.lax.psum(jnp.take(col_hist_l, elect, axis=0),
                              axis_name)      # (2k, B, 3) global
        nb_e = jnp.take(f_numbins, elect)
        hi_e = (jnp.arange(n_elect, dtype=jnp.int32)[:, None] * col_bins
                + jnp.arange(col_bins, dtype=jnp.int32)[None, :])
        hi_e = jnp.where(
            jnp.arange(col_bins, dtype=jnp.int32)[None, :]
            < nb_e[:, None], hi_e, n_elect * col_bins)
        hist_f = bundle_ops.expand_column_hist(
            hist_e, jnp.stack([sg, sh, cnt]), hi_e,
            jnp.take(f_elide, elect), jnp.take(f_default, elect))
        fmask_e = jnp.take(fmask, elect)
        if has_cat:
            is_cat_e = jnp.take(is_cat_v, elect)
        rel, t, use_m1, prefix = split_ops.per_feature_best(
            hist_f, sg, sh, cnt, nb_e, jnp.take(f_missing, elect),
            jnp.take(f_default, elect),
            fmask_e & ~is_cat_e if has_cat else fmask_e,
            jnp.take(f_monotone, elect), mn, mx,
            jnp.take(f_penalty, elect), None, **scan_kwargs_global)
        fe = jnp.argmax(rel).astype(jnp.int32)
        res = split_ops.materialize_split(
            fe, rel, t, use_m1, prefix, sg, sh, cnt, mn, mx,
            l1=l1, l2=l2, max_delta_step=max_delta_step)
        if has_cat:
            crel, caux = split_ops.per_feature_best_categorical(
                hist_f, sg, sh, cnt, nb_e, jnp.take(f_missing, elect),
                fmask_e & is_cat_e, mn, mx,
                jnp.take(f_penalty, elect), **cat_kwargs_global)
            cfe = jnp.argmax(crel).astype(jnp.int32)
            cres = split_ops.materialize_cat_split(
                cfe, crel, caux, hist_f, sg, sh, cnt, mn, mx,
                l1=l1, l2=l2, cat_l2=cat_l2_v,
                max_delta_step=max_delta_step)
            res, cm = _merge_num_cat(res, cres)
        else:
            cm = jnp.zeros((cat_b,), jnp.float32)
        row = best_row(res, child_depth)
        # map the elected-subset index back to the real feature id
        sub_f = res.feature.astype(jnp.int32)
        return row.at[B_FEAT].set(
            jnp.take(elect, sub_f).astype(jnp.float32)), cm

    def reduce_hist(h):
        return h                               # stays local

    def search_row(col_hist, sg, sh, cnt, mn, mx, key, child_depth):
        fmask = node_mask(key)
        rel = _local_rel(col_hist, fmask)
        votes = jax.lax.psum(_vote(rel), axis_name)
        elect = jnp.argsort(
            -votes, stable=True)[:n_elect].astype(jnp.int32)
        return _elected_scan(col_hist, elect, sg, sh, cnt, mn, mx,
                             fmask, child_depth)

    # batched 2-child elected reduction: ONE (2, 2k, B, 3) psum per
    # split instead of two sequential ones — half the collective
    # latency on real ICI. XLA:CPU's collective rendezvous fatally
    # aborts on the batched form under the virtual mesh (hard 40s
    # timeout, observed round 2), so the lever defaults to
    # backend-keyed auto. LGBM_TPU_VOTING_BATCHED=0/1 overrides.
    vb_env = _env("LGBM_TPU_VOTING_BATCHED", "auto")
    voting_batched = (jax.default_backend() == "tpu"
                      if vb_env == "auto" else vb_env == "1")

    def search2_rows(col_hist2, sg2, sh2, cnt2, mn2, mx2, keys2,
                     child_depth):
        fmask2 = jax.vmap(node_mask)(keys2)
        rel2 = jax.vmap(_local_rel)(col_hist2, fmask2)
        votes2 = jax.lax.psum(jax.vmap(_vote)(rel2), axis_name)
        elect2 = jnp.argsort(
            -votes2, axis=1,
            stable=True)[:, :n_elect].astype(jnp.int32)
        if voting_batched:
            rows2, cm2 = jax.vmap(
                _elected_scan,
                in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None))(
                col_hist2, elect2, sg2, sh2, cnt2, mn2, mx2, fmask2,
                child_depth)
        else:
            pairs = [
                _elected_scan(col_hist2[i], elect2[i], sg2[i], sh2[i],
                              cnt2[i], mn2[i], mx2[i], fmask2[i],
                              child_depth)
                for i in range(2)]
            rows2 = jnp.stack([p[0] for p in pairs])
            cm2 = jnp.stack([p[1] for p in pairs])
        return rows2, cm2
    return reduce_hist, search_row, search2_rows


def _quant_prepare(grad, hess, w, rng_key, *, quant_bits, quant_renew,
                   n_total, axis_name):
    """Quantized working-row preparation shared by the compact and chunk
    cores: split the RNG exactly like the masked strategy does (so a
    renew-off run quantizes bit-identically to it), discretize
    (grad*w, hess*w) at the STORAGE resolution (16-bit under leaf
    re-quantization — the packed word's field width, free bits — else
    grad_bits), and, when renewing, measure the root's stored-int maxes
    for the initial requant ratio (pmax'd so every shard agrees).

    Returns (rng_key, packed (N,) int32, s_g, s_h, root_max (2,) f32 or
    None)."""
    rng_key, qkey = jax.random.split(rng_key)
    sbits = quant_ops.storage_bits(quant_bits, quant_renew)
    if axis_name is not None:
        packed, s_g, s_h = quant_ops.quantize_gh_pmax(
            grad * w, hess * w, qkey, grad_bits=sbits, n_total=n_total,
            axis_name=axis_name)
    else:
        packed, s_g, s_h = quant_ops.quantize_gh_core(
            grad * w, hess * w, qkey, grad_bits=sbits)
    if not quant_renew:
        return rng_key, packed, s_g, s_h, None
    qg, qh = quant_ops.unpack_gh(packed)
    m = jnp.stack([jnp.max(jnp.abs(qg)), jnp.max(jnp.abs(qh))]) \
        .astype(jnp.float32)
    if axis_name is not None:
        m = jax.lax.pmax(m, axis_name)
    return rng_key, packed, s_g, s_h, m


def _quant_gh_words(packed: jax.Array, w: jax.Array,
                    gw: int) -> jax.Array:
    """The working row's gh section: ONE u32 word (the packed (qg|qh)
    lane) when weights are trivial, or two words (packed | 0/1 weight)
    when pad/out-of-bag rows must be fenced out of the count lane —
    either way 1-2 words where the float layout bitcasts three."""
    pk = jax.lax.bitcast_convert_type(packed, jnp.uint32)[:, None]
    if gw == 1:
        return pk
    return jnp.concatenate([pk, (w > 0).astype(jnp.uint32)[:, None]],
                           axis=1)


def _quant_win_operand(win, vmask, *, cw, gw, quant_bits, qcap_op,
                       r_g, r_h):
    """(W, 3) integer histogram operand from a packed row window: the
    stored (qg|qh) word re-quantized to the leaf's ratio (1.0 = fixed
    root scale). The weighted layout folds the 0/1 weight word into the
    validity mask so w=0 rows stay off the count lane."""
    pk = jax.lax.bitcast_convert_type(win[:, cw], jnp.int32)
    if gw == 2:
        vmask = vmask & (win[:, cw + 1] != 0)
    return quant_ops.gh_operand_scaled(pk, vmask, quant_bits, qcap_op,
                                       r_g, r_h)


def _quant_side_maxes(win, go_left, vmask, *, cw, gw):
    """(2, 2) f32 [[max|qg|, max|qh|] left, [..] right] over a window's
    valid rows — measured during the partition pass (which reads every
    parent row anyway) to seed each child's leaf-local requant ratio."""
    pk = jax.lax.bitcast_convert_type(win[:, cw], jnp.int32)
    qg, qh = quant_ops.unpack_gh(pk)
    if gw == 2:
        vmask = vmask & (win[:, cw + 1] != 0)
    a = jnp.stack([jnp.abs(qg), jnp.abs(qh)], axis=1).astype(jnp.float32)
    left = jnp.max(jnp.where((go_left & vmask)[:, None], a, 0.0), axis=0)
    right = jnp.max(jnp.where((~go_left & vmask)[:, None], a, 0.0), axis=0)
    return jnp.stack([left, right])


def make_scatter_reduce_q(axis_name, D, c_cols, wire):
    """Quantized rendering of the DP scatter mode's histogram collective
    (the reference's ReduceScatter, data_parallel_tree_learner.cpp:149-
    164): psum_scatter TWO integer lanes [sum_qg, sum_qh] — int16 wire
    when the shard-sum bound quant_max * N fits (1/3 the f32 triple's
    bytes), int32 otherwise (2/3) — and reconstruct the count lane from
    the hessian lane via the leaf's replicated global count:
    cnt_bin = round(qh_bin * leaf_n / qh_tot). Exact for constant-
    hessian objectives; for varying hessians the min_data gate becomes
    approximate — the same class of deviation the host DP learner's
    compact allreduce documents."""
    cs = -(-c_cols // D)
    c_pad = cs * D

    def reduce_q(h_int, leaf_n, qh_tot_q):
        payload = h_int[:, :, :2].astype(wire)
        payload = jnp.pad(payload, ((0, c_pad - c_cols), (0, 0), (0, 0)))
        sl = jax.lax.psum_scatter(payload, axis_name, scatter_dimension=0,
                                  tiled=True).astype(jnp.int32)
        cnt = jnp.round(sl[:, :, 1].astype(jnp.float32)
                        * (leaf_n / jnp.maximum(qh_tot_q, 1.0))) \
            .astype(jnp.int32)
        return jnp.concatenate([sl, cnt[:, :, None]], axis=2)
    return reduce_q


def grow_tree_compact_core(
        codes_pack: jax.Array, codes_row: jax.Array,
        grad: jax.Array, hess: jax.Array, w: jax.Array,
        base_mask: jax.Array,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_categorical, f_col, f_base, f_elide, hist_idx, rng_key,
        *, c_cols: int, item_bits: int,
        num_leaves: int, num_bins: int, col_bins: int, max_depth: int,
        l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: int, min_sum_hessian: float,
        min_gain_to_split: float, bynode_k: int, use_pallas: bool,
        partition: str = "sort",
        axis_name=None, pool_slots: int = 0, scatter_cols: int = 0,
        feature_shards: int = 0, voting_k: int = 0, window_step: int = 4,
        trivial_weights: bool = False, cat_statics=None,
        quant_bits: int = 0, quant_renew: bool = True,
        quant_total_rows: int = 0, grow_program: str = "per_split"):
    """Compaction-based whole-tree growth: O(leaf-size) work per split.

    The masked strategy in grow_tree pays a full O(N) histogram pass per
    split — ruinous at Higgs scale. This variant keeps the reference's
    DataPartition idea (data_partition.hpp:20-205) on device, but instead
    of a permutation of row IDS it physically reorders one packed
    (N, CW + 4) u32 buffer (bit-packed codes | bitcast grad,hess,weight |
    row id). Random access is latency-bound on TPU (~14ns/row regardless
    of width), so moving WHOLE rows once per split costs the same as
    moving bare indices — and then every window read (feature column,
    histogram input, gh) is a contiguous dynamic_slice at HBM bandwidth
    instead of a full-table gather. The histogram is built from the
    SMALLER child's contiguous half-window after the partition (sibling =
    parent - smaller, FeatureHistogram::Subtract). Dynamic leaf sizes meet
    XLA's static shapes through a small ladder of padded window classes
    (x4 steps) dispatched with lax.switch — each class is traced once.

    pool_slots caps the histogram pool at K slots with on-device LRU
    eviction — the role of the reference's HistogramPool
    (src/treelearner/feature_histogram.hpp:654-831), which lets
    num_leaves scale far past pool memory. On a parent-histogram miss
    the sibling is rebuilt by a direct masked pass over the larger
    child's window instead of the subtraction trick. 0 = dense (one
    slot per leaf, no evictions ever).

    scatter_cols (= shard count, 0 = off) switches the data-parallel
    histogram reduction from replicating psum to the reference's comm
    pattern (data_parallel_tree_learner.cpp:149-200): lax.psum_scatter
    tiles the column axis so each shard owns C/D columns of every
    histogram (pool memory /D, reduce traffic ~halved), runs the split
    scan on its slice only, and the global winner is elected from a
    tiny (D, 12) all_gather of per-shard candidates — the analog of
    SyncUpGlobalBestSplit. Requires identity column mapping (no EFB
    bundles) and no by-node feature sampling; callers gate on that.

    quant_bits > 0 switches the working row to the quantized layout:
    the gh section is ONE u32 (qg<<16|qh) word (trivial weights) or two
    (packed | 0/1 weight) — 2 words/row less transport than the f32
    triple on every partition move and histogram read — the pool is
    EXACT int32, sibling subtraction is integer, and the scans read
    leaf-dequantized f32 copies. quant_renew turns on leaf-wise
    re-quantization (rows stored at 16-bit, operands re-discretized to
    grad_bits per leaf range; see ops/quantize.py); off = fixed root
    scale, bit-identical to the masked strategy's quantization. In
    scatter mode the histogram collective becomes the two-integer-lane
    reduce-scatter of make_scatter_reduce_q. The float path's program
    is untouched (all layout switches are jit statics).
    """
    n = grad.shape[0]
    cw = codes_pack.shape[1]
    L = num_leaves
    has_cat = cat_statics is not None
    cat_b = num_bins if has_cat else 1
    # K=1 cannot hold both children of a split (the second allocation
    # would evict the first and corrupt the sibling subtraction)
    K = max(2, pool_slots) if 0 < pool_slots < L else L
    pooled = K < L
    quant = quant_bits > 0
    if not quant:
        gh = jnp.stack([grad * w, hess * w, w], axis=1)
    helper_kwargs = dict(
        num_bins=num_bins, max_depth=max_depth, l1=l1, l2=l2,
        max_delta_step=max_delta_step, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian=min_sum_hessian, min_gain_to_split=min_gain_to_split,
        bynode_k=bynode_k)
    scatter = (scatter_cols > 1 and axis_name is not None
               and feature_shards == 0)
    # feature-parallel: rows replicated, every shard builds histograms
    # ONLY for its column slice (no histogram collective at all — the
    # local slice over all rows IS the global histogram); the winner is
    # elected exactly like scatter mode (feature_parallel_tree_learner
    # .cpp:33-76 + SyncUpGlobalBestSplit role)
    fp = feature_shards > 1 and axis_name is not None
    voting = voting_k > 0 and axis_name is not None and not (scatter or fp)
    sliced = scatter or fp
    per_w = 32 // item_bits

    # quantized packed rows (quant_bits > 0): the gh section of the
    # working row is ONE u32 (qg<<16|qh) word (two under non-trivial
    # weights) instead of the three bitcast f32 words; histograms are
    # EXACT int32 from the integer contraction; scans dequantize at
    # leaf-local scales (quant_renew). Supported reductions: serial,
    # DP psum, DP scatter (int16/int32 two-lane reduce-scatter).
    assert not (quant and (voting or fp)), \
        "quantized packed rows: voting/feature-parallel modes fall back " \
        "to the host learners (create_tree_learner gates)"
    renew = quant and quant_renew
    if quant:
        n_total = quant_total_rows or n
        qcap_op = quant_ops.quant_max(quant_bits, n_total)
        rng_key, gh_packed, q_sg, q_sh, root_max = _quant_prepare(
            grad, hess, w, rng_key, quant_bits=quant_bits,
            quant_renew=quant_renew, n_total=n_total, axis_name=axis_name)
        gw = 1 if trivial_weights else 2

        def q_ratios(leaf_max):
            """(r_g, r_h) leaf-local operand rescale from stored maxes;
            fixed 1.0 when renewal is off."""
            if not renew:
                return jnp.float32(1.0), jnp.float32(1.0)
            return (quant_ops.requant_ratio(leaf_max[0], qcap_op),
                    quant_ops.requant_ratio(leaf_max[1], qcap_op))

        def q_dequant(h_int, r_g, r_h):
            return h_int.astype(jnp.float32) * quant_ops.dequant_scale3(
                q_sg * r_g, q_sh * r_h)

        if scatter:
            reduce_q = make_scatter_reduce_q(
                axis_name, scatter_cols, c_cols,
                quant_ops.wire_dtype(quant_bits, n_total))
    else:
        gw = 3

    if voting:
        reduce_hist, search_row, search2_rows = make_voting_search(
            axis_name=axis_name, voting_k=voting_k, c_cols=c_cols,
            col_bins=col_bins, base_mask=base_mask,
            f_numbins=f_numbins, f_missing=f_missing,
            f_default=f_default, f_monotone=f_monotone,
            f_penalty=f_penalty, f_elide=f_elide, hist_idx=hist_idx,
            f_categorical=f_categorical, has_cat=has_cat,
            cat_statics=cat_statics, helper_kwargs=helper_kwargs)
    elif not sliced:
        (node_mask, scan, store_best, scan2,
         best_row) = _tree_helpers(
            base_mask, f_numbins, f_missing, f_default, f_monotone,
            f_penalty, f_elide, hist_idx,
            f_categorical=f_categorical, cat_statics=cat_statics,
            **helper_kwargs)

        def reduce_hist(h):
            return jax.lax.psum(h, axis_name) if axis_name is not None else h

        def search_row(col_hist, sg, sh, cnt, mn, mx, key, child_depth):
            res, cm = scan(col_hist, sg, sh, cnt, mn, mx, node_mask(key))
            return best_row(res, child_depth), cm

        search2_rows = search2_simple(scan2, best_row)
    else:
        D = scatter_cols if scatter else feature_shards
        (reduce_hist, search_row, search2_rows, cs, shard,
         start) = make_sliced_search(
            axis_name=axis_name, fp=fp, D=D,
            c_cols=c_cols, col_bins=col_bins, item_bits=item_bits,
            base_mask=base_mask, f_numbins=f_numbins, f_missing=f_missing,
            f_default=f_default, f_monotone=f_monotone,
            f_penalty=f_penalty, f_elide=f_elide,
            f_categorical=f_categorical, has_cat=has_cat,
            cat_statics=cat_statics, helper_kwargs=helper_kwargs)

    hist_cols = cs if fp else c_cols   # width of branch-built histograms
    if fp:
        cs_words = cs // per_w
        assert cw >= cs_words * D, \
            "feature-parallel needs codes packed to the padded column count"
        w0 = (shard * cs_words).astype(jnp.int32)

        def decode_for_hist(words2d):
            wsl = jax.lax.dynamic_slice(
                words2d, (jnp.int32(0), w0), (words2d.shape[0], cs_words))
            return _unpack_codes(wsl, cs, item_bits)
    else:
        def decode_for_hist(words2d):
            return _unpack_codes(words2d[:, :cw], c_cols, item_bits)

    classes = _size_classes(n, step=window_step)
    wmax = classes[-1]
    thresholds = jnp.asarray(np.array(classes[:-1], np.int32))
    d_cols = cw + gw + 1

    # packed working buffer: codes | gh section | row id, padded by wmax
    # (gh section: three bitcast f32 words on the float path, one packed
    # int word — two with a weight word — on the quantized path)
    if quant:
        gh_u = _quant_gh_words(gh_packed, w, gw)
    else:
        gh_u = jax.lax.bitcast_convert_type(gh, jnp.uint32)      # (N, 3)
    ids = jnp.arange(n, dtype=jnp.uint32)[:, None]
    data0 = jnp.concatenate([codes_pack, gh_u, ids], axis=1)
    data0 = jnp.concatenate(
        [data0, jnp.zeros((wmax, d_cols), jnp.uint32)], axis=0)

    # ---- root ------------------------------------------------------------
    from ..ops.histogram import build_histogram, build_histogram_quantized
    if quant:
        r0_g, r0_h = q_ratios(root_max) if renew else q_ratios(None)
        ghq0 = quant_ops.gh_operand_scaled(
            gh_packed, w > 0, quant_bits, qcap_op, r0_g, r0_h)
        hist0 = build_histogram_quantized(codes_row, ghq0, col_bins,
                                          use_pallas=use_pallas)
        if scatter:
            # exact global int totals first (3 scalars), then the
            # two-lane reduce-scatter with count reconstruction
            tot_q = jax.lax.psum(hist0[0].sum(axis=0), axis_name)
            totals = q_dequant(tot_q, r0_g, r0_h)
            hist0 = reduce_q(hist0, totals[2], tot_q[1].astype(jnp.float32))
        else:
            if axis_name is not None:
                hist0 = jax.lax.psum(hist0, axis_name)
            totals = q_dequant(hist0[0].sum(axis=0), r0_g, r0_h)
        hist0_scan = q_dequant(hist0, r0_g, r0_h)
    elif fp:
        # rows are replicated: totals come straight from gh, and the
        # root histogram is built from this shard's column slice only
        totals = gh.sum(axis=0)
        cr = codes_row
        if cr.shape[1] < cs * D:
            cr = jnp.pad(cr, ((0, 0), (0, cs * D - cr.shape[1])))
        cr_sl = jax.lax.dynamic_slice(
            cr, (jnp.int32(0), (shard * cs).astype(jnp.int32)), (n, cs))
        hist0 = build_histogram(cr_sl, gh, col_bins, use_pallas=use_pallas)
    else:
        hist0 = build_histogram(codes_row, gh, col_bins,
                                use_pallas=use_pallas)
        if scatter or voting:
            # global totals first (the post-reduce histogram is a column
            # slice / stays local), then reduce per mode
            totals = jax.lax.psum(hist0[0].sum(axis=0), axis_name)
            hist0 = reduce_hist(hist0)
        else:
            hist0 = reduce_hist(hist0)
            totals = hist0[0].sum(axis=0)
    if not quant:
        hist0_scan = hist0
    pool_c = hist0.shape[0]
    root_key, loop_key = jax.random.split(rng_key)
    row0, cm0 = search_row(hist0_scan, totals[0], totals[1], totals[2],
                           jnp.float32(-np.inf), jnp.float32(np.inf),
                           root_key, jnp.int32(0))

    zi = functools.partial(jnp.zeros, dtype=jnp.int32)
    best = jnp.full((L, 12), NEG_INF, jnp.float32).at[:, B_FEAT:].set(0.0)
    best = best.at[0].set(row0)
    best_cat = jnp.zeros((L, cat_b), jnp.float32).at[0].set(cm0)
    # pool dtype follows the histogram dtype: int32 on the quantized
    # path (sibling subtraction below is then exact integer arithmetic)
    pool = jnp.zeros((K, pool_c, col_bins, 3), hist0.dtype).at[0].set(hist0)
    rec = jnp.zeros((L - 1, 13), jnp.float32)
    carry = _CarryC(
        k=jnp.int32(0),
        data=data0,
        pos_leaf=jnp.zeros(n + wmax, jnp.int32),
        leaf_begin=zi(L), leaf_phys=zi(L).at[0].set(n),
        pool=pool,
        slot_of=jnp.full((L,), -1, jnp.int32).at[0].set(0),
        slot_owner=jnp.full((K,), -1, jnp.int32).at[0].set(0),
        slot_last=zi(K),
        depth=zi(L),
        leaf_min=jnp.full((L,), -np.inf, jnp.float32),
        leaf_max=jnp.full((L,), np.inf, jnp.float32),
        best=best, best_cat=best_cat, rec=rec,
        rec_cat=jnp.zeros((L - 1, cat_b), jnp.float32), key=loop_key)

    def cond(c: _CarryC):
        return (c.k < L - 1) & (jnp.max(c.best[:, B_GAIN]) > 1e-10)

    def make_branch(wsz: int):
        half = (wsz + 1) // 2

        def branch(op):
            if renew:
                c, l, row, new_id, need_other, rq = op
                rq_g, rq_h = rq
            else:
                c, l, row, new_id, need_other = op
                rq_g = rq_h = jnp.float32(1.0)
            feat = row[B_FEAT].astype(jnp.int32)
            begin = c.leaf_begin[l]
            pcount = c.leaf_phys[l]

            win = jax.lax.dynamic_slice(c.data, (begin, 0), (wsz, d_cols))
            valid = jnp.arange(wsz, dtype=jnp.int32) < pcount
            go_left = packed_go_left(
                win, feat, row[B_THR].astype(jnp.int32),
                row[B_DLEFT] > 0.5, f_numbins, f_missing, f_default,
                f_col, f_base, f_elide, item_bits=item_bits,
                f_categorical=f_categorical if has_cat else None,
                cat_mask=c.best_cat[l] if has_cat else None) & valid
            if renew:
                # each child's stored-int maxes seed its leaf-local
                # requant ratio (measured here: the window is in hand)
                qmax2 = _quant_side_maxes(win, go_left, valid, cw=cw, gw=gw)

            # stable partition of the window (reference DataPartition::
            # Split): overrun rows past pcount get key 2; the full 3-way
            # compaction is identity on them (they are already tail-
            # contiguous), so they return to their slots untouched
            key3 = jnp.where(valid, jnp.where(go_left, 0, 1), 2)
            win_sorted = partition_window(win, key3, partition)
            data = jax.lax.dynamic_update_slice(c.data, win_sorted,
                                                (begin, 0))
            lphys = jnp.sum(go_left.astype(jnp.int32))
            rphys = pcount - lphys
            # pos_leaf / leaf_begin / leaf_phys updates happen OUTSIDE the
            # switch (the body computes them from lphys): fewer branch
            # outputs means fewer carry buffers crossing the conditional
            # boundary, where XLA's copy insertion is conservative — the
            # (N,)-sized pos_leaf update in particular cost a full-array
            # copy per split here

            # LOCAL histogram of the GLOBALLY smaller child (all shards
            # must hist the same side so the cross-shard sum is one
            # child's histogram; the choice key is the replicated global
            # count from the split record). Fast path: the side fits the
            # contiguous half window; fallback (possible only when local
            # physical share is skewed vs the global choice under
            # bagging/sharding): masked pass over the full window.
            left_small = row[B_LCNT] <= row[B_RCNT]
            s_begin = jnp.where(left_small, 0, lphys)
            s_count = jnp.where(left_small, lphys, rphys)
            hist_dtype = jnp.int32 if quant else jnp.float32

            def win_hist(rows2d, vbool):
                """Histogram of a row window restricted to `vbool` rows —
                the one layout dispatch (float triple vs packed int)."""
                s_codes = decode_for_hist(rows2d[:, :cw])
                if quant:
                    ghq = _quant_win_operand(
                        rows2d, vbool, cw=cw, gw=gw, quant_bits=quant_bits,
                        qcap_op=qcap_op, r_g=rq_g, r_h=rq_h)
                    return build_histogram_quantized(
                        s_codes, ghq, col_bins, use_pallas=use_pallas)
                s_gh = jax.lax.bitcast_convert_type(
                    rows2d[:, cw:cw + 3], jnp.float32) \
                    * vbool.astype(jnp.float32)[:, None]
                return build_histogram(s_codes, s_gh, col_bins,
                                       use_pallas=use_pallas)

            def hist_half(_):
                start = jnp.clip(s_begin, 0, wsz - half)
                off = s_begin - start
                sw = jax.lax.dynamic_slice(win_sorted, (start, 0),
                                           (half, d_cols))
                j = jnp.arange(half, dtype=jnp.int32)
                return win_hist(sw, (j >= off) & (j < off + s_count))

            def hist_range(range_begin, range_count):
                # masked full-window pass over [range_begin,
                # range_begin + range_count)
                j = jnp.arange(wsz, dtype=jnp.int32)
                return win_hist(win_sorted,
                                (j >= range_begin)
                                & (j < range_begin + range_count))

            if trivial_weights and axis_name is None:
                # all-ones weights single-chip: record counts equal
                # physical counts, so the smaller side always fits the
                # contiguous half window — the masked full-window
                # fallback (and its extra compiled histogram program
                # per window class) is statically dead
                hist_small = hist_half(None)
            else:
                hist_small = jax.lax.cond(
                    s_count <= half, hist_half,
                    lambda _: hist_range(s_begin, s_count), operand=None)

            # pooled mode, parent-histogram miss: the sibling cannot come
            # from subtraction, so build the LARGER child's histogram
            # directly with a masked pass over the window (reference
            # HistogramPool miss -> ConstructHistograms re-run)
            if pooled:
                o_begin = jnp.where(left_small, lphys, 0)
                o_count = pcount - s_count
                hist_other = jax.lax.cond(
                    need_other, lambda _: hist_range(o_begin, o_count),
                    lambda _: jnp.zeros((hist_cols, col_bins, 3),
                                        hist_dtype),
                    operand=None)
            else:
                hist_other = jnp.zeros((hist_cols, col_bins, 3),
                                       hist_dtype)
            out = (data, lphys, hist_small, hist_other)
            return out + (qmax2,) if renew else out
        return branch

    branches = [make_branch(wsz) for wsz in classes]

    def body(c: _CarryC, qx=None):
        b = c.best
        l = jnp.argmax(b[:, B_GAIN]).astype(jnp.int32)
        row = b[l]
        new_id = c.k + 1
        feat = row[B_FEAT].astype(jnp.int32)
        pcount = c.leaf_phys[l]
        slot_l = c.slot_of[l]
        have_parent = slot_l >= 0
        j = jnp.sum((pcount > thresholds).astype(jnp.int32))
        if renew:
            # the leaf's operand ratio comes from maxes recorded at its
            # CREATION (replicated), so the branch needs no collective
            scale_of, leafmax = qx
            rq_g, rq_h = q_ratios(leafmax[l])
            data, lphys, hist_small, hist_other, qmax2 = jax.lax.switch(
                j, branches,
                (c, l, row, new_id, ~have_parent, (rq_g, rq_h)))
            if axis_name is not None:
                qmax2 = jax.lax.pmax(qmax2, axis_name)
        else:
            rq_g = rq_h = jnp.float32(1.0)
            data, lphys, hist_small, hist_other = jax.lax.switch(
                j, branches, (c, l, row, new_id, ~have_parent))
        begin = c.leaf_begin[l]
        rphys = pcount - lphys
        leaf_begin = c.leaf_begin.at[new_id].set(begin + lphys)
        leaf_phys = c.leaf_phys.at[l].set(lphys).at[new_id].set(rphys)
        # O(N) elementwise pos_leaf rewrite (fuses to one in-place pass;
        # cheaper than carrying the update through the conditional)
        posv = jnp.arange(n + wmax, dtype=jnp.int32)
        pos_leaf = jnp.where(
            (posv >= begin) & (posv < begin + lphys), l,
            jnp.where((posv >= begin + lphys) & (posv < begin + pcount),
                      new_id, c.pos_leaf))
        left_small = row[B_LCNT] <= row[B_RCNT]
        if axis_name is not None:
            # cross-shard histogram reduction: psum replicates (dense
            # equivalent of the reference's reduce-scatter, scan runs
            # identically everywhere); scatter mode IS the reference's
            # pattern (each shard owns its column tile). The miss-path
            # histogram reduces alongside so no shard ever takes a
            # collective the others skip.
            if quant and scatter:
                # two integer lanes on the wire; counts reconstructed
                # from the hessian lane + the replicated global count
                s_cnt_g = jnp.where(left_small, row[B_LCNT], row[B_RCNT])
                s_qh_g = jnp.where(left_small, row[B_LSH], row[B_RSH]) \
                    * (q_sh * rq_h)
                hist_small = reduce_q(hist_small, s_cnt_g, s_qh_g)
                if pooled:
                    o_cnt_g = row[B_LCNT] + row[B_RCNT] - s_cnt_g
                    o_qh_g = (row[B_LSH] + row[B_RSH]) * (q_sh * rq_h) \
                        - s_qh_g
                    hist_other = reduce_q(hist_other, o_cnt_g, o_qh_g)
            else:
                hist_small = reduce_hist(hist_small)
                if pooled:
                    hist_other = reduce_hist(hist_other)

        parent = (c.pool[jnp.clip(slot_l, 0, K - 1)] if pooled
                  else c.pool[l])
        if renew:
            # re-express the parent pool entry in the split's ratio
            # before subtraction (counts pass through exact)
            parent = quant_ops.rescale_histogram(
                parent, rq_g / scale_of[l, 0], rq_h / scale_of[l, 1])
        sibling = jnp.where(have_parent, parent - hist_small, hist_other) \
            if pooled else parent - hist_small
        hist_l = jnp.where(left_small, hist_small, sibling)
        hist_r = jnp.where(left_small, sibling, hist_small)

        # pool slot bookkeeping: l reuses its parent slot when cached,
        # otherwise allocates; new_id always allocates. Allocation takes
        # a free slot first, else evicts the least-recently-used (the
        # reference HistogramPool's Get/Move semantics).
        step = new_id
        if pooled:
            iarangeK = jnp.arange(K, dtype=jnp.int32)

            def alloc(slot_of, slot_owner, slot_last, forbid, want):
                score = jnp.where(slot_owner < 0, jnp.int32(-1), slot_last)
                score = jnp.where(iarangeK == forbid,
                                  jnp.iinfo(jnp.int32).max, score)
                s = jnp.argmin(score).astype(jnp.int32)
                old = slot_owner[s]
                safe_old = jnp.clip(old, 0, L - 1)
                slot_of = slot_of.at[safe_old].set(
                    jnp.where(want & (old >= 0), -1, slot_of[safe_old]))
                return s, slot_of

            s_l_new, slot_of = alloc(c.slot_of, c.slot_owner, c.slot_last,
                                     jnp.int32(-1), ~have_parent)
            s_l = jnp.where(have_parent, slot_l, s_l_new)
            slot_of = slot_of.at[l].set(s_l)
            slot_owner = c.slot_owner.at[s_l].set(l)
            slot_last = c.slot_last.at[s_l].set(step)
            s_r, slot_of = alloc(slot_of, slot_owner, slot_last, s_l,
                                 jnp.bool_(True))
            slot_of = slot_of.at[new_id].set(s_r)
            slot_owner = slot_owner.at[s_r].set(new_id)
            slot_last = slot_last.at[s_r].set(step)
        else:
            s_l, s_r = l, new_id
            slot_of = c.slot_of
            slot_owner, slot_last = c.slot_owner, c.slot_last
        pool = c.pool.at[s_l].set(hist_l).at[s_r].set(hist_r)

        if quant:
            # scans read f32: dequantize the children at the split's
            # leaf-local scale (the pool keeps the exact integers)
            hist_l_s = q_dequant(hist_l, rq_g, rq_h)
            hist_r_s = q_dequant(hist_r, rq_g, rq_h)
        else:
            hist_l_s, hist_r_s = hist_l, hist_r
        (key, leaf_min, leaf_max, depth, rec2, rec_cat2, best2,
         best_cat2) = split_epilogue(
            k=c.k, key=c.key, l=l, new_id=new_id, row=row,
            mono_f=f_monotone[feat], best_cat_l=c.best_cat[l],
            leaf_min=c.leaf_min, leaf_max=c.leaf_max, depth=c.depth,
            rec=c.rec, rec_cat=c.rec_cat, best=b, best_cat=c.best_cat,
            hist_l=hist_l_s, hist_r=hist_r_s, search2=search2_rows)
        c2 = _CarryC(new_id, data, pos_leaf, leaf_begin, leaf_phys,
                     pool, slot_of, slot_owner, slot_last,
                     depth, leaf_min, leaf_max, best2, best_cat2,
                     rec2, rec_cat2, key)
        if renew:
            scale2 = jnp.stack([rq_g, rq_h])
            return c2, (scale_of.at[l].set(scale2).at[new_id].set(scale2),
                        leafmax.at[l].set(qmax2[0]).at[new_id]
                        .set(qmax2[1]))
        return c2, None

    if renew:
        scale0 = jnp.ones((L, 2), jnp.float32) \
            .at[0].set(jnp.stack([r0_g, r0_h]))
        leafmax0 = jnp.zeros((L, 2), jnp.float32).at[0].set(root_max)
        out, _ = run_split_loop(
            lambda t: cond(t[0]), lambda t: body(t[0], t[1]),
            (carry, (scale0, leafmax0)), L - 1, grow_program)
    else:
        out = run_split_loop(cond, lambda cc: body(cc)[0], carry,
                             L - 1, grow_program)
    # final row -> leaf map: scatter physical-position leaves onto row ids
    row_ids = out.data[:n, d_cols - 1].astype(jnp.int32)
    leaf_id = jnp.zeros(n, jnp.int32).at[row_ids].set(
        out.pos_leaf[:n], unique_indices=True)
    return (out.rec, out.rec_cat if has_cat else None,
            leaf_id, out.k, totals)


class _CarryK(NamedTuple):
    k: jax.Array
    data: jax.Array          # (N + CH, D) u32 packed rows grouped by leaf
    scratch: jax.Array       # (N + CH, D) u32 right-segment staging
    pos_leaf: jax.Array      # (N + CH,) leaf id per physical position
    leaf_begin: jax.Array    # (L,)
    leaf_phys: jax.Array     # (L,)
    pool: jax.Array          # (L, C, B, 3) dense histogram pool
    depth: jax.Array
    leaf_min: jax.Array
    leaf_max: jax.Array
    best: jax.Array          # (L, 12) f32
    best_cat: jax.Array      # (L, B|1) f32
    rec: jax.Array           # (L-1, 13) f32
    rec_cat: jax.Array       # (L-1, B|1) f32
    key: jax.Array


@functools.partial(
    jax.jit,
    static_argnames=("c_cols", "item_bits",
                     "num_leaves", "num_bins", "col_bins", "max_depth",
                     "bynode_k", "use_pallas", "partition",
                     "chunk_rows", "fuse_hist", "feature_shards",
                     "cat_statics", "trivial_weights", "quant_bits",
                     "quant_renew", "data_prebuilt", "grow_program"))
def grow_tree_chunk(
        codes_pack: jax.Array, codes_row: jax.Array,
        grad: jax.Array, hess: jax.Array, w: jax.Array,
        base_mask: jax.Array,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_categorical, f_col, f_base, f_elide, hist_idx, rng_key,
        *, c_cols: int, item_bits: int,
        num_leaves: int, num_bins: int, col_bins: int, max_depth: int,
        l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: int, min_sum_hessian: float,
        min_gain_to_split: float, bynode_k: int, use_pallas: bool,
        partition: str = "sort", chunk_rows: int = 65536,
        fuse_hist: bool = True, feature_shards: int = 0,
        cat_statics=None, trivial_weights: bool = False,
        quant_bits: int = 0, quant_renew: bool = True,
        data_prebuilt: bool = False, grow_program: str = "per_split"):
    return grow_tree_chunk_core(
        codes_pack, codes_row, grad, hess, w, base_mask,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_categorical, f_col, f_base, f_elide, hist_idx, rng_key,
        c_cols=c_cols, item_bits=item_bits, num_leaves=num_leaves,
        num_bins=num_bins, col_bins=col_bins, max_depth=max_depth,
        l1=l1, l2=l2, max_delta_step=max_delta_step,
        min_data_in_leaf=min_data_in_leaf, min_sum_hessian=min_sum_hessian,
        min_gain_to_split=min_gain_to_split, bynode_k=bynode_k,
        use_pallas=use_pallas, partition=partition, chunk_rows=chunk_rows,
        fuse_hist=fuse_hist, feature_shards=feature_shards,
        axis_name=None, cat_statics=cat_statics,
        trivial_weights=trivial_weights, quant_bits=quant_bits,
        quant_renew=quant_renew, data_prebuilt=data_prebuilt,
        grow_program=grow_program)


def grow_tree_chunk_core(
        codes_pack: jax.Array, codes_row: jax.Array,
        grad: jax.Array, hess: jax.Array, w: jax.Array,
        base_mask: jax.Array,
        f_numbins, f_missing, f_default, f_monotone, f_penalty,
        f_categorical, f_col, f_base, f_elide, hist_idx, rng_key,
        *, c_cols: int, item_bits: int,
        num_leaves: int, num_bins: int, col_bins: int, max_depth: int,
        l1: float, l2: float, max_delta_step: float,
        min_data_in_leaf: int, min_sum_hessian: float,
        min_gain_to_split: float, bynode_k: int, use_pallas: bool,
        partition: str = "sort", chunk_rows: int = 65536,
        fuse_hist: bool = True, feature_shards: int = 0,
        scatter_cols: int = 0, voting_k: int = 0,
        axis_name=None, cat_statics=None, trivial_weights: bool = False,
        quant_bits: int = 0, quant_renew: bool = True,
        quant_total_rows: int = 0, data_prebuilt: bool = False,
        grow_program: str = "per_split"):
    """Switch-free whole-tree growth over fixed-size chunks.

    The compact strategy resolves dynamic leaf sizes with a lax.switch
    over padded window classes; XLA's copy insertion around that
    conditional copies the packed working buffer once per split, and
    every class duplicates the branch program. This variant removes the
    conditional entirely: a split of a p-row leaf runs ceil(p / CH)
    iterations of fixed-(CH, D)-shaped inner fori loops, so every carry
    update is an unconditional dynamic_update_slice XLA aliases in
    place, one traced partition program serves every leaf size, and the
    per-split fixed cost is a handful of chunk passes instead of the
    branch machinery.

    Correctness of the in-place movement (reference DataPartition::Split
    semantics, stable 3-way):
      * pass B (forward over chunks): chunk i's rows are read before any
        write that can touch them — left writes land in
        [begin, begin+loff[i]+CH) which never reaches past chunk i's own
        region (loff[i] <= i*CH), and are merge-masked to exactly
        lcnt[i] rows so rows of later chunks are preserved; right
        segments stage front-aligned at chunk i's own location in a
        scratch buffer.
      * pass C (forward): staged right segments place at
        begin + L_tot + roff[i], merge-masked to rcnt[i] rows, so the
        garbage tail never leaks into the next leaf.
      * rows past the leaf end (other leaves' rows in the final chunk)
        carry partition key 2 and are never written.
    The smaller child's histogram accumulates over its chunks after the
    move (sibling = parent - smaller, FeatureHistogram::Subtract).

    axis_name enables the sharded modes, all four of the compact
    core's reductions:
      * data-parallel psum (rows sharded; root and smaller-child
        histograms psum-replicate and every shard runs the identical
        scan — data_parallel_tree_learner.cpp:149-164 in its
        replicated rendering);
      * scatter_cols > 1: the reference comm pattern — per-chunk
        histograms accumulate full-width locally, ONE lax.psum_scatter
        per split tiles the column axis so each shard scans only the
        C/D columns it owns, and the winner is elected from a (D, 12+B)
        all_gather of candidate rows (make_sliced_search;
        data_parallel_tree_learner.cpp:149-200 + SyncUpGlobalBestSplit);
      * voting_k > 0: PV-Tree 2-stage voting — local scan + top-k vote,
        elect 2k, reduce only the elected features' histograms
        (make_voting_search; voting_parallel_tree_learner.cpp:170-260);
      * feature_shards > 1: feature-parallel (rows replicated,
        histograms built and scanned per column slice, winners elected
        via make_sliced_search — feature_parallel_tree_learner.cpp:33-76).
    The LRU-capped histogram pool stays on the compact strategy.

    data_prebuilt=True is the streaming entry (io/stream.py +
    DeviceTreeLearner's stream assembly): `codes_pack` is then the
    ALREADY-ASSEMBLED (n + CH, cw + gw + 1) working buffer data0
    (`[packed codes | gh words | row id]`, CH zero-pad rows) and
    `codes_row` a dummy — the core skips its in-program data0 build and
    accumulates the root histogram chunk-wise over the buffer with the
    same contraction the split loop uses, so no full-N `codes_pack` /
    `codes_row` device copies ever exist. Everything downstream of the
    root (carry, split loop, epilogue) is the identical program, which
    is what makes streamed output bit-identical to resident growth
    (serial only; the sharded modes keep their resident inputs).
    """
    from ..ops.histogram import build_histogram, build_histogram_quantized
    n = grad.shape[0]
    L = num_leaves
    CH = int(chunk_rows)
    maxch = -(-n // CH)
    has_cat = cat_statics is not None
    cat_b = num_bins if has_cat else 1
    quant = quant_bits > 0
    if data_prebuilt:
        # serial streaming, or streamed data-parallel over the plain
        # psum lane (each shard's buffer holds its own rows; per-leaf
        # histograms are the only cross-shard exchange)
        assert feature_shards <= 1 and scatter_cols <= 1 \
            and voting_k <= 0, \
            "data_prebuilt runs the serial or plain-psum DP chunk core"
        cw = codes_pack.shape[1] - ((1 if trivial_weights else 2)
                                    if quant else 3) - 1
        assert codes_pack.shape[0] == n + CH, \
            "prebuilt data0 must carry CH zero-pad rows"
    else:
        cw = codes_pack.shape[1]
    if not quant and not data_prebuilt:
        gh = jnp.stack([grad * w, hess * w, w], axis=1)
    helper_kwargs = dict(
        num_bins=num_bins, max_depth=max_depth, l1=l1, l2=l2,
        max_delta_step=max_delta_step, min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian=min_sum_hessian, min_gain_to_split=min_gain_to_split,
        bynode_k=bynode_k)
    fp = feature_shards > 1 and axis_name is not None
    scatter = scatter_cols > 1 and axis_name is not None and not fp
    voting = voting_k > 0 and axis_name is not None and not (scatter or fp)
    per_w = 32 // item_bits

    # quantized packed rows: same layout + leaf-requant scheme as the
    # compact core (see grow_tree_compact_core); the supported sharded
    # reductions are serial, DP psum and DP scatter
    assert not (quant and (voting or fp)), \
        "quantized packed rows: voting/feature-parallel modes fall back " \
        "to the host learners (create_tree_learner gates)"
    renew = quant and quant_renew
    if quant:
        n_total = quant_total_rows or n
        qcap_op = quant_ops.quant_max(quant_bits, n_total)
        rng_key, gh_packed, q_sg, q_sh, root_max = _quant_prepare(
            grad, hess, w, rng_key, quant_bits=quant_bits,
            quant_renew=quant_renew, n_total=n_total, axis_name=axis_name)
        gw = 1 if trivial_weights else 2

        def q_ratios(leaf_max):
            if not renew:
                return jnp.float32(1.0), jnp.float32(1.0)
            return (quant_ops.requant_ratio(leaf_max[0], qcap_op),
                    quant_ops.requant_ratio(leaf_max[1], qcap_op))

        def q_dequant(h_int, r_g, r_h):
            return h_int.astype(jnp.float32) * quant_ops.dequant_scale3(
                q_sg * r_g, q_sh * r_h)

        if scatter:
            reduce_q = make_scatter_reduce_q(
                axis_name, scatter_cols, c_cols,
                quant_ops.wire_dtype(quant_bits, n_total))
    else:
        gw = 3
    d_cols = cw + gw + 1
    if fp:
        # feature-parallel: rows replicated, each shard builds and scans
        # only its word-aligned column slice; the winner is elected from
        # the all_gather of candidate rows (make_sliced_search)
        (reduce_hist, search_row, search2, cs, shard,
         _start) = make_sliced_search(
            axis_name=axis_name, fp=True, D=feature_shards,
            c_cols=c_cols, col_bins=col_bins, item_bits=item_bits,
            base_mask=base_mask, f_numbins=f_numbins, f_missing=f_missing,
            f_default=f_default, f_monotone=f_monotone,
            f_penalty=f_penalty, f_elide=f_elide,
            f_categorical=f_categorical, has_cat=has_cat,
            cat_statics=cat_statics, helper_kwargs=helper_kwargs)
        cs_words = cs // per_w
        assert cw >= cs_words * feature_shards, \
            "feature-parallel needs codes packed to the padded column count"
        w0 = (shard * cs_words).astype(jnp.int32)
        hist_w = cs

        def decode_hist_cols(words2d):
            wsl = jax.lax.dynamic_slice(
                words2d, (jnp.int32(0), w0), (words2d.shape[0], cs_words))
            return _unpack_codes(wsl, cs, item_bits)
    elif scatter:
        # per-chunk histograms accumulate FULL-width locally; one
        # psum_scatter per split hands each shard its column slice
        (reduce_hist, search_row, search2, cs, shard,
         _start) = make_sliced_search(
            axis_name=axis_name, fp=False, D=scatter_cols,
            c_cols=c_cols, col_bins=col_bins, item_bits=item_bits,
            base_mask=base_mask, f_numbins=f_numbins, f_missing=f_missing,
            f_default=f_default, f_monotone=f_monotone,
            f_penalty=f_penalty, f_elide=f_elide,
            f_categorical=f_categorical, has_cat=has_cat,
            cat_statics=cat_statics, helper_kwargs=helper_kwargs)
        hist_w = cs

        def decode_hist_cols(words2d):
            return _unpack_codes(words2d[:, :cw], c_cols, item_bits)
    elif voting:
        reduce_hist, search_row, search2 = make_voting_search(
            axis_name=axis_name, voting_k=voting_k, c_cols=c_cols,
            col_bins=col_bins, base_mask=base_mask,
            f_numbins=f_numbins, f_missing=f_missing,
            f_default=f_default, f_monotone=f_monotone,
            f_penalty=f_penalty, f_elide=f_elide, hist_idx=hist_idx,
            f_categorical=f_categorical, has_cat=has_cat,
            cat_statics=cat_statics, helper_kwargs=helper_kwargs)
        hist_w = c_cols

        def decode_hist_cols(words2d):
            return _unpack_codes(words2d[:, :cw], c_cols, item_bits)
    else:
        (node_mask, scan, store_best, scan2,
         best_row) = _tree_helpers(
            base_mask, f_numbins, f_missing, f_default, f_monotone,
            f_penalty, f_elide, hist_idx,
            f_categorical=f_categorical, cat_statics=cat_statics,
            **helper_kwargs)
        hist_w = c_cols

        def decode_hist_cols(words2d):
            return _unpack_codes(words2d[:, :cw], c_cols, item_bits)

        def search_row(col_hist, sg, sh, cnt, mn, mx, key, child_depth):
            res, cm = scan(col_hist, sg, sh, cnt, mn, mx, node_mask(key))
            return best_row(res, child_depth), cm

        search2 = search2_simple(scan2, best_row)

        if axis_name is not None:
            def reduce_hist(h):
                return jax.lax.psum(h, axis_name)
        else:
            def reduce_hist(h):
                return h

    if data_prebuilt:
        # the streaming layer assembled data0 on device (gh words from
        # the SAME _quant_prepare key in the quantized case, so the
        # in-program scale/key derivation above stays the one source)
        data0 = codes_pack
    else:
        if quant:
            gh_u = _quant_gh_words(gh_packed, w, gw)
        else:
            gh_u = jax.lax.bitcast_convert_type(gh, jnp.uint32)
        ids = jnp.arange(n, dtype=jnp.uint32)[:, None]
        data0 = jnp.concatenate([codes_pack, gh_u, ids], axis=1)
        data0 = jnp.concatenate(
            [data0, jnp.zeros((CH, d_cols), jnp.uint32)], axis=0)

    if data_prebuilt and quant:
        # chunk-wise root accumulation over the prebuilt buffer: same
        # per-chunk contraction as the split loop's chunk_hist. The
        # int32 partial sums make the grouping change exactly
        # associative, so this equals the resident full-N build
        # bit-for-bit.
        r0_g, r0_h = q_ratios(root_max)
        iota_root = jnp.arange(CH, dtype=jnp.int32)

        from ..ops.histogram import accumulate_histogram

        def root_chunk(i, acc):
            win = jax.lax.dynamic_slice(
                data0, (i * CH, jnp.int32(0)), (CH, data0.shape[1]))
            count = jnp.clip(n - i * CH, 0, CH)
            codes = decode_hist_cols(win[:, :cw])
            operand = _quant_win_operand(
                win, iota_root < count, cw=cw, gw=gw,
                quant_bits=quant_bits, qcap_op=qcap_op,
                r_g=r0_g, r_h=r0_h)
            return accumulate_histogram(acc, codes, operand, col_bins,
                                        use_pallas=use_pallas)

        hist0 = jax.lax.fori_loop(
            0, maxch, root_chunk,
            jnp.zeros((hist_w, col_bins, 3), jnp.int32))
        if axis_name is not None:
            hist0 = jax.lax.psum(hist0, axis_name)
        totals = q_dequant(hist0[0].sum(axis=0), r0_g, r0_h)
        hist0_scan = q_dequant(hist0, r0_g, r0_h)
    elif data_prebuilt:
        # float path: f32 adds are NOT associative, so chunk-wise
        # accumulation would regroup the resident full-N contraction and
        # break bit-identity for arbitrary gradients. data0 already
        # holds every row, so run the identical full-N build on a
        # transient decode (same shapes/values as the resident
        # codes_row + gh operands; freed after the root build).
        hist0 = build_histogram(
            decode_hist_cols(data0[:n]),
            jax.lax.bitcast_convert_type(data0[:n, cw:cw + 3],
                                         jnp.float32),
            col_bins, use_pallas=use_pallas)
        hist0 = reduce_hist(hist0)
        totals = hist0[0].sum(axis=0)
    elif quant:
        r0_g, r0_h = q_ratios(root_max)
        ghq0 = quant_ops.gh_operand_scaled(
            gh_packed, w > 0, quant_bits, qcap_op, r0_g, r0_h)
        hist0 = build_histogram_quantized(codes_row, ghq0, col_bins,
                                          use_pallas=use_pallas)
        if scatter:
            tot_q = jax.lax.psum(hist0[0].sum(axis=0), axis_name)
            totals = q_dequant(tot_q, r0_g, r0_h)
            hist0 = reduce_q(hist0, totals[2], tot_q[1].astype(jnp.float32))
        else:
            if axis_name is not None:
                hist0 = jax.lax.psum(hist0, axis_name)
            totals = q_dequant(hist0[0].sum(axis=0), r0_g, r0_h)
        hist0_scan = q_dequant(hist0, r0_g, r0_h)
    elif fp:
        # rows replicated: totals come straight from gh; root histogram
        # from this shard's column slice only
        totals = gh.sum(axis=0)
        cr = codes_row
        if cr.shape[1] < cs * feature_shards:
            cr = jnp.pad(
                cr, ((0, 0), (0, cs * feature_shards - cr.shape[1])))
        cr_sl = jax.lax.dynamic_slice(
            cr, (jnp.int32(0), (shard * cs).astype(jnp.int32)), (n, cs))
        hist0 = build_histogram(cr_sl, gh, col_bins, use_pallas=use_pallas)
    else:
        hist0 = build_histogram(codes_row, gh, col_bins,
                                use_pallas=use_pallas)
        if scatter or voting:
            # global totals first: the post-reduce histogram is a
            # column slice (scatter) / stays local (voting)
            totals = jax.lax.psum(hist0[0].sum(axis=0), axis_name)
            hist0 = reduce_hist(hist0)
        else:
            hist0 = reduce_hist(hist0)
            totals = hist0[0].sum(axis=0)
    if not quant:
        hist0_scan = hist0
    root_key, loop_key = jax.random.split(rng_key)
    row0, cm0 = search_row(hist0_scan, totals[0], totals[1], totals[2],
                           jnp.float32(-np.inf), jnp.float32(np.inf),
                           root_key, jnp.int32(0))
    best = jnp.full((L, 12), NEG_INF, jnp.float32).at[:, B_FEAT:].set(0.0)
    best = best.at[0].set(row0)
    best_cat = jnp.zeros((L, cat_b), jnp.float32).at[0].set(cm0)
    zi = functools.partial(jnp.zeros, dtype=jnp.int32)
    carry = _CarryK(
        k=jnp.int32(0), data=data0, scratch=jnp.zeros_like(data0),
        pos_leaf=jnp.zeros(n + CH, jnp.int32),
        leaf_begin=zi(L), leaf_phys=zi(L).at[0].set(n),
        pool=jnp.zeros((L, hist_w, col_bins, 3), hist0.dtype).at[0]
            .set(hist0),
        depth=zi(L),
        leaf_min=jnp.full((L,), -np.inf, jnp.float32),
        leaf_max=jnp.full((L,), np.inf, jnp.float32),
        best=best, best_cat=best_cat,
        rec=jnp.zeros((L - 1, 13), jnp.float32),
        rec_cat=jnp.zeros((L - 1, cat_b), jnp.float32), key=loop_key)

    iota_ch = jnp.arange(CH, dtype=jnp.int32)

    def cond(c: _CarryK):
        return (c.k < L - 1) & (jnp.max(c.best[:, B_GAIN]) > 1e-10)

    def body(c: _CarryK, qx=None):
        b = c.best
        l = jnp.argmax(b[:, B_GAIN]).astype(jnp.int32)
        row = b[l]
        new_id = c.k + 1
        feat = row[B_FEAT].astype(jnp.int32)
        thr = row[B_THR].astype(jnp.int32)
        dleft = row[B_DLEFT] > 0.5
        cmask = c.best_cat[l] if has_cat else None
        begin = c.leaf_begin[l]
        p = c.leaf_phys[l]
        nch = -(-p // CH)
        if renew:
            scale_of, leafmax = qx
            rq_g, rq_h = q_ratios(leafmax[l])
        else:
            rq_g = rq_h = jnp.float32(1.0)
        # the GLOBALLY smaller child (replicated record counts) decides
        # which side's rows accumulate the fused histogram
        left_small = row[B_LCNT] <= row[B_RCNT]
        # scatter accumulates chunks FULL-width locally (the one
        # psum_scatter afterwards maps it to this shard's hist_w slice);
        # every other mode accumulates at pool width directly
        acc_w = c_cols if scatter else hist_w
        hist_zero = jnp.zeros((acc_w, col_bins, 3),
                              jnp.int32 if quant else jnp.float32)

        def chunk_hist(rows_win, count):
            codes = decode_hist_cols(rows_win[:, :cw])
            if quant:
                ghq = _quant_win_operand(
                    rows_win, iota_ch < count, cw=cw, gw=gw,
                    quant_bits=quant_bits, qcap_op=qcap_op,
                    r_g=rq_g, r_h=rq_h)
                return build_histogram_quantized(codes, ghq, col_bins,
                                                 use_pallas=use_pallas)
            v = (iota_ch < count).astype(jnp.float32)
            ghw = jax.lax.bitcast_convert_type(
                rows_win[:, cw:cw + 3], jnp.float32) * v[:, None]
            return build_histogram(codes, ghw, col_bins,
                                   use_pallas=use_pallas)

        # pass B: per chunk — read, decide, local 3-way stable partition,
        # exact-write lefts forward into data, stage rights in scratch;
        # when the LEFT child is the smaller one its histogram fuses in
        # (the chunk's left segment sits at win_s[:lc]) so no later pass
        # re-reads those rows
        fuse = fuse_hist

        def pass_b(i, acc):
            if renew:
                data, scratch, lrun, rcnt, hist, qmx = acc
            else:
                data, scratch, lrun, rcnt, hist = acc
            start = begin + i * CH
            win = jax.lax.dynamic_slice(data, (start, 0), (CH, d_cols))
            valid = iota_ch < (p - i * CH)
            gl = packed_go_left(
                win, feat, thr, dleft, f_numbins, f_missing, f_default,
                f_col, f_base, f_elide, item_bits=item_bits,
                f_categorical=f_categorical if has_cat else None,
                cat_mask=cmask) & valid
            if renew:
                qmx = jnp.maximum(
                    qmx, _quant_side_maxes(win, gl, valid, cw=cw, gw=gw))
            key3 = jnp.where(gl, 0, jnp.where(valid, 1, 2))
            win_s = partition_window(win, key3, partition)
            lc = jnp.sum(gl.astype(jnp.int32))
            vc = jnp.sum(valid.astype(jnp.int32))
            d_old = jax.lax.dynamic_slice(
                data, (begin + lrun, 0), (CH, d_cols))
            merged = jnp.where((iota_ch < lc)[:, None], win_s, d_old)
            data = jax.lax.dynamic_update_slice(
                data, merged, (begin + lrun, 0))
            win_pad = jnp.concatenate(
                [win_s, jnp.zeros((CH, d_cols), jnp.uint32)], axis=0)
            rights = jax.lax.dynamic_slice(
                win_pad, (lc, 0), (CH, d_cols))
            scratch = jax.lax.dynamic_update_slice(
                scratch, rights, (start, 0))
            if fuse:
                hist = hist + jax.lax.cond(
                    left_small, lambda _: chunk_hist(win_s, lc),
                    lambda _: hist_zero, operand=None)
            out = (data, scratch, lrun + lc, rcnt.at[i].set(vc - lc), hist)
            return out + (qmx,) if renew else out

        acc0 = (c.data, c.scratch, jnp.int32(0), zi(maxch), hist_zero)
        if renew:
            acc0 = acc0 + (jnp.zeros((2, 2), jnp.float32),)
            data, scratch, lphys, rcnt, hist_small, qmax2 = \
                jax.lax.fori_loop(0, nch, pass_b, acc0)
            if axis_name is not None:
                qmax2 = jax.lax.pmax(qmax2, axis_name)
        else:
            data, scratch, lphys, rcnt, hist_small = jax.lax.fori_loop(
                0, nch, pass_b, acc0)
        rphys = p - lphys
        roff = jnp.cumsum(rcnt) - rcnt

        # pass C: place staged right segments after the left block; when
        # the RIGHT child is smaller its histogram fuses here (chunk i's
        # rights sit at seg[:rcnt[i]])
        def pass_c(i, acc):
            data, hist = acc
            seg = jax.lax.dynamic_slice(
                scratch, (begin + i * CH, 0), (CH, d_cols))
            dst = begin + lphys + roff[i]
            d_old = jax.lax.dynamic_slice(data, (dst, 0), (CH, d_cols))
            merged = jnp.where((iota_ch < rcnt[i])[:, None], seg, d_old)
            data = jax.lax.dynamic_update_slice(data, merged, (dst, 0))
            if fuse:
                hist = hist + jax.lax.cond(
                    left_small, lambda _: hist_zero,
                    lambda _: chunk_hist(seg, rcnt[i]), operand=None)
            return data, hist

        data, hist_small = jax.lax.fori_loop(
            0, nch, pass_c, (data, hist_small))

        if not fuse:
            # separate smaller-child histogram pass (post-move layout)
            sb = begin + jnp.where(left_small, 0, lphys)
            sc = jnp.where(left_small, lphys, rphys)

            def pass_h(i, hist):
                start = sb + i * CH
                win = jax.lax.dynamic_slice(data, (start, 0),
                                            (CH, d_cols))
                return hist + chunk_hist(win, sc - i * CH)

            hist_small = jax.lax.fori_loop(0, -(-sc // CH), pass_h,
                                           hist_zero)
        # psum / psum_scatter-to-slice / identity (fp, voting, serial)
        if quant and scatter:
            s_cnt_g = jnp.where(left_small, row[B_LCNT], row[B_RCNT])
            s_qh_g = jnp.where(left_small, row[B_LSH], row[B_RSH]) \
                * (q_sh * rq_h)
            hist_small = reduce_q(hist_small, s_cnt_g, s_qh_g)
        elif quant:
            if axis_name is not None:
                hist_small = jax.lax.psum(hist_small, axis_name)
        else:
            hist_small = reduce_hist(hist_small)

        parent = c.pool[l]
        if renew:
            parent = quant_ops.rescale_histogram(
                parent, rq_g / scale_of[l, 0], rq_h / scale_of[l, 1])
        sibling = parent - hist_small
        hist_l = jnp.where(left_small, hist_small, sibling)
        hist_r = jnp.where(left_small, sibling, hist_small)
        pool = c.pool.at[l].set(hist_l).at[new_id].set(hist_r)

        leaf_begin = c.leaf_begin.at[new_id].set(begin + lphys)
        leaf_phys = c.leaf_phys.at[l].set(lphys).at[new_id].set(rphys)
        posv = jnp.arange(n + CH, dtype=jnp.int32)
        pos_leaf = jnp.where(
            (posv >= begin) & (posv < begin + lphys), l,
            jnp.where((posv >= begin + lphys) & (posv < begin + p),
                      new_id, c.pos_leaf))

        if quant:
            hist_l_s = q_dequant(hist_l, rq_g, rq_h)
            hist_r_s = q_dequant(hist_r, rq_g, rq_h)
        else:
            hist_l_s, hist_r_s = hist_l, hist_r
        (key, leaf_min, leaf_max, depth, rec2, rec_cat2, best2,
         best_cat2) = split_epilogue(
            k=c.k, key=c.key, l=l, new_id=new_id, row=row,
            mono_f=f_monotone[feat], best_cat_l=c.best_cat[l],
            leaf_min=c.leaf_min, leaf_max=c.leaf_max, depth=c.depth,
            rec=c.rec, rec_cat=c.rec_cat, best=b, best_cat=c.best_cat,
            hist_l=hist_l_s, hist_r=hist_r_s, search2=search2)
        c2 = _CarryK(new_id, data, scratch, pos_leaf, leaf_begin,
                     leaf_phys, pool, depth, leaf_min, leaf_max,
                     best2, best_cat2, rec2, rec_cat2, key)
        if renew:
            scale2 = jnp.stack([rq_g, rq_h])
            return c2, (scale_of.at[l].set(scale2).at[new_id].set(scale2),
                        leafmax.at[l].set(qmax2[0]).at[new_id]
                        .set(qmax2[1]))
        return c2, None

    if renew:
        scale0 = jnp.ones((L, 2), jnp.float32) \
            .at[0].set(jnp.stack([r0_g, r0_h]))
        leafmax0 = jnp.zeros((L, 2), jnp.float32).at[0].set(root_max)
        out, _ = run_split_loop(
            lambda t: cond(t[0]), lambda t: body(t[0], t[1]),
            (carry, (scale0, leafmax0)), L - 1, grow_program)
    else:
        out = run_split_loop(cond, lambda cc: body(cc)[0], carry,
                             L - 1, grow_program)
    row_ids = out.data[:n, d_cols - 1].astype(jnp.int32)
    leaf_id = jnp.zeros(n, jnp.int32).at[row_ids].set(
        out.pos_leaf[:n], unique_indices=True)
    return (out.rec, out.rec_cat if has_cat else None,
            leaf_id, out.k, totals)


def make_sliced_search(*, axis_name, fp, D, c_cols, col_bins, item_bits,
                       base_mask, f_numbins, f_missing, f_default,
                       f_monotone, f_penalty, f_elide, f_categorical,
                       has_cat, cat_statics, helper_kwargs):
    """Feature-sliced scan + candidate election, shared by the compact
    core's scatter/feature-parallel modes and the chunk core's
    feature-parallel mode: every shard searches only the columns it owns
    (after the reduce-scatter in scatter mode — fp=False — or built
    directly over its slice in feature-parallel mode — fp=True), then
    the winner is elected from an all_gather of per-shard candidate rows
    (SyncUpGlobalBestSplit role). Returns (reduce_hist, search_row,
    search2_rows, cs, shard, start)."""
    f_all = int(f_numbins.shape[0])
    assert f_all == c_cols, \
        "sliced modes require identity feature->column mapping"
    if fp:
        # slice boundaries fall on packed-word boundaries so the
        # window decode can slice words directly
        cs = padded_shard_cols(c_cols, D, item_bits)
    else:
        cs = -(-c_cols // D)            # columns per shard (padded)
    c_pad = cs * D
    shard = jax.lax.axis_index(axis_name)
    start = (shard * cs).astype(jnp.int32)

    def pad1(a, fill):
        return jnp.pad(a, (0, c_pad - f_all), constant_values=fill)

    def sl(a):
        return jax.lax.dynamic_slice_in_dim(a, start, cs)

    mask_sl = sl(pad1(base_mask, False))
    nb_sl = sl(pad1(f_numbins, 1))
    miss_sl = sl(pad1(f_missing, 0))
    def_sl = sl(pad1(f_default, 0))
    mono_sl = sl(pad1(f_monotone, 0))
    pen_sl = sl(pad1(f_penalty, 1.0))
    elide_sl = sl(pad1(f_elide, 0))
    cat_sl = sl(pad1(f_categorical, 0)) if has_cat else None
    # local expansion gather for the slice's flattened (cs*B + 1)
    # column histogram (identity mapping: feature j bin b -> j*B + b)
    hi_local = (jnp.arange(cs, dtype=jnp.int32)[:, None] * col_bins
                + jnp.arange(col_bins, dtype=jnp.int32)[None, :])
    hi_local = jnp.where(
        jnp.arange(col_bins, dtype=jnp.int32)[None, :] < nb_sl[:, None],
        hi_local, cs * col_bins)
    (_, scan_sl, _, _, best_row) = _tree_helpers(
        mask_sl, nb_sl, miss_sl, def_sl, mono_sl, pen_sl, elide_sl,
        hi_local, f_categorical=cat_sl, cat_statics=cat_statics,
        **helper_kwargs)

    if fp:
        def reduce_hist(h):
            return h     # already the local slice over ALL rows
    else:
        def reduce_hist(h):
            h = jnp.pad(h, ((0, c_pad - c_cols), (0, 0), (0, 0)))
            return jax.lax.psum_scatter(
                h, axis_name, scatter_dimension=0, tiled=True)

    def _elect(row, cm):
        # the candidate row carries its (B,) categorical left-bin
        # mask through the election so every shard can route the
        # partition on a categorical winner it does not own
        # (SyncUpGlobalBestSplit's serialized cat_threshold role,
        # split_info.hpp:22-193)
        payload = jnp.concatenate([row, cm])     # (12 + cat_b,)
        rows = jax.lax.all_gather(payload, axis_name)
        win = rows[jnp.argmax(rows[:, B_GAIN])]
        return win[:12], win[12:]

    def search_row(col_hist, sg, sh, cnt, mn, mx, key, child_depth):
        res, cm = scan_sl(col_hist, sg, sh, cnt, mn, mx, mask_sl)
        row = best_row(res, child_depth)
        row = row.at[B_FEAT].add(start.astype(jnp.float32))
        return _elect(row, cm)

    def search2_rows(col_hist2, sg2, sh2, cnt2, mn2, mx2, keys2,
                     child_depth):
        res2, cm2 = jax.vmap(scan_sl)(
            col_hist2, sg2, sh2, cnt2, mn2, mx2,
            jnp.broadcast_to(mask_sl, (2,) + mask_sl.shape))
        rows = jax.vmap(
            functools.partial(best_row, child_depth=child_depth))(res2)
        rows = rows.at[:, B_FEAT].add(start.astype(jnp.float32))
        payload = jnp.concatenate([rows, cm2], axis=1)   # (2, 12+cat_b)
        g = jax.lax.all_gather(payload, axis_name)       # (D, 2, .)
        win = jnp.argmax(g[:, :, B_GAIN], axis=0)        # (2,)
        sel = g[win, jnp.arange(2)]
        return sel[:, :12], sel[:, 12:]

    return reduce_hist, search_row, search2_rows, cs, shard, start


def partition_window(win: jax.Array, key3: jax.Array,
                     partition: str) -> jax.Array:
    """Stable 3-way reorder of a (W, D) u32 window by key3 in {0,1,2} —
    the ONE dispatch over the partition formulations (reference
    DataPartition::Split role), shared by the compact branches and the
    chunk passes. 'sort' = argsort+take; 'scan' = per-class exclusive
    ranks via cumsum + one row scatter (no sort passes); 'pallas' = the
    block-streaming one-hot-matmul kernel."""
    if partition == "pallas":
        from ..ops.pallas.partition_kernel import stable_partition3
        return stable_partition3(
            win, key3, interpret=jax.default_backend() != "tpu")
    if partition == "scan":
        is0 = key3 == 0
        is1 = key3 == 1
        i0 = is0.astype(jnp.int32)
        i1 = is1.astype(jnp.int32)
        i2 = (key3 == 2).astype(jnp.int32)
        n0 = jnp.sum(i0)
        n1 = jnp.sum(i1)
        d0 = jnp.cumsum(i0) - 1
        d1 = n0 + jnp.cumsum(i1) - 1
        d2 = n0 + n1 + jnp.cumsum(i2) - 1
        dest = jnp.where(is0, d0, jnp.where(is1, d1, d2))
        return jnp.zeros_like(win).at[dest].set(win, unique_indices=True)
    order = jnp.argsort(key3.astype(jnp.int8), stable=True)
    return jnp.take(win, order, axis=0)


def packed_go_left(win: jax.Array, feat, thr, dleft,
                   f_numbins, f_missing, f_default, f_col, f_base, f_elide,
                   *, item_bits: int, f_categorical=None,
                   cat_mask=None) -> jax.Array:
    """Decode feature `feat`'s codes from a packed u32 row window and
    apply the split decision — the one copy of the unpack + logical-bin +
    decide_left sequence shared by the partition branches and the
    out-of-bag router (any drift between them would silently mis-route).

    cat_mask (B,) enables categorical routing: when `feat` is categorical
    the row goes left iff its logical bin is set in the mask (the bitset
    semantics of CategoricalDecisionInner / partition_step_categorical)."""
    per = 32 // item_bits
    mask = jnp.uint32((1 << item_bits) - 1)
    n_r = win.shape[0]
    word = (f_col[feat] // per).astype(jnp.int32)
    sub = (f_col[feat] % per).astype(jnp.uint32)
    col32 = jax.lax.dynamic_slice(win, (0, word), (n_r, 1))[:, 0]
    col = ((col32 >> (sub * item_bits)) & mask).astype(jnp.int32)
    fbins = bundle_ops.logical_bins_for_feature(
        col, f_base[feat], f_default[feat], f_numbins[feat], f_elide[feat])
    num_left = decide_left(fbins, thr, dleft, f_missing[feat],
                           f_default[feat], f_numbins[feat])
    if cat_mask is None:
        return num_left
    cat_left = cat_mask[jnp.clip(fbins, 0, cat_mask.shape[0] - 1)] > 0.5
    return jnp.where(f_categorical[feat] != 0, cat_left, num_left)


def objective_buffer_names(objective):
    """Names of the objective's device buffers (label, weights,
    transformed labels, lambdarank's segment tensors ...) read inside
    get_gradients. The fused steps pass these as jit ARGUMENTS via a
    trace-time attribute swap so they lower as parameters instead of HLO
    constants — the same payload/cache argument as the code buffers.
    Objectives declare them via device_buffer_names(); the per-row
    heuristic remains for duck-typed custom objectives."""
    fn = getattr(objective, "device_buffer_names", None)
    if fn is not None:
        return list(fn())
    n = getattr(objective, "num_data", None)
    if not n:
        return []
    return sorted(
        k for k, v in vars(objective).items()
        if isinstance(v, jax.Array) and v.ndim >= 1 and v.shape[0] == n)


@contextlib.contextmanager
def swapped_attrs(obj, names, values):
    saved = [getattr(obj, k) for k in names]
    for k, v in zip(names, values):
        setattr(obj, k, v)
    try:
        yield
    finally:
        for k, v in zip(names, saved):
            setattr(obj, k, v)


def exact_k_bag_weights(bag_key: jax.Array, n: int, bag_k: int) -> jax.Array:
    """0/1 weight vector with exactly bag_k ones, deterministic per key
    (reference Bagging, gbdt.cpp:210-276)."""
    u = jax.random.uniform(bag_key, (n,))
    cut = jnp.sort(u)[bag_k - 1]
    return (u <= cut).astype(jnp.float32)


def goss_sample(g, h, bag_key, n: int, top_k: int, other_k: int,
                multiply: float):
    """The ONE copy of in-program GOSS sampling (reference
    src/boosting/goss.hpp:60-117), shared by the serial and the
    feature-parallel fused steps: rank-based exact top_k by |g*h|
    (gradient ties cannot change the subset size), exactly other_k of
    the rest uniformly, amplified by `multiply` (goss.hpp:91). Returns
    (g, h, w, bag_idx, oob_idx) — amplified gradients, 0/1 weights, and
    the in-bag / out-of-bag row ids for bag compaction."""
    gmag = jnp.abs(g * h)
    ridx = jnp.argsort(-gmag, stable=True)
    top_idx, rest = ridx[:top_k], ridx[top_k:]
    perm = jnp.argsort(jax.random.uniform(bag_key, (n - top_k,)))
    other_idx = jnp.take(rest, perm[:other_k])
    oob_idx = jnp.take(rest, perm[other_k:])
    bag_idx = jnp.concatenate([top_idx, other_idx])
    amp = jnp.ones((n,), jnp.float32).at[other_idx].set(
        jnp.float32(multiply), unique_indices=True)
    w = jnp.zeros((n,), jnp.float32).at[bag_idx].set(
        1.0, unique_indices=True)
    return g * amp, h * amp, w, bag_idx, oob_idx


def route_rows_by_rec(codes_pack_rows: jax.Array, rec: jax.Array,
                      k: jax.Array, f_numbins, f_missing, f_default,
                      f_col, f_base, f_elide, *, item_bits: int,
                      num_leaves: int, rec_cat=None,
                      f_categorical=None) -> jax.Array:
    """Assign rows to leaves by replaying the (L-1, 13) split records.

    The role of the reference's out-of-bag AddPredictionToScore: rows that
    did not participate in training still need their leaf. Each replayed
    split streams ONE packed code column over the rows (no gathers), so
    the whole pass costs O(rows * splits) sequential-bandwidth work —
    cheap next to growing the tree itself."""
    n_r = codes_pack_rows.shape[0]

    def body(i, leaf):
        r = rec[i]
        do = i < k
        go_left = packed_go_left(
            codes_pack_rows, r[R_FEAT].astype(jnp.int32),
            r[R_THR].astype(jnp.int32), r[R_DLEFT] > 0.5,
            f_numbins, f_missing, f_default, f_col, f_base, f_elide,
            item_bits=item_bits, f_categorical=f_categorical,
            cat_mask=None if rec_cat is None else rec_cat[i])
        at = leaf == r[R_LEAF].astype(jnp.int32)
        return jnp.where(do & at & ~go_left, i + 1, leaf)

    return jax.lax.fori_loop(0, num_leaves - 1, body,
                             jnp.zeros(n_r, jnp.int32))


def leaf_values_from_rec(rec: jax.Array, k: jax.Array, L: int) -> jax.Array:
    """On-device replay of the (L-1, 13) split records into the final (L,)
    leaf-value vector: split i rewrites its leaf with lout and writes rout
    into leaf i+1 (the same ids the host replay assigns)."""
    def body(i, lv):
        do = i < k
        leaf = rec[i, R_LEAF].astype(jnp.int32)
        lv = lv.at[leaf].set(jnp.where(do, rec[i, R_LOUT], lv[leaf]))
        lv = lv.at[i + 1].set(jnp.where(do, rec[i, R_ROUT], lv[i + 1]))
        return lv
    return jax.lax.fori_loop(0, L - 1, body, jnp.zeros((L,), jnp.float32))


def padded_shard_cols(c_cols: int, shards: int, item_bits: int) -> int:
    """Word-aligned per-shard column width for feature-parallel slicing:
    ceil(c_cols / shards) rounded up to a whole packed u32 word. The ONE
    copy used by the learner's packing and the core's slice math."""
    per = 32 // item_bits
    cs = -(-c_cols // shards)
    return -(-cs // per) * per


def padded_device_bins(raw_bins: int) -> int:
    """Pow2-padded on-device bin count (min 16) — the one copy of the
    padding rule used for device_bins, col_device_bins and the pool
    plan. raw_bins <= 256 always pads to <= 256, so u8 storage holds."""
    return 1 << max(4, (int(raw_bins) - 1).bit_length())


def resolve_strategy(config: Config, dataset: Dataset,
                     forced: Optional[str] = None) -> str:
    """Growth-strategy selection shared by __init__ and supports():
    compaction pays off once O(N)-per-split masked passes dominate;
    small data stays on the simpler masked program. 'chunk' is the
    switch-free fixed-chunk formulation (opt-in pending on-chip A/B);
    it requires the dense histogram pool, so LRU-capped configs fall
    back to compact."""
    strat = forced or strategy_env()
    stream = str(getattr(config, "stream_mode", "off") or "off")
    if stream in ("chunked", "goss"):
        # streaming assembles the chunk core's working buffer from host
        # chunks; masked has no chunk seam to hook, and an LRU-capped
        # pool cannot take the per-chunk accumulation. Loud errors
        # beat a silent fallback to a non-streaming core.
        if strat == "masked":
            raise LightGBMError(
                "stream_mode=%s requires the chunk growth core; the "
                "masked strategy has no chunk seam (unset "
                "LGBM_TPU_STRATEGY=masked or turn streaming off)"
                % stream)
        _, pool_slots = plan_histogram_pool(config, dataset)
        if pool_slots > 0:
            raise LightGBMError(
                "stream_mode=%s needs the dense histogram pool but "
                "num_leaves=%d exceeds the histogram_pool_size budget "
                "(LRU pool has no chunk seam); raise "
                "histogram_pool_size or reduce num_leaves"
                % (stream, int(config.num_leaves)))
        return "chunk"
    if strat == "auto":
        # the quantized pipeline rides every strategy: masked (int pool
        # + dequant-hook scans) below the compaction threshold, packed
        # compact/chunk (one-word (qg|qh) rows) above it
        strat = "compact" if dataset.num_data >= 65536 else "masked"
    if strat == "chunk":
        _, pool_slots = plan_histogram_pool(config, dataset)
        if pool_slots > 0:
            # silent here: supports() probes this speculatively; __init__
            # logs the actual fallback once
            strat = "compact"
    return strat


def plan_histogram_pool(config: Config, dataset: Dataset):
    """(slot_bytes, pool_slots): the LRU histogram-pool budget math
    (reference HistogramPool, feature_histogram.hpp:654-831) — the ONE
    copy used by both __init__ and the supports() capability check.
    histogram_pool_size is the reference's knob (MB, < 0 = no explicit
    limit); without it we default to a 1 GiB HBM budget. pool_slots == 0
    means the dense one-slot-per-leaf pool fits."""
    if dataset.columns:
        ncols = max(1, len(dataset.columns))
        raw_bins = max(c.num_bins for c in dataset.columns)
    else:
        ncols = max(1, dataset.num_features)
        raw_bins = int(dataset.max_num_bins)
    slot_bytes = ncols * padded_device_bins(raw_bins) * 12
    if config.histogram_pool_size and config.histogram_pool_size > 0:
        budget = int(config.histogram_pool_size * (1 << 20))
    else:
        budget = 1 << 30
    k_cap = max(8, budget // slot_bytes)
    L = int(config.num_leaves)
    return slot_bytes, (k_cap if L > k_cap else 0)


class DeviceTreeLearner:
    """Drop-in TreeLearner whose Train runs one jitted program per tree."""

    # make_fused_step(goss=...) is implemented (in-program sampling);
    # subclasses without it override to False
    supports_fused_goss = True

    def __init__(self, config: Config, dataset: Dataset,
                 strategy: Optional[str] = None, device_place: bool = True):
        # device_place=False keeps the compact buffers host-side so a
        # sharding subclass can place them itself without a device
        # round-trip (DeviceDataParallelTreeLearner)
        self.config = config
        self.dataset = dataset
        (self.f_numbins, self.f_missing, self.f_default,
         self.f_categorical, self.f_monotone) = dataset.feature_meta_arrays()
        # categorical splits run inside the whole-tree program (scan-level
        # merge); gbdt's fused path checks cat_in_program before masking
        # categorical features out of the feature sample
        self._has_cat = bool(np.any(np.asarray(self.f_categorical)))
        self.cat_in_program = self._has_cat
        self.num_features = dataset.num_features
        self.num_bins = int(dataset.max_num_bins)
        self.device_bins = padded_device_bins(self.num_bins)
        # out-of-core streaming: the binned matrix stays host-side in
        # the packed wire format and chunks onto the device per
        # iteration (io/stream.py); no device-resident codes_t /
        # codes_pack / codes_row copies exist in this mode
        self.stream_mode = str(getattr(config, "stream_mode", "off")
                               or "off")
        stream_on = self.stream_mode != "off"
        bundle = dataset.bundle_arrays()
        if bundle is not None:
            codes, f_col, f_base, f_elide, hist_idx, col_bins = bundle
            self.codes_t = (None if stream_on else
                            jnp.asarray(jnp.swapaxes(codes, 0, 1)))  # (C, N)
            self.f_col, self.f_base, self.f_elide = f_col, f_base, f_elide
            self.col_device_bins = padded_device_bins(int(col_bins))
            # pad hist_idx bin axis to device_bins; pad slots hit the
            # trailing zero entry of the flattened column histogram
            zero_slot = len(dataset.columns) * self.col_device_bins
            hi = np.asarray(hist_idx)
            # re-space flat indices for the padded column bin count
            raw_cb = int(col_bins)
            cols_i = hi // raw_cb
            bins_i = hi % raw_cb
            invalid = hi == (len(dataset.columns) * raw_cb)
            hi2 = np.where(invalid, zero_slot,
                           cols_i * self.col_device_bins + bins_i)
            pad = self.device_bins - hi2.shape[1]
            if pad > 0:
                hi2 = np.concatenate(
                    [hi2, np.full((hi2.shape[0], pad), zero_slot, np.int32)],
                    axis=1)
            self.hist_idx = jnp.asarray(hi2.astype(np.int32))
        else:
            if stream_on or getattr(dataset, "row_shard", None) is not None:
                # streaming holds no resident codes; a row-sharded
                # (dist_shard_mode=rows) dataset has only its local block
                # host-side and always runs the compact/chunk strategy,
                # which reads codes_pack/codes_row — the (F, N) masked-
                # strategy view would need the full matrix
                self.codes_t = None
            else:
                binned = dataset.device_binned()
                self.codes_t = jnp.asarray(
                    jnp.swapaxes(binned, 0, 1))  # (F, N)
            nf = self.num_features
            self.f_col = jnp.arange(nf, dtype=jnp.int32)
            self.f_base = jnp.zeros(nf, jnp.int32)
            self.f_elide = jnp.zeros(nf, jnp.int32)
            self.col_device_bins = self.device_bins
            zero_slot = nf * self.device_bins
            hi = (np.arange(nf, dtype=np.int64)[:, None] * self.device_bins
                  + np.arange(self.device_bins)[None, :])
            nb = np.asarray(self.f_numbins)[:, None]
            hi = np.where(np.arange(self.device_bins)[None, :] < nb,
                          hi, zero_slot)
            self.hist_idx = jnp.asarray(hi.astype(np.int32))
        contri = config.feature_contri or []
        pen = np.array([contri[fr] if fr < len(contri) else 1.0
                        for fr in dataset.used_features], dtype=np.float32)
        self.f_penalty = jnp.asarray(pen)
        # Measured on v5e (tools/microbench_injit.py): the XLA one-hot
        # contraction beats the Pallas kernel ~2.4x (XLA fuses the one-hot
        # build into the matmul pipeline better than Mosaic schedules it),
        # so the fused XLA path is the default even on TPU.
        self._use_pallas = use_pallas_env() and jax.default_backend() == "tpu"
        # quantized-gradient training: >0 switches every growth strategy
        # to exact int32 histograms (jit caches key on this static);
        # quant_renew enables the packed cores' leaf-wise re-quantization
        self.quant_bits = config.quant_bits
        self.quant_renew = bool(getattr(config, "quant_renew", True))
        self.hist_chunk = int(config.hist_chunk_size or 0)
        requested = strategy or strategy_env()
        self.strategy = resolve_strategy(config, dataset, strategy)
        # partition formulation: sort | scan | pallas (explicit
        # LGBM_TPU_PARTITION wins on any backend; pallas runs interpret
        # mode off-TPU so CI covers the integrated path). Measured
        # default (round-5 battery, 1M x 28 x 255 on v5e): scan beats
        # sort 1.296M vs 0.79M row-trees/s on the compact strategy —
        # the argsort's O(W log W) passes dominate — but LOSES on chunk
        # (574k vs 982k: fixed 64k chunks keep the sort short while the
        # scan pays its scatter on every chunk), so the flip is scoped
        # to TPU + compact.
        self._partition_mode = partition_mode_env(
            default="scan" if (jax.default_backend() == "tpu"
                               and self.strategy == "compact") else "sort")
        if requested == "chunk" and self.strategy != "chunk":
            log.warning("chunk strategy needs the dense histogram pool; "
                        "using compact (LRU-capped) instead")
        if (self.strategy == "masked" and dataset.num_data >= 262144
                and int(config.num_leaves) >= 127):
            # the masked program's compile blew past 19 minutes at
            # 1M x 255 on the tunneled TPU (round-3 battery log); auto
            # never picks it at this scale, so this is an explicit opt-in
            log.warning(
                "masked strategy at %d rows x %d leaves compiles very "
                "slowly; compact or chunk is strongly recommended",
                dataset.num_data, int(config.num_leaves))
        # default 2 measured fastest on-chip (754k vs 679k row-trees/s at
        # step 4, 1M x 255 leaves — docs/DESIGN.md 6a-r3): the tighter
        # ladder's lower window inflation beats its extra compile time
        self.window_step = max(2, int(_env("LGBM_TPU_WINDOW_STEP", "2")))
        self.chunk_rows = max(8192, int(_env("LGBM_TPU_CHUNK", "65536")))
        # LRU-capped histogram pool: when the dense (L,C,B,3) pool would
        # exceed the budget, the compact strategy runs with K LRU slots
        # and rebuilds sibling histograms on miss
        _, self.pool_slots = plan_histogram_pool(config, dataset)
        self._shard: Optional[DeviceDataShard] = None
        if self.strategy in ("compact", "chunk"):
            host_codes = (dataset.bundled if dataset.bundled is not None
                          else dataset.binned)
            host_codes = np.asarray(host_codes)
            # bit-pack column codes into u32 words for the physically
            # reordered working buffer (8 4-bit, 4 u8, or 2 u16 codes per
            # word). The 4-bit form is the reference's Dense4bitsBin
            # (src/io/dense_nbits_bin.hpp) — usable whenever every
            # column's codes fit a nibble (max_bin <= 16), halving HBM
            # traffic per partition pass.
            # decide from DECLARED per-column bin counts, not the data:
            # a data-dependent choice would let rank-partitioned shards
            # disagree on the packed layout (divergent traced programs)
            if dataset.columns:
                declared_bins = max(c.num_bins for c in dataset.columns)
            else:
                declared_bins = int(dataset.max_num_bins)
            if host_codes.dtype.itemsize == 2:
                self.item_bits = 16
            elif declared_bins <= 16:
                self.item_bits = 4
            else:
                self.item_bits = 8
            self.c_cols = host_codes.shape[1]
            # LGBM_TPU_PACK_WORDS pads the packed code section to a fixed
            # u32-word width: row gathers on TPU are latency-bound per
            # row, so wider rows may reach DMA bandwidth (A/B lever for
            # the partition cost; costs memory proportionally)
            pack_words = int(_env("LGBM_TPU_PACK_WORDS", "0"))
            col_target = (pack_words * (32 // self.item_bits)
                          if pack_words > 0 else None)
            if col_target is not None and col_target < host_codes.shape[1]:
                log.warning(
                    "LGBM_TPU_PACK_WORDS=%d is below the natural packed "
                    "width (%d cols); padding lever inactive",
                    pack_words, host_codes.shape[1])
            packed = self.pack_codes(host_codes, col_target=col_target)
            if stream_on:
                # host wire store + double-buffered H2D chunk pipeline;
                # the device never holds a full copy of the binned rows
                self.codes_row = None
                self.codes_pack = None
                self._shard = DeviceDataShard(
                    packed, item_bits=self.item_bits,
                    c_cols=self.c_cols,
                    chunk_rows=int(getattr(
                        config, "stream_chunk_rows", 0) or 0),
                    core_chunk_rows=self.chunk_rows)
            elif device_place:
                self.codes_row = jnp.asarray(host_codes)      # (N, C)
                self.codes_pack = jnp.asarray(packed)
            else:
                self.codes_row = host_codes
                self.codes_pack = packed
        else:
            self.codes_row = None
            self.codes_pack = None
            self.item_bits = 8
            self.c_cols = int(self.codes_t.shape[0])
        self._ones_w = None
        self.last_leaf_id: Optional[jax.Array] = None
        self._leaf_id_host: Optional[np.ndarray] = None
        self._bag_mask_host: Optional[np.ndarray] = None
        # streaming per-iteration context (assembled data0 + subset ids)
        # and the GOSS working-set hint handed down by the booster
        self._stream_ctx: Optional[dict] = None
        self._stream_top_hint: Optional[np.ndarray] = None
        self._stream_jits: dict = {}
        # vmap-batched multiclass growth (train_batched): jitted
        # class-batched grow programs keyed by K, and the per-class leaf
        # routing of the last batched iteration
        self._batched_fns: dict = {}
        self._batched_leaf_ids: Optional[jax.Array] = None

    def pack_codes(self, host_codes: np.ndarray,
                   col_target: Optional[int] = None) -> np.ndarray:
        """Bit-pack (N, C) column codes into u32 words for the compact
        working buffer. col_target pads the column capacity (the
        feature-parallel learner needs word-aligned per-shard slices)."""
        nrow, ncol = host_codes.shape
        want = max(ncol, col_target or 0)
        if self.item_bits == 4:
            npairs = ((want + 7) // 8) * 4          # byte pairs per row
            byte_arr = np.zeros((nrow, npairs * 2), dtype=np.uint8)
            byte_arr[:, :ncol] = host_codes
            packed_bytes = (byte_arr[:, 0::2]
                            | (byte_arr[:, 1::2] << 4)).astype(np.uint8)
            return np.ascontiguousarray(packed_bytes).view(np.uint32)
        per = 32 // self.item_bits
        padded = np.zeros((nrow, ((want + per - 1) // per) * per),
                          dtype=np.uint8 if self.item_bits == 8
                          else np.uint16)
        padded[:, :ncol] = host_codes
        return np.ascontiguousarray(padded).view(np.uint32)

    # ------------------------------------------------------------------
    @staticmethod
    def supports(config: Config, dataset: Dataset,
                 strategy: Optional[str] = None,
                 categorical_ok: bool = True) -> bool:
        """Static capability check; unsupported configs use the host-loop
        learner (create_tree_learner falls back). categorical_ok=False
        lets a caller opt out of device categorical handling (no in-tree
        caller does since round 3 wired categoricals into every sharded
        mode; kept for API stability)."""
        if not categorical_ok and any(
                dataset.bin_mappers[fr].bin_type == BIN_CATEGORICAL
                for fr in dataset.used_features):
            return False
        if config.forcedsplits_filename:
            return False
        if config.cegb_tradeoff > 0 and (
                config.cegb_penalty_split > 0
                or bool(config.cegb_penalty_feature_coupled)
                or bool(config.cegb_penalty_feature_lazy)):
            return False
        # pool footprint via the same plan __init__ uses: the compact
        # strategy caps at K LRU slots, only the masked strategy's dense
        # (L, C, B, 3) pool can blow up. `strategy` lets callers that
        # force a strategy (DeviceDataParallelTreeLearner forces compact)
        # check the learner they will actually build.
        slot_bytes, pool_slots = plan_histogram_pool(config, dataset)
        strat = resolve_strategy(config, dataset, strategy)
        if strat == "compact" and pool_slots > 0:
            slots = pool_slots
        else:
            slots = int(config.num_leaves)
        if slots * slot_bytes > _POOL_BYTE_LIMIT:
            return False
        return True

    def _statics(self):
        cfg = self.config
        bynode_k = 0
        if 0.0 < cfg.feature_fraction_bynode < 1.0:
            bynode_k = max(1, int(self.num_features * cfg.feature_fraction_bynode))
        # a hashable tuple (jit static): (cat_l2, cat_smooth,
        # max_cat_threshold, max_cat_to_onehot, min_data_per_group)
        cat_statics = None
        if self._has_cat:
            cat_statics = (float(cfg.cat_l2), float(cfg.cat_smooth),
                           int(cfg.max_cat_threshold),
                           int(cfg.max_cat_to_onehot),
                           int(cfg.min_data_per_group))
        return dict(
            cat_statics=cat_statics,
            num_leaves=int(cfg.num_leaves), num_bins=self.device_bins,
            col_bins=self.col_device_bins,
            max_depth=int(cfg.max_depth), l1=float(cfg.lambda_l1),
            l2=float(cfg.lambda_l2),
            max_delta_step=float(cfg.max_delta_step),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            min_sum_hessian=float(cfg.min_sum_hessian_in_leaf),
            min_gain_to_split=float(cfg.min_gain_to_split),
            bynode_k=bynode_k, use_pallas=self._use_pallas,
            grow_program=str(getattr(cfg, "grow_program", "per_split")))

    def _feature_mask(self, rng: np.random.RandomState) -> np.ndarray:
        frac = self.config.feature_fraction
        mask = np.ones(self.num_features, dtype=bool)
        if 0.0 < frac < 1.0:
            k = max(1, int(self.num_features * frac))
            chosen = rng.choice(self.num_features, k, replace=False)
            mask[:] = False
            mask[chosen] = True
        return mask

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              bag_indices: Optional[np.ndarray] = None,
              iter_seed: int = 0) -> Tree:
        cfg = self.config
        ds = self.dataset
        n = ds.num_data
        if bag_indices is None:
            if self._ones_w is None:
                self._ones_w = jnp.ones(n, jnp.float32)
            w = self._ones_w
            self._bag_mask_host = None
        else:
            wv = np.zeros(n, dtype=np.float32)
            wv[bag_indices] = 1.0
            w = jnp.asarray(wv)
            self._bag_mask_host = wv > 0
        rng = np.random.RandomState(
            (cfg.feature_fraction_seed + iter_seed) % (2**31 - 1))
        base_mask = jnp.asarray(self._feature_mask(rng))
        key = jax.random.PRNGKey(iter_seed)

        if self._shard is not None:
            # assemble the streamed working buffer BEFORE grow_dispatch:
            # the shard attributes its blocking residue to the
            # stream_wait recorder phase, and phases must not nest
            self._stream_ctx = self._stream_assemble(
                grad, hess, w, key, bag_indices)

        with telem.phase("grow_dispatch"):
            rec, rec_cat, leaf_id, n_splits, _ = self._run_grow(
                grad, hess, w, base_mask, key)
        telemetry.note_grow_dispatches(1.0, trees=1.0)

        self.last_leaf_id = leaf_id
        self._leaf_id_host = None
        with telem.phase("host_sync"):
            if rec_cat is None:
                rec_h, k = jax.device_get((rec, n_splits))
                rec_cat_h = None
            else:
                rec_h, rec_cat_h, k = jax.device_get(
                    (rec, rec_cat, n_splits))
        k = int(k)
        if k == 0:
            log.warning("No further splits with positive gain")
        with telem.phase("tree_replay"):
            return self.replay_tree(rec_h, k, rec_cat_h)

    # -- vmap-batched multiclass growth --------------------------------
    def supports_batched_k(self) -> bool:
        """Whether train_batched can grow all K per-class trees of one
        boosting iteration as ONE batched device program. Requires the
        fused-tree growth program (the fixed-trip scan is what makes the
        whole-tree program vmappable — a data-dependent while_loop has
        no batch rule), the masked strategy (one shared dense code
        buffer; the packed strategies' LRU pool ladder is per-tree
        state), and resident data."""
        return (type(self) is DeviceTreeLearner
                and self.strategy == "masked"
                and self._shard is None
                and str(getattr(self.config, "grow_program",
                                "per_split")) == "fused_tree")

    def _batched_grow_fn(self, num_class: int):
        """jit(vmap(grow_tree)) over the class axis, cached per K. The
        code buffer and row weights are shared (in_axes=None); per-class
        gradients, hessians, feature masks, and RNG keys are batched —
        so per-class quant scales (derived in-program from grad/hess and
        the key) ride as batched operands automatically."""
        fn = self._batched_fns.get(num_class)
        if fn is not None:
            return fn
        statics = self._statics()
        meta = (self.f_numbins, self.f_missing, self.f_default,
                self.f_monotone, self.f_penalty, self.f_categorical,
                self.f_col, self.f_base, self.f_elide, self.hist_idx)
        quant_bits, hist_chunk = self.quant_bits, self.hist_chunk

        def one(codes_t, g, h, w, base_mask, key):
            return grow_tree(codes_t, g, h, w, base_mask, *meta, key,
                             quant_bits=quant_bits, hist_chunk=hist_chunk,
                             **statics)

        fn = jax.jit(jax.vmap(one, in_axes=(None, 0, 0, None, 0, 0)))
        self._batched_fns[num_class] = fn
        return fn

    def train_batched(self, grad: jax.Array, hess: jax.Array,
                      bag_indices: Optional[np.ndarray] = None,
                      iter_seed0: int = 0) -> List[Tree]:
        """Grow the K per-class trees of one boosting iteration as ONE
        batched device dispatch (large-K multiclass: K trees/iteration
        used to cost K grow dispatches + K host syncs).

        Seeds match train() exactly: class k uses
        iter_seed = iter_seed0 + k for both the feature-fraction
        RandomState and the PRNGKey, so the batched program is
        bit-identical to the per-class loop. Per-class leaf routing
        lands in self._batched_leaf_ids; the caller installs row k as
        last_leaf_id before each per-class score update."""
        cfg = self.config
        n = self.dataset.num_data
        K = int(grad.shape[0])
        if bag_indices is None:
            if self._ones_w is None:
                self._ones_w = jnp.ones(n, jnp.float32)
            w = self._ones_w
            self._bag_mask_host = None
        else:
            wv = np.zeros(n, dtype=np.float32)
            wv[bag_indices] = 1.0
            w = jnp.asarray(wv)
            self._bag_mask_host = wv > 0
        masks = np.stack([
            self._feature_mask(np.random.RandomState(
                (cfg.feature_fraction_seed + iter_seed0 + k) % (2**31 - 1)))
            for k in range(K)])
        base_masks = jnp.asarray(masks)
        keys = jnp.stack([jax.random.PRNGKey(iter_seed0 + k)
                          for k in range(K)])
        fn = self._batched_grow_fn(K)
        with telem.phase("grow_fused"):
            rec, rec_cat, leaf_ids, n_splits, _ = fn(
                self.codes_t, grad, hess, w, base_masks, keys)
        telemetry.note_grow_dispatches(1.0, trees=float(K))
        self._batched_leaf_ids = leaf_ids
        self.last_leaf_id = None
        self._leaf_id_host = None
        with telem.phase("host_sync"):
            if rec_cat is None:
                rec_h, ks = jax.device_get((rec, n_splits))
                rec_cat_h = None
            else:
                rec_h, rec_cat_h, ks = jax.device_get(
                    (rec, rec_cat, n_splits))
        trees = []
        with telem.phase("tree_replay"):
            for k in range(K):
                kk = int(ks[k])
                if kk == 0:
                    log.warning("No further splits with positive gain")
                trees.append(self.replay_tree(
                    rec_h[k], kk,
                    None if rec_cat_h is None else rec_cat_h[k]))
        return trees

    def _grow_fn_kwargs(self, trivial_weights: bool = False):
        """(grow fn, strategy-specific kwargs) for the packed strategies.
        trivial_weights asserts the weight vector reaching the grower is
        all-ones; only the compact strategy consumes it (it drops the
        masked full-window histogram fallback), and only below 2**24
        rows where the float32 record counts that pick the smaller side
        are exact integers."""
        trivial = trivial_weights and self.dataset.num_data < (1 << 24)
        if self.strategy == "chunk":
            return grow_tree_chunk, dict(
                c_cols=self.c_cols, item_bits=self.item_bits,
                chunk_rows=self.chunk_rows,
                fuse_hist=not flag("LGBM_TPU_CHUNK_NO_FUSE_HIST"),
                partition=self._partition_mode,
                trivial_weights=trivial,
                quant_bits=self.quant_bits, quant_renew=self.quant_renew)
        return grow_tree_compact, dict(
            c_cols=self.c_cols, item_bits=self.item_bits,
            pool_slots=self.pool_slots, window_step=self.window_step,
            trivial_weights=trivial,
            partition=self._partition_mode,
            quant_bits=self.quant_bits, quant_renew=self.quant_renew)

    def _run_grow(self, grad, hess, w, base_mask, key):
        """The grow-program invocation; sharded subclasses override this
        single hook and inherit the rest of train()."""
        if self._stream_ctx is not None:
            return self._run_grow_streamed(base_mask, key)
        if self.strategy in ("compact", "chunk"):
            grow, kw = self._grow_fn_kwargs(
                trivial_weights=w is self._ones_w)
            return grow(
                self.codes_pack, self.codes_row, grad, hess, w, base_mask,
                self.f_numbins, self.f_missing, self.f_default,
                self.f_monotone, self.f_penalty, self.f_categorical,
                self.f_col, self.f_base,
                self.f_elide, self.hist_idx, key, **kw, **self._statics())
        return grow_tree(
            self.codes_t, grad, hess, w, base_mask,
            self.f_numbins, self.f_missing, self.f_default,
            self.f_monotone, self.f_penalty, self.f_categorical,
            self.f_col, self.f_base,
            self.f_elide, self.hist_idx, key,
            quant_bits=self.quant_bits, hist_chunk=self.hist_chunk,
            **self._statics())

    # -- out-of-core streaming (io/stream.py) --------------------------
    def _stream_init_fn(self, rows_n: int, trivial: bool):
        """jit that builds the (rows_n + CH, d_cols) u32 working buffer
        with the gh words + row-id columns filled and the code section
        zeroed (chunk writes fill it). The quantized path runs
        _quant_prepare with the SAME rng_key the growth core re-derives
        its scales from, so the core stays the one source of key/scale
        derivation and the assembled gh words match it bit-for-bit."""
        jkey = ("init", rows_n, trivial)
        fn = self._stream_jits.get(jkey)
        if fn is None:
            quant = self.quant_bits > 0
            gw = (1 if trivial else 2) if quant else 3
            cw = int(self._shard.code_words)
            CH = int(self.chunk_rows)
            d_cols = cw + gw + 1
            qb, qr = self.quant_bits, self.quant_renew

            def init(grad, hess, w, rng_key):
                if quant:
                    _, gh_packed, _, _, _ = _quant_prepare(
                        grad, hess, w, rng_key, quant_bits=qb,
                        quant_renew=qr, n_total=rows_n, axis_name=None)
                    gh_u = _quant_gh_words(gh_packed, w, gw)
                else:
                    gh_u = jax.lax.bitcast_convert_type(
                        jnp.stack([grad * w, hess * w, w], axis=1),
                        jnp.uint32)
                ids = jnp.arange(rows_n, dtype=jnp.uint32)[:, None]
                tail = jnp.concatenate([gh_u, ids], axis=1)
                buf = jnp.zeros((rows_n + CH, d_cols), jnp.uint32)
                return jax.lax.dynamic_update_slice(
                    buf, tail, (jnp.int32(0), jnp.int32(cw)))

            fn = jax.jit(init)
            self._stream_jits[jkey] = fn
        return fn

    def _stream_write(self, data0, chunk, start: int):
        """Donated contiguous chunk write: data0[start:start+rows, :CW]
        = chunk. Chunks are exact-sized (the tail chunk keeps its
        natural shape), so the write never clamps."""
        jkey = ("write", int(chunk.shape[0]),
                tuple(int(d) for d in data0.shape))
        fn = self._stream_jits.get(jkey)
        if fn is None:
            fn = jax.jit(
                lambda buf, ck, s: jax.lax.dynamic_update_slice(
                    buf, ck, (s, jnp.int32(0))),
                donate_argnums=(0,))
            self._stream_jits[jkey] = fn
        return fn(data0, chunk, jnp.int32(start))

    def _stream_scatter(self, data0, rows, pos):
        """Donated scatter write of packed code rows into subset-local
        positions (GOSS working-set hits and streamed misses)."""
        jkey = ("scatter", int(rows.shape[0]),
                tuple(int(d) for d in data0.shape))
        fn = self._stream_jits.get(jkey)
        if fn is None:
            fn = jax.jit(
                lambda buf, r, p: buf.at[p, :r.shape[1]].set(
                    r, unique_indices=True),
                donate_argnums=(0,))
            self._stream_jits[jkey] = fn
        return fn(data0, rows, pos)

    def _stream_assemble(self, grad, hess, w, key, bag_indices):
        """Build the chunk core's pre-assembled data0 on device.

        stream_mode=chunked (or a GOSS warmup iteration): every wire row
        streams through the double buffer into its own slot — pure data
        movement, so the grown tree is bit-identical to resident
        training for any stream_chunk_rows. stream_mode=goss with a
        sampled bag: the bag compacts to a subset buffer; pinned
        working-set rows are gathered on device (no H2D), the rest
        stream, and the next iteration's top-gradient rows are re-pinned
        from the assembled buffer before it is consumed."""
        shard = self._shard
        n = self.dataset.num_data
        if self.stream_mode == "goss" and bag_indices is not None:
            idx = np.sort(np.asarray(
                jax.device_get(bag_indices)).astype(np.int64))
            jidx = jnp.asarray(idx)
            g = jnp.take(grad, jidx)
            h = jnp.take(hess, jidx)
            wv = jnp.ones(idx.size, jnp.float32)
            # the compacted bag is all-ones by construction; mirror the
            # _grow_fn_kwargs exactness bound so assembly and core agree
            # on the static gh-word layout
            trivial = n < (1 << 24)
        else:
            idx = None
            g, h, wv = grad, hess, w
            trivial = (w is self._ones_w) and n < (1 << 24)
        rows_n = n if idx is None else int(idx.size)
        data0 = self._stream_init_fn(rows_n, trivial)(g, h, wv, key)
        shard.track_buffer("data0", int(data0.nbytes))
        if idx is None:
            for s, cnt, dev in shard.iter_chunks():
                data0 = self._stream_write(data0, dev, s)
        else:
            ws_ids, ws_rows = shard.working_set()
            if ws_ids.size:
                hit = np.isin(idx, ws_ids.astype(np.int64),
                              assume_unique=True)
                hit_pos = np.nonzero(hit)[0].astype(np.int32)
                miss_pos = np.nonzero(~hit)[0].astype(np.int32)
                if hit_pos.size:
                    cache_pos = np.searchsorted(
                        ws_ids, idx[hit_pos]).astype(np.int32)
                    rows = jnp.take(ws_rows, jnp.asarray(cache_pos),
                                    axis=0)
                    data0 = self._stream_scatter(
                        data0, rows, jnp.asarray(hit_pos))
            else:
                miss_pos = np.arange(idx.size, dtype=np.int32)
            if miss_pos.size:
                for s, cnt, dev in shard.iter_chunks(
                        row_ids=idx[miss_pos]):
                    data0 = self._stream_scatter(
                        data0, dev, jnp.asarray(miss_pos[s:s + cnt]))
            self._stream_refresh_ws(data0, idx)
        return {"data0": data0, "idx": idx, "g": g, "h": h, "w": wv,
                "trivial": trivial}

    def _stream_refresh_ws(self, data0, idx) -> None:
        """Re-pin the booster's top-gradient hint as the next working
        set, gathering packed code rows straight out of the assembled
        buffer (zero extra H2D — the rows are already on device)."""
        top = self._stream_top_hint
        self._stream_top_hint = None
        if top is None or not top.size:
            return
        top = np.sort(np.asarray(top).astype(np.int64))
        top = top[np.isin(top, idx, assume_unique=True)]
        if not top.size:
            return
        pos = np.searchsorted(idx, top).astype(np.int32)
        cw = int(self._shard.code_words)
        jkey = ("wsgather", int(pos.size),
                tuple(int(d) for d in data0.shape))
        fn = self._stream_jits.get(jkey)
        if fn is None:
            fn = jax.jit(lambda buf, p: buf[p, :cw])
            self._stream_jits[jkey] = fn
        self._shard.pin_working_set(top.astype(np.int32),
                                    fn(data0, jnp.asarray(pos)))

    def _run_grow_streamed(self, base_mask, key):
        """Grow from the pre-assembled streamed buffer: the chunk core
        runs with data_prebuilt=True (codes_pack arg IS data0, codes_row
        a dummy) and is otherwise the identical program — root histogram
        grouping aside, which the chunk-wise accumulation keeps exact
        for both the int32 and the exact-arithmetic float cases."""
        ctx = self._stream_ctx
        self._stream_ctx = None
        grow, kw = self._grow_fn_kwargs(trivial_weights=ctx["trivial"])
        kw["data_prebuilt"] = True
        dummy_row = jnp.zeros((1, 1), jnp.uint8)
        rec, rec_cat, leaf_id, n_splits, totals = grow(
            ctx["data0"], dummy_row, ctx["g"], ctx["h"], ctx["w"],
            base_mask, self.f_numbins, self.f_missing, self.f_default,
            self.f_monotone, self.f_penalty, self.f_categorical,
            self.f_col, self.f_base, self.f_elide, self.hist_idx, key,
            **kw, **self._statics())
        if ctx["idx"] is not None:
            leaf_id = self._stream_full_leaf_id(
                ctx["idx"], leaf_id, rec, rec_cat, n_splits)
        self._shard.release_buffer("data0")
        return rec, rec_cat, leaf_id, n_splits, totals

    def _stream_full_leaf_id(self, idx, leaf_sub, rec, rec_cat, k):
        """Full-row leaf assignment for the compacted GOSS bag: in-bag
        rows take the core's ids; out-of-bag rows replay the split
        records chunk-by-chunk from the wire store (the streamed
        counterpart of the reference's out-of-bag
        AddPredictionToScore)."""
        n = self.dataset.num_data
        full = jnp.zeros(n, jnp.int32).at[jnp.asarray(idx)].set(
            leaf_sub, unique_indices=True)
        mask = np.ones(n, dtype=bool)
        mask[idx] = False
        oob = np.nonzero(mask)[0]
        if not oob.size:
            return full
        # emit_phase=False: this routing runs inside grow_dispatch and
        # recorder phases must not nest (bytes are still counted)
        for s, cnt, dev in self._shard.iter_chunks(
                row_ids=oob, emit_phase=False):
            lc = self._stream_route(dev, rec, rec_cat, k)
            full = full.at[jnp.asarray(
                oob[s:s + cnt].astype(np.int64))].set(
                    lc, unique_indices=True)
        return full

    def _stream_route(self, rows, rec, rec_cat, k):
        jkey = ("route", int(rows.shape[0]))
        fn = self._stream_jits.get(jkey)
        if fn is None:
            item_bits = self.item_bits
            L = int(self.config.num_leaves)
            f_meta = (self.f_numbins, self.f_missing, self.f_default,
                      self.f_col, self.f_base, self.f_elide)
            f_cat = self.f_categorical if self._has_cat else None

            def route(rows, rec, rec_cat, kk):
                return route_rows_by_rec(
                    rows, rec, kk, *f_meta, item_bits=item_bits,
                    num_leaves=L, rec_cat=rec_cat,
                    f_categorical=f_cat)

            fn = jax.jit(route)
            self._stream_jits[jkey] = fn
        return fn(rows, rec, rec_cat, k)

    def stream_note_top(self, top_ids) -> None:
        """Booster hook (GOSS sampling): the row ids whose |g*h| ranks
        highest this iteration — the working set to pin for the next.
        No-op unless this learner streams."""
        if self._shard is None:
            return
        self._stream_top_hint = np.asarray(
            jax.device_get(top_ids)).astype(np.int64)

    def stream_state(self):
        """Checkpointable streaming state (None when not streaming)."""
        if self._shard is None:
            return None
        return self._shard.stream_state()

    def load_stream_state(self, st) -> None:
        if self._shard is not None and st:
            self._shard.load_stream_state(st)

    def device_data_bytes(self) -> dict:
        """Model-tracked device bytes of the row data this learner holds
        — the streamed-vs-resident A/B quantity. In-program temporaries
        common to both modes (scratch, position arrays, the histogram
        pool) are excluded. Resident counts the live input buffers plus
        the in-program data0 copy that coexists with them during
        growth; streamed reports the shard high-water mark (data0 +
        in-flight chunks + pinned working set)."""
        if self._shard is not None:
            return {"mode": "streamed",
                    "bytes": int(max(self._shard.peak_bytes,
                                     self._shard.live_bytes()))}
        total = 0
        for a in (self.codes_t, self.codes_pack, self.codes_row):
            if a is not None and hasattr(a, "nbytes"):
                total += int(a.nbytes)
        if self.strategy == "chunk" and self.codes_pack is not None:
            quant = self.quant_bits > 0
            gw = 1 if quant else 3  # trivial-weight (unbagged) layout
            cw = int(self.codes_pack.shape[1])
            total += ((self.dataset.num_data + self.chunk_rows)
                      * (cw + gw + 1) * 4)
        return {"mode": "resident", "bytes": int(total)}

    def replay_tree(self, rec_h, k: int, rec_cat_h=None) -> Tree:
        """Materialize a host Tree from the fetched (L-1, 13) split-record
        array (the one device->host transfer per tree). rec_cat_h carries
        the categorical winners' (L-1, B) left-bin masks; a split whose
        feature is categorical replays as a bitset node."""
        from .serial_learner import _make_bitset
        ds = self.dataset
        rec_h = np.asarray(rec_h)
        tree = Tree(self.config.num_leaves)
        for i in range(k):
            r = rec_h[i]
            inner_f = int(r[R_FEAT])
            real_f = ds.inner_to_real(inner_f)
            mapper = ds.bin_mappers[real_f]
            if mapper.bin_type == BIN_CATEGORICAL and rec_cat_h is not None:
                bins = [int(bb) for bb in
                        np.nonzero(np.asarray(rec_cat_h[i]) > 0.5)[0]]
                inner_bits = _make_bitset(bins)
                cats = [mapper.bin_2_categorical[b] for b in bins
                        if b < len(mapper.bin_2_categorical)]
                real_bits = _make_bitset(cats)
                tree.split_categorical(
                    int(r[R_LEAF]), inner_f, real_f,
                    [int(wd) for wd in inner_bits],
                    [int(wd) for wd in real_bits],
                    float(r[R_LOUT]), float(r[R_ROUT]),
                    int(round(float(r[R_LCNT]))),
                    int(round(float(r[R_RCNT]))),
                    float(r[R_LSH]), float(r[R_RSH]),
                    float(r[R_GAIN]), mapper.missing_type)
                continue
            thr_bin = int(r[R_THR])
            tree.split(
                int(r[R_LEAF]), inner_f, real_f, thr_bin,
                ds.real_threshold(inner_f, thr_bin),
                float(r[R_LOUT]), float(r[R_ROUT]),
                int(round(float(r[R_LCNT]))),
                int(round(float(r[R_RCNT]))),
                float(r[R_LSH]), float(r[R_RSH]),
                float(r[R_GAIN]), mapper.missing_type,
                bool(r[R_DLEFT] > 0.5))
        return tree

    # ------------------------------------------------------------------
    def make_fused_step(self, objective, goss=None, bagging=True):
        """One boosting iteration as a single device program: gradients ->
        bag/GOSS sampling -> whole-tree growth -> on-device leaf-value
        replay -> score update. Through a tunneled TPU every extra
        dispatch costs ~10ms and every H2D ~130ms/4MB, so the fused step
        leaves exactly one small D2H fetch (the split records) per
        iteration.

        goss = (top_k, other_k, multiply): gradient-based one-side
        sampling on device (reference src/boosting/goss.hpp) — keep the
        top_k rows by |g*h|, sample other_k of the rest uniformly and
        amplify their gradients by `multiply`; the tree then trains on
        the compacted (top_k + other_k)-row subset.

        Returns step(score_row, base_mask, tree_key, bag_key, shrinkage)
        -> (new_score_row, rec, rec_cat, leaf_id, num_splits, finite) —
        `finite` is the in-program on_nonfinite sentry reduction over the
        updated score row, so guarded runs cost no extra dispatch.
        """
        statics = self._statics()
        n = self.dataset.num_data
        cfg = self.config
        use_compact = self.strategy in ("compact", "chunk")
        meta = (self.f_numbins, self.f_missing, self.f_default,
                self.f_monotone, self.f_penalty, self.f_categorical,
                self.f_col, self.f_base,
                self.f_elide, self.hist_idx)
        if goss is not None:
            top_k, other_k, multiply = goss
            bag_on = True
            bag_k = min(n, top_k + other_k)
        elif not bagging:
            # GOSS warmup: train on ALL rows even if bagging params are
            # set (reference GOSS replaces bagging outright)
            bag_on = False
            bag_k = n
        else:
            bag_on = cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0
            bag_k = max(1, int(n * cfg.bagging_fraction))
        L = statics["num_leaves"]
        # bag compaction (reference subset-copy bagging, gbdt.cpp:727-792):
        # physically gather the bag once per iteration so every per-split
        # window scales with the bag, not N; out-of-bag rows get their
        # leaf from a rec-replay routing pass
        bag_compact = (use_compact and bag_on and bag_k < n
                       and not flag("LGBM_TPU_NO_BAG_COMPACT"))
        if use_compact:
            # bag-compacted and full-data fused paths hand the grower an
            # all-ones weight vector; GOSS/bagging without compaction
            # carries 0/1 weights and keeps the masked fallback
            grow, grow_kw = self._grow_fn_kwargs(
                trivial_weights=bag_compact
                or (goss is None and not bag_on))
        else:
            grow, grow_kw = grow_tree, dict(quant_bits=self.quant_bits,
                                            hist_chunk=self.hist_chunk)

        obj_keys = objective_buffer_names(objective)

        @jax.jit
        def step_impl(codes_pack, codes_row, obj_bufs, score_row,
                      base_mask, tree_key, bag_key, shrinkage):
            # the code buffers (and the objective's device buffers) are
            # explicit ARGUMENTS, not closure captures: closed-over
            # device arrays lower as HLO constants, which baked the
            # whole dataset into the program — 120.5 MB of StableHLO at
            # 1M x 28 x 255 (codes ~112 MB + objective vectors ~8 MB)
            # vs 0.24 MB with everything as args — bloating the
            # remote-compile payload and keying the persistent compile
            # cache on the dataset bytes instead of just shapes. Masked
            # strategy passes (codes_t, codes_t).
            # tests/test_program_size.py pins the property.
            with swapped_attrs(objective, obj_keys, obj_bufs):
                g, h = objective.get_gradients(score_row)
            bag_idx = oob_idx = None
            if goss is not None:
                g, h, w, bag_idx, oob_idx = goss_sample(
                    g, h, bag_key, n, top_k, other_k, multiply)
            elif bag_on:
                w = exact_k_bag_weights(bag_key, n, bag_k)
                inbag = w > 0
            else:
                w = jnp.ones((n,), jnp.float32)
            if bag_compact:
                if bag_idx is None:
                    order = jnp.argsort(
                        jnp.where(inbag, 0, 1).astype(jnp.int8),
                        stable=True)
                    bag_idx, oob_idx = order[:bag_k], order[bag_k:]
                rec, rec_cat, leaf_b, k, _ = grow(
                    jnp.take(codes_pack, bag_idx, axis=0),
                    jnp.take(codes_row, bag_idx, axis=0),
                    jnp.take(g, bag_idx), jnp.take(h, bag_idx),
                    jnp.ones((bag_k,), jnp.float32), base_mask,
                    *meta, tree_key, **grow_kw, **statics)
                leaf_o = route_rows_by_rec(
                    jnp.take(codes_pack, oob_idx, axis=0), rec, k,
                    self.f_numbins, self.f_missing, self.f_default,
                    self.f_col, self.f_base, self.f_elide,
                    item_bits=self.item_bits, num_leaves=L,
                    rec_cat=rec_cat, f_categorical=self.f_categorical)
                leaf_id = jnp.zeros(n, jnp.int32) \
                    .at[bag_idx].set(leaf_b, unique_indices=True) \
                    .at[oob_idx].set(leaf_o, unique_indices=True)
            elif use_compact:
                rec, rec_cat, leaf_id, k, _ = grow(
                    codes_pack, codes_row, g, h, w, base_mask,
                    *meta, tree_key, **grow_kw, **statics)
            else:
                rec, rec_cat, leaf_id, k, _ = grow(
                    codes_pack, g, h, w, base_mask, *meta, tree_key,
                    **grow_kw, **statics)

            # on-device leaf-value replay avoids any H2D of leaf values.
            # The k == 0 gate makes the returned score EXACTLY the input
            # score on a no-split iteration, so the pipelined caller
            # (gbdt._train_one_iter_fused) can commit it before k is
            # fetched and still match the reference's stop semantics.
            lv = leaf_values_from_rec(rec, k, L)
            delta = jnp.take(lv, jnp.clip(leaf_id, 0, L - 1)) * shrinkage
            delta = jnp.where(k > 0, delta, jnp.zeros_like(delta))
            new_score = score_row + delta
            # in-program non-finite sentry: any NaN/inf gradient or leaf
            # output propagates into the updated score, so one reduction
            # INSIDE the program covers the whole fused iteration and a
            # guarded run adds zero extra dispatches
            finite = jnp.all(jnp.isfinite(new_score))
            return new_score, rec, rec_cat, leaf_id, k, finite

        def step(score_row, base_mask, tree_key, bag_key, shrinkage):
            # read self.codes_* at CALL time like the DP/FP wrappers, so
            # a rebuilt code buffer is never silently shadowed by a
            # stale snapshot
            codes_args = ((self.codes_pack, self.codes_row) if use_compact
                          else (self.codes_t, self.codes_t))
            obj_bufs = tuple(getattr(objective, k) for k in obj_keys)
            return step_impl(*codes_args, obj_bufs, score_row, base_mask,
                             tree_key, bag_key, shrinkage)

        # contract surface for tests/tools (program-size pinning)
        step.impl = step_impl
        step.obj_keys = obj_keys
        return step

    # ------------------------------------------------------------------
    def leaf_rows(self, leaf: int) -> np.ndarray:
        """IN-BAG row indices of a leaf after training (leaf renewal path).

        last_leaf_id routes every row (out-of-bag included), but leaf
        renewal must use in-bag rows only, matching the reference's
        RenewTreeOutput over the data partition (serial_tree_learner.cpp:
        855-893) and SerialTreeLearner.leaf_rows."""
        if self._leaf_id_host is None:
            self._leaf_id_host = np.asarray(jax.device_get(self.last_leaf_id))
        in_leaf = self._leaf_id_host == leaf
        if self._bag_mask_host is not None:
            in_leaf = in_leaf & self._bag_mask_host
        return np.nonzero(in_leaf)[0]
