"""GBDT boosting orchestrator + DART / GOSS / RF variants.

Equivalent of the reference boosting layer (reference: src/boosting/gbdt.cpp,
dart.hpp, goss.hpp, rf.hpp, gbdt_model_text.cpp). The per-iteration flow
mirrors GBDT::TrainOneIter (gbdt.cpp:368-451): boost-from-average on the
first iteration, objective gradients, bagging, one tree per class, leaf
renewal, shrinkage, score update, metric eval.

TPU mapping: scores and gradients live on device as (K, N) f32; gradient
computation is one fused jitted op; score updates run the vectorized binned
traversal (ops/predict.py); only the tiny tree structures and split decisions
ride on host.
"""
from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry
from ..config import Config
from ..io.dataset import Dataset
from ..metrics import create_metrics
from ..objectives import create_objective
from ..objectives.objective import MAPE
from ..ops import predict as predict_ops
from ..ops import quantize as quantize_ops
from ..resilience import faults
from ..telemetry import counters as telem_counters
from ..telemetry import recorder as telem
from ..utils import log
from ..utils.envs import flag, pipeline_env
from .serial_learner import SerialTreeLearner
from .tree import Tree

K_EPSILON = 1e-15
MODEL_VERSION = "v3"


def _host_global(arr) -> Optional[np.ndarray]:
    """Host copy of a device array that may span processes. Addressable
    arrays fetch directly; process-spanning ones (row-sharded scores on
    a real multi-host mesh) replicate through a collective — so when a
    process group is active EVERY rank must reach this call in the same
    order (distributed/checkpoint.py runs capture on all ranks)."""
    if arr is None:
        return None
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(jax.device_get(arr))
    from jax.experimental import multihost_utils
    gathered = faults.run_collective(
        lambda: multihost_utils.process_allgather(arr),
        site="host_global")
    return np.asarray(gathered)


def _threshold_l1_np(s: float, l1: float) -> float:
    return math.copysign(max(0.0, abs(s) - l1), s)


def _grad_norm_summary(grad, hess) -> dict:
    """Host L2/max summary of the iteration's gradient pair for the
    flight recorder. Costs one device fetch — callers gate on
    telemetry.events.enabled()."""
    g = np.asarray(jax.device_get(grad), dtype=np.float64)
    h = np.asarray(jax.device_get(hess), dtype=np.float64)
    return {"grad_l2": float(np.linalg.norm(g)),
            "grad_max_abs": float(np.max(np.abs(g))) if g.size else 0.0,
            "hess_l2": float(np.linalg.norm(h))}


class ScoreUpdater:
    """Per-dataset raw scores (reference: src/boosting/score_updater.hpp)."""

    def __init__(self, dataset: Dataset, num_class: int):
        self.dataset = dataset
        n = dataset.num_data
        init = np.zeros((num_class, n), dtype=np.float32)
        self.has_init_score = dataset.metadata.init_score is not None
        if self.has_init_score:
            s = np.asarray(dataset.metadata.init_score, dtype=np.float32)
            if s.size == n * num_class:
                init = s.reshape(num_class, n)
            else:
                init = np.tile(s.reshape(1, n), (num_class, 1))
        self._score = jnp.asarray(init)
        self._host_cache: Optional[np.ndarray] = None
        (self.f_numbins, self.f_missing, self.f_default,
         _, _) = dataset.feature_meta_arrays()

    # `score` is a property so that EVERY mutation — the .at updates
    # below AND the direct assignments from the fused/pipelined paths —
    # invalidates the cached host copy exactly once.
    @property
    def score(self) -> jax.Array:
        return self._score

    @score.setter
    def score(self, value: jax.Array) -> None:
        self._score = value
        self._host_cache = None

    def add_constant(self, val: float, class_id: int) -> None:
        self.score = self.score.at[class_id].add(jnp.float32(val))

    def add_tree(self, tree: Tree, class_id: int) -> None:
        if not getattr(tree, "inner_valid", True):
            # deserialized trees (init_model / BoosterMerge continuation)
            # carry raw thresholds only; reconstruct binned routing first
            tree.rebin_inner(self.dataset)
        vals = predict_ops.predict_binned_tree_values(
            self.dataset.device_binned(), self.f_missing, self.f_default,
            self.f_numbins, tree)
        self.score = self.score.at[class_id].add(vals)

    def add_tree_by_leaf_id(self, tree: Tree, leaf_id, class_id: int) -> None:
        """Score update from the device learner's row->leaf assignment:
        a (N,) gather instead of re-walking the tree (the role of the
        reference's in-bag AddScore(tree_learner) fast path,
        score_updater.hpp:84)."""
        leaf_vals = jnp.asarray(
            np.asarray(tree.leaf_value[:max(tree.num_leaves, 1)],
                       dtype=np.float32))
        self.score = self.score.at[class_id].add(
            jnp.take(leaf_vals, jnp.clip(leaf_id, 0, tree.num_leaves - 1)))

    def multiply(self, factor: float, class_id: int) -> None:
        self.score = self.score.at[class_id].multiply(jnp.float32(factor))

    def host_scores(self) -> np.ndarray:
        """Host f64 copy of the scores, cached per score version: multi-
        metric / multi-valid eval of one iteration fetches the device
        array ONCE instead of a fresh device_get + f64 convert per
        metric. Routed through `_host_global` because a multi-process
        data-parallel run row-shards the score across hosts — the gather
        is a collective there, so every rank evaluates metrics in the
        same order (they already do: eval runs lock-step per iteration).
        Callers treat the returned array as read-only."""
        if self._host_cache is None:
            self._host_cache = np.asarray(
                _host_global(self._score), dtype=np.float64)
            if telem_counters.is_active():
                telem_counters.incr("transfer_d2h_bytes",
                                    self._score.size * 4)
        return self._host_cache


class GBDT:
    """The boosting engine (reference: src/boosting/gbdt.cpp GBDT)."""

    average_output = False

    def __init__(self, config: Config, train_set: Optional[Dataset],
                 objective=None):
        self.config = config
        self.train_set = train_set
        # fused-iteration pipelining (round 5): the most recent fused
        # iteration's split records may still be in flight on device;
        # `models` materializes them on read (see the property below).
        # The lock keeps concurrent READERS (the C ABI's thread-safety
        # contract: prediction may run concurrently with anything) from
        # double-materializing one stash; mutation calls themselves are
        # serialized by the caller, as in the reference.
        self._pending_fused = None
        self._pend_lock = threading.Lock()
        self._pipeline = pipeline_env()
        self._models: List[Tree] = []
        self.iter = 0
        self.num_init_iteration = 0
        self.shrinkage_rate = config.learning_rate
        self.objective = objective
        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self.valid_updaters: List[ScoreUpdater] = []
        self.valid_metrics: List[List] = []
        self.train_metrics: List = []
        self.best_iteration = 0
        self.label_idx = 0
        self.loaded_parameter = ""
        self._sentry_retrying = False
        self._ev_grad_norms = None
        # tensorized-ensemble cache: trees_to_arrays is O(T*M) host work
        # plus a device upload, and back-to-back predicts on a static
        # model were re-paying it every call. Keyed on a model
        # fingerprint (length + last-tree identity + an explicit
        # generation for in-place leaf edits), so growth, rollback and
        # refit all invalidate. The serving registry warms through the
        # same cache.
        self._ensemble_cache: Dict = {}
        self._ensemble_gen = 0

        if train_set is not None:
            self._init_train(train_set)

    # ------------------------------------------------------------------
    @property
    def models(self) -> List[Tree]:
        """The host-side tree list. With fused-iteration pipelining the
        newest tree's split records may still be on device; any read
        materializes them first, so every consumer (predict, save,
        rollback, cv, plotting, the C API) sees a consistent model."""
        if self._pending_fused is not None:
            self._materialize_pending()
        return self._models

    @models.setter
    def models(self, value: List[Tree]) -> None:
        if self._pending_fused is not None:
            self._materialize_pending()
        self._models = value

    def _materialize_pending(self) -> None:
        """Fetch + replay the in-flight fused iteration (if any). If that
        iteration found no split, training should have stopped there:
        rewind iter/score (its score delta was gated to 0 in-program, so
        the restore is a no-op numerically) and run the generic path at
        that iteration so the reference's stop bookkeeping — constant
        boost-from-average tree on a first-iteration stop, warning,
        model trimming — happens even when no further train_one_iter
        call is coming (e.g. the no-split iteration was the last one
        dispatched and the stop is discovered by a save/predict)."""
        with self._pend_lock:
            pend = self._pending_fused
            if pend is None:
                return
            self._pending_fused = None
        if self._materialize_one(pend):
            self.score_updater.score = pend[4]
            self.iter = pend[6]
            self._train_one_iter_generic()

    def _materialize_one(self, pend) -> bool:
        """Replay one stashed fused iteration into a host tree. Returns
        True when the iteration found no split (k == 0)."""
        rec, rec_cat, leaf_id, k_dev, _score_before, init_score, it, \
            shrinkage = pend
        if rec_cat is None:
            rec_h, k = jax.device_get((rec, k_dev))
            rec_cat_h = None
        else:
            rec_h, rec_cat_h, k = jax.device_get((rec, rec_cat, k_dev))
        k = int(k)
        if k == 0:
            return True
        tree = self.learner.replay_tree(rec_h, k, rec_cat_h)
        tree.apply_shrinkage(shrinkage)
        if abs(init_score) > K_EPSILON:
            tree.add_bias(init_score)
        self.learner.last_leaf_id = leaf_id
        self.learner._leaf_id_host = None
        self.learner._bag_mask_host = None
        self._last_leaf_ids[0] = leaf_id
        self._last_leaf_ids_iter = it
        for vu in self.valid_updaters:
            vu.add_tree(tree, 0)
        self._models.append(tree)
        return False

    def _init_train(self, train_set: Dataset) -> None:
        cfg = self.config
        telemetry.configure(getattr(cfg, "telemetry", "off"),
                            explicit="telemetry" in getattr(cfg, "raw", {}))
        # resolved config rides along in any postmortem bundle (a dict
        # assignment — free when bundling is off)
        telemetry.bundle.set_context(
            "config", {str(k): str(v)
                       for k, v in sorted(getattr(cfg, "raw", {}).items())})
        if self.objective is None and cfg.objective != "none":
            self.objective = create_objective(cfg.objective, cfg)
        if self.objective is not None:
            self.objective.init(train_set.metadata, train_set.num_data)
            self.num_class = self.objective.num_model_per_iteration
        else:
            self.num_class = max(1, cfg.num_class)
        self.num_tree_per_iteration = self.num_class
        from ..parallel.learners import create_tree_learner
        self.learner = create_tree_learner(cfg, train_set)
        self.score_updater = ScoreUpdater(train_set, self.num_class)
        self.num_data = train_set.num_data
        self.train_metrics = create_metrics(cfg.metric, cfg, cfg.objective)
        for m in self.train_metrics:
            m.init(train_set.metadata, train_set.num_data)
        self._bag_rng = np.random.RandomState(cfg.bagging_seed % (2**31 - 1))
        self._bag_indices: Optional[np.ndarray] = None
        self._last_leaf_ids: Dict[int, Any] = {}
        self._last_leaf_ids_iter = -1
        self._fused_step = None
        self._class_need_train = [
            self.objective.class_need_train(k) if self.objective else True
            for k in range(self.num_class)]
        self.feature_names = train_set.feature_names
        self.max_feature_idx = train_set.num_total_features - 1

    # ------------------------------------------------------------------
    def add_valid(self, valid_set: Dataset, name: str) -> None:
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        vu = ScoreUpdater(valid_set, self.num_class)
        # a valid set added after trees already exist (init_model / merge
        # continuation, or add_valid mid-training) must see their scores
        per = max(self.num_tree_per_iteration, 1)
        for it in range(len(self.models) // per):
            for k in range(per):
                vu.add_tree(self.models[it * per + k], k)
        self.valid_updaters.append(vu)
        metrics = create_metrics(self.config.metric, self.config,
                                 self.config.objective)
        for m in metrics:
            m.init(valid_set.metadata, valid_set.num_data)
        self.valid_metrics.append(metrics)

    # ------------------------------------------------------------------
    def _boost_from_average(self, class_id: int, update_scorer: bool) -> float:
        cfg = self.config
        # _models + pending check (NOT the materializing property): this
        # runs at the top of every iteration, and materializing here
        # would serialize the pipelined fused path
        if (self._models or self._pending_fused is not None
                or self.score_updater.has_init_score
                or self.objective is None):
            return 0.0
        if not (cfg.boost_from_average or self.train_set.num_features == 0):
            if self.objective.name in ("regression_l1", "quantile", "mape"):
                log.warning("Disabling boost_from_average in %s may cause the "
                            "slow convergence", self.objective.name)
            return 0.0
        init_score = self.objective.boost_from_score(class_id)
        if abs(init_score) > K_EPSILON:
            if update_scorer:
                self.score_updater.add_constant(init_score, class_id)
                for vu in self.valid_updaters:
                    vu.add_constant(init_score, class_id)
            log.info("Start training from score %f", init_score)
            return init_score
        return 0.0

    def _compute_gradients(self):
        """objective->GetGradients over the whole score tensor. This is
        the gradient fault-injection boundary (resilience/faults.py):
        an active plan may poison the returned pair, which the sentries
        below must then catch."""
        score = self.score_updater.score
        if self.num_class == 1:
            g, h = self.objective.get_gradients(score[0])
            g, h = g[None, :], h[None, :]
        else:
            g, h = self.objective.get_gradients(score)
        plan = faults.active_plan()
        if plan is not None:
            g, h = plan.inject_gradients(g, h, self.iter)
        return g, h

    # -- non-finite sentries (resilience/sentries.py) -------------------
    def _sentry_enabled(self) -> bool:
        return getattr(self.config, "on_nonfinite", "off") \
            not in ("off", "", "none")

    def _apply_nonfinite_policy(self, what: str) -> str:
        """Host-side policy dispatch once a guard trips. Returns 'skip'
        (drop the iteration) or 'retry' (previous iteration rolled back,
        recompute and go again); policy 'raise' raises."""
        from ..resilience.sentries import NonFiniteError
        pol = self.config.on_nonfinite
        if pol == "raise":
            raise NonFiniteError(
                f"non-finite {what} detected at iteration {self.iter}; "
                "set on_nonfinite=skip_iter/rollback to continue instead")
        # only roll back when a previous iteration remains afterwards:
        # rolling back to an EMPTY model would replay boost-from-average
        # with shifted bias bookkeeping
        if pol == "rollback" and self.iter > 0 \
                and len(self.models) > self.num_tree_per_iteration:
            log.warning("non-finite %s at iteration %d: rolling back one "
                        "iteration", what, self.iter)
            telemetry.events.emit("rollback", iteration=self.iter,
                                  what=what, reason="non_finite")
            self.rollback_one_iter()
            return "retry"
        log.warning("non-finite %s at iteration %d: skipping iteration",
                    what, self.iter)
        telemetry.events.emit("skip_iter", iteration=self.iter, what=what,
                              reason="non_finite")
        return "skip"

    def _guard_gradients(self, grad, hess, recompute=None):
        """One fused isfinite reduction over (grad, hess); returns the
        (possibly recomputed) pair, or None when the iteration should be
        skipped. `recompute` re-derives the pair after a rollback (None
        for custom-fobj gradients, which cannot be recomputed here)."""
        if not self._sentry_enabled():
            return grad, hess
        from ..resilience import sentries
        for _ in range(2):
            if sentries.all_finite(grad, hess):
                return grad, hess
            act = self._apply_nonfinite_policy("gradients/hessians")
            if act != "retry" or recompute is None:
                return None
            grad, hess = recompute()
        raise sentries.NonFiniteError(
            f"non-finite gradients persist at iteration {self.iter} "
            "after rollback")

    def _guard_tree(self, tree) -> bool:
        """Host check over the new tree's leaf outputs. True = usable;
        False = drop the tree (policy skip/rollback); raises on 'raise'."""
        if not self._sentry_enabled() or tree.num_leaves <= 1:
            return True
        vals = np.asarray(tree.leaf_value[:tree.num_leaves],
                          dtype=np.float64)
        if np.isfinite(vals).all():
            return True
        from ..resilience.sentries import NonFiniteError
        if self.config.on_nonfinite == "raise":
            raise NonFiniteError(
                f"non-finite leaf outputs at iteration {self.iter}")
        log.warning("non-finite leaf outputs at iteration %d: dropping "
                    "tree", self.iter)
        return False

    def _bagging(self, iteration: int):
        """Row sampling per iteration (reference gbdt.cpp:210-276)."""
        cfg = self.config
        n = self.num_data
        if cfg.bagging_freq <= 0 or cfg.bagging_fraction >= 1.0:
            if (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0) \
                    and cfg.bagging_freq > 0:
                pass  # balanced bagging handled below
            else:
                return None
        if iteration % max(cfg.bagging_freq, 1) != 0 and self._bag_indices is not None:
            return self._bag_indices
        if (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0) \
                and self.objective is not None and self.objective.name == "binary":
            pos = np.nonzero(self.train_set.label > 0)[0]
            neg = np.nonzero(self.train_set.label <= 0)[0]
            kp = max(1, int(len(pos) * cfg.pos_bagging_fraction))
            kn = max(1, int(len(neg) * cfg.neg_bagging_fraction))
            idx = np.concatenate([
                self._bag_rng.choice(pos, kp, replace=False),
                self._bag_rng.choice(neg, kn, replace=False)])
        else:
            k = max(1, int(n * cfg.bagging_fraction))
            idx = self._bag_rng.choice(n, k, replace=False)
        idx = np.sort(idx).astype(np.int32)
        self._bag_indices = idx
        return idx

    # ------------------------------------------------------------------
    def _fused_eligible(self) -> bool:
        """Whether the single-program device iteration applies (plain GBDT,
        single-class jittable objective, device learner, plain bagging)."""
        from .device_learner import DeviceTreeLearner
        if self.__class__ is GOSS and not getattr(
                self.learner, "supports_fused_goss", False):
            # every current device learner carries in-program GOSS; the
            # guard protects future device learners that opt out
            return False
        plan = faults.active_plan()
        if plan is not None and plan.has_gradient_faults:
            # gradient faults inject at the host boundary
            # (_compute_gradients); the fused step computes gradients
            # in-program, so route through the generic path
            return False
        if getattr(self.config, "stream_mode", "off") != "off":
            # streamed assembly is a host-driven H2D loop per iteration;
            # the fused whole-iteration program has no seam for it
            return False
        return (self.__class__ in (GBDT, GOSS)
                and isinstance(self.learner, DeviceTreeLearner)
                and self.objective is not None
                and not self.objective.is_renew_tree_output
                and self.num_class == 1
                and self.num_tree_per_iteration == 1
                and self._class_need_train[0]
                and self.train_set.num_features > 0
                and self.config.pos_bagging_fraction >= 1.0
                and self.config.neg_bagging_fraction >= 1.0)

    def _batched_k_eligible(self) -> bool:
        """Whether this iteration's K per-class trees can grow as one
        vmap-batched device program (DeviceTreeLearner.train_batched).
        Plain multiclass GBDT only — DART/GOSS/RF keep the per-class
        loop — and every class must actually train this iteration.
        LGBM_TPU_NO_VMAP_K is the escape hatch."""
        if (self.__class__ is not GBDT
                or self.num_tree_per_iteration <= 1
                or flag("LGBM_TPU_NO_VMAP_K")):
            return False
        if (self.objective is None
                or self.objective.is_renew_tree_output
                or self.train_set.num_features == 0
                or not all(self._class_need_train)):
            return False
        sup = getattr(self.learner, "supports_batched_k", None)
        return bool(sup and sup())

    def _train_one_iter_fused(self) -> bool:
        """One boosting iteration as one device program + one small fetch
        (see DeviceTreeLearner.make_fused_step)."""
        cfg = self.config
        with telem.phase("boost_avg"):
            init_score = self._boost_from_average(0, True)
        goss_params = self._fused_goss()
        # GOSS replaces bagging outright (goss.hpp overrides Bagging):
        # its warmup step must train on ALL rows even when bagging
        # params are set
        bagging = not self._is_goss()
        if self._fused_step is None:
            self._fused_step = {}
        fkey = goss_params is not None
        if fkey not in self._fused_step:
            self._fused_step[fkey] = self.learner.make_fused_step(
                self.objective, goss=goss_params, bagging=bagging)
        fused_step = self._fused_step[fkey]
        rng = np.random.RandomState(
            (cfg.feature_fraction_seed + self.iter) % (2**31 - 1))
        fmask = self.learner._feature_mask(rng)
        if not getattr(self.learner, "cat_in_program", False):
            # learners without in-program categorical splitting (the
            # parallel device learners) must not sample cat features
            fmask = fmask & np.asarray(self.learner.f_categorical == 0)
        base_mask = jnp.asarray(fmask)
        tree_key = jax.random.PRNGKey(self.iter)
        # same bag key for bagging_freq consecutive iterations == reference
        # re-bags only on iter % freq == 0 and reuses the bag otherwise;
        # GOSS resamples EVERY iteration (goss.hpp has no freq notion)
        freq = 1 if self._fused_goss() else max(cfg.bagging_freq, 1)
        bag_key = jax.random.PRNGKey(
            (cfg.bagging_seed + (self.iter // freq)) % (2**31 - 1))
        score_before = self.score_updater.score
        with telem.phase("grow_dispatch"):
            new_score, rec, rec_cat, leaf_id, k_dev, finite_dev = fused_step(
                score_before[0], base_mask, tree_key, bag_key,
                jnp.float32(self.shrinkage_rate))
        telemetry.note_grow_dispatches(1.0, trees=1.0)

        if self._sentry_enabled():
            # the finite flag is computed INSIDE the fused program (one
            # reduction over the updated score row — any non-finite
            # gradient or leaf output propagates into it), so guarding
            # the iteration adds zero extra dispatches; the bool() here
            # is the policy decision's unavoidable host sync
            with telem.phase("sentry"):
                finite = bool(finite_dev)
            if not finite:
                act = self._apply_nonfinite_policy("fused iteration outputs")
                if act == "retry" and not self._sentry_retrying:
                    self._sentry_retrying = True
                    try:
                        return self._train_one_iter_fused()
                    finally:
                        self._sentry_retrying = False
                self.iter += 1   # skip: nothing committed, nothing stashed
                return False

        pend = (rec, rec_cat, leaf_id, k_dev, score_before, init_score,
                self.iter, self.shrinkage_rate)

        if self._pipeline:
            # Pipelined (TPU default): commit the score immediately (the
            # program gates the delta to 0 when k == 0, so this is safe
            # before k is known), stash the record handles, and replay
            # the PREVIOUS iteration's tree while this program runs on
            # device — hiding the ~70 ms/iter record-fetch round trip
            # and the host replay entirely (tools/profile_fused.py).
            with telem.phase("score_update"):
                self.score_updater.score = score_before.at[0].set(new_score)
            with self._pend_lock:
                prev = self._pending_fused
                self._pending_fused = pend
            self.iter += 1
            with telem.phase("host_sync"):
                prev_stopped = (prev is not None
                                and self._materialize_one(prev))
            if prev_stopped:
                # the PREVIOUS iteration found no split, so training
                # should already have stopped there. Its score delta was
                # 0, so the in-flight program saw identical gradients
                # and is pure waste: discard it, rewind to the no-split
                # iteration's OWN index (the generic re-run must use its
                # seeds — prev's feature mask found no split; this
                # iteration's fresh mask might), and let the generic
                # path produce the reference's stop bookkeeping
                # (constant init-score tree on a first-iteration stop,
                # warning, model trimming).
                with self._pend_lock:
                    self._pending_fused = None
                self.score_updater.score = prev[4]
                self.iter = prev[6]
                return self._train_one_iter_generic()
            return False

        with telem.phase("host_sync"):
            stopped = self._materialize_one(pend)
        if stopped:
            # delegate the stop bookkeeping (constant init-score tree on a
            # first-iteration stop, warning, model trimming) to the generic
            # path so both paths produce identical final models
            return self._train_one_iter_generic()
        with telem.phase("score_update"):
            self.score_updater.score = score_before.at[0].set(new_score)
        self.iter += 1
        return False

    def _fused_goss(self):
        """GOSS sampling parameters for the fused step; None for plain
        bagging (the GOSS subclass overrides)."""
        return None

    def _is_goss(self) -> bool:
        return False

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        """One boosting iteration; returns True when training should stop
        (no tree with >1 leaf was produced)."""
        ev_on = telemetry.events.enabled()
        if ev_on:
            coll0 = (telem_counters.get("collective_dispatches"),
                     telem_counters.get("collective_retries"))
            self._ev_grad_norms = None
        with telem.iteration(self.iter):
            if gradients is None and hessians is None \
                    and self._fused_eligible():
                stop = self._train_one_iter_fused()
            else:
                stop = self._train_one_iter_generic(gradients, hessians)
        if ev_on:
            self._emit_iteration_event(stop, coll0)
        return stop

    def _emit_iteration_event(self, stop: bool, coll0) -> None:
        """Assemble this iteration's flight-recorder record: recorder
        phases, grad/hess norms (generic path), quantization plan,
        stream overlap/peaks, and collective deltas. Events-gated — the
        off path never reaches here."""
        rec: Dict[str, Any] = {}
        last = telem.last_iteration()
        if last is not None:
            rec.update(last)
        else:
            rec["iteration"] = self.iter - (0 if stop else 1)
        if stop:
            rec["stop"] = True
        if self._ev_grad_norms is not None:
            rec["grad_norms"] = self._ev_grad_norms
        cfg = self.config
        if getattr(cfg, "quantized_grad", False):
            rec["quant"] = {
                "grad_bits": int(cfg.grad_bits),
                "renew": bool(getattr(cfg, "quant_renew", False)),
                "storage_bits": quantize_ops.storage_bits(
                    int(cfg.grad_bits),
                    bool(getattr(cfg, "quant_renew", False)))}
        shard = getattr(self.learner, "_shard", None)
        if shard is not None:
            overlap = shard.overlap_fraction()
            rec["stream"] = {
                "overlap_fraction": (None if overlap is None
                                     else round(overlap, 4)),
                "peak_bytes": int(getattr(shard, "peak_bytes", 0)),
                "h2d_bytes": int(getattr(shard, "h2d_bytes", 0))}
        d0, r0 = coll0
        dispatches = telem_counters.get("collective_dispatches") - d0
        retries = telem_counters.get("collective_retries") - r0
        if dispatches or retries:
            rec["collectives"] = {"dispatches": int(dispatches),
                                  "retries": int(retries)}
        telemetry.record_iteration(rec)

    def _train_one_iter_generic(self, gradients=None, hessians=None) -> bool:
        init_scores = [0.0] * self.num_tree_per_iteration
        with telem.phase("gradient"):
            if gradients is None or hessians is None:
                for k in range(self.num_tree_per_iteration):
                    init_scores[k] = self._boost_from_average(k, True)
                grad, hess = self._compute_gradients()
            else:
                grad = jnp.asarray(gradients, dtype=jnp.float32).reshape(
                    self.num_tree_per_iteration, self.num_data)
                hess = jnp.asarray(hessians, dtype=jnp.float32).reshape(
                    self.num_tree_per_iteration, self.num_data)

            guarded = self._guard_gradients(
                grad, hess,
                self._compute_gradients if gradients is None else None)
        if guarded is None:
            self.iter += 1   # skipped: seeds keep moving, no tree/score
            return False
        grad, hess = guarded
        if telemetry.events.enabled():
            self._ev_grad_norms = _grad_norm_summary(grad, hess)

        with telem.phase("bagging"):
            bag_indices = self._bagging(self.iter)
        batched_trees = None
        if self._batched_k_eligible():
            # vmap-batched multiclass: all K per-class trees of this
            # iteration grow as ONE batched device program (per-class
            # seeds derived exactly as the per-class loop derives them,
            # so the models are bit-identical)
            batched_trees = self.learner.train_batched(
                grad, hess, bag_indices,
                iter_seed0=self.iter * self.num_tree_per_iteration)
        should_continue = False
        sentry_dropped = False
        for k in range(self.num_tree_per_iteration):
            new_tree = Tree(2)
            if self._class_need_train[k] and self.train_set.num_features > 0:
                if batched_trees is not None:
                    new_tree = batched_trees[k]
                    # _update_score routes by last_leaf_id: install class
                    # k's routing row from the batched program
                    self.learner.last_leaf_id = \
                        self.learner._batched_leaf_ids[k]
                    self.learner._leaf_id_host = None
                else:
                    new_tree = self.learner.train(
                        grad[k], hess[k], bag_indices,
                        iter_seed=self.iter * self.num_tree_per_iteration + k)
                if not self._guard_tree(new_tree):
                    new_tree = Tree(2)
                    sentry_dropped = True
            if new_tree.num_leaves > 1:
                should_continue = True
                if (self.objective is not None
                        and self.objective.is_renew_tree_output):
                    self._renew_tree_output(new_tree, k)
                new_tree.apply_shrinkage(self.shrinkage_rate)
                self._update_score(new_tree, k)
                if abs(init_scores[k]) > K_EPSILON:
                    new_tree.add_bias(init_scores[k])
            else:
                if len(self.models) < self.num_tree_per_iteration:
                    if not self._class_need_train[k] and self.objective is not None:
                        output = self.objective.boost_from_score(k)
                    else:
                        output = init_scores[k]
                    new_tree.as_constant_tree(output)
                    self.score_updater.add_constant(output, k)
                    for vu in self.valid_updaters:
                        vu.add_constant(output, k)
            self.models.append(new_tree)

        if not should_continue:
            if sentry_dropped and \
                    len(self.models) > self.num_tree_per_iteration:
                # every tree of this iteration was dropped by the sentry:
                # treat as a skipped iteration, not end of training
                del self.models[-self.num_tree_per_iteration:]
                self.iter += 1
                return False
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter += 1
        return False

    def _update_score(self, tree: Tree, class_id: int) -> None:
        with telem.phase("score_update"):
            self._update_score_inner(tree, class_id)

    def _update_score_inner(self, tree: Tree, class_id: int) -> None:
        leaf_id = getattr(self.learner, "last_leaf_id", None)
        if leaf_id is not None:
            self.score_updater.add_tree_by_leaf_id(tree, leaf_id, class_id)
            # remember the routing so rollback_one_iter subtracts along the
            # exact same path (EFB bundle-conflict rows can route
            # differently under tree traversal than under the partition)
            self._last_leaf_ids[class_id] = leaf_id
            self._last_leaf_ids_iter = self.iter
        else:
            self.score_updater.add_tree(tree, class_id)
            self._last_leaf_ids.pop(class_id, None)
        for vu in self.valid_updaters:
            vu.add_tree(tree, class_id)

    def _renew_tree_output(self, tree: Tree, class_id: int) -> None:
        """Leaf re-fit for L1-family objectives (reference:
        serial_tree_learner.cpp:855-893 RenewTreeOutput)."""
        scores = np.asarray(jax.device_get(
            self.score_updater.score[class_id]), dtype=np.float64)
        label = np.asarray(self.train_set.label, dtype=np.float64)
        if isinstance(self.objective, MAPE):
            weights = self.objective.leaf_renew_weight
        else:
            weights = self.train_set.metadata.weight
        for leaf in range(tree.num_leaves):
            rows = self.learner.leaf_rows(leaf)
            if len(rows) == 0:
                continue
            residuals = label[rows] - scores[rows]
            w = weights[rows] if weights is not None else None
            tree.set_leaf_output(
                leaf, self.objective.renew_leaf_output(residuals, w))

    def rollback_one_iter(self) -> None:
        if self.iter <= 0:
            return
        self.invalidate_ensemble_cache()
        for k in range(self.num_tree_per_iteration):
            tree = self.models[len(self.models) - self.num_tree_per_iteration + k]
            tree.apply_shrinkage(-1.0)
            leaf_id = (self._last_leaf_ids.get(k)
                       if self._last_leaf_ids_iter == self.iter - 1 else None)
            if leaf_id is not None and tree.num_leaves > 1:
                self.score_updater.add_tree_by_leaf_id(tree, leaf_id, k)
            else:
                self.score_updater.add_tree(tree, k)
            for vu in self.valid_updaters:
                vu.add_tree(tree, k)
        self._last_leaf_ids.clear()
        del self.models[-self.num_tree_per_iteration:]
        self.iter -= 1

    # ------------------------------------------------------------------
    def eval_metrics(self) -> Dict[str, List]:
        """(dataset_name, metric_name, value, higher_better) tuples."""
        # valid_updaters receive the pending tree only at materialization
        # (train scores are committed at dispatch, so only the VALID side
        # lags): sync here so per-iteration eval and early stopping see
        # iteration N with N trees, exactly like the synchronous path
        self._materialize_pending()
        out = []
        if self.train_metrics:
            scores = self.score_updater.host_scores()
            s = scores[0] if self.num_class == 1 else scores
            for m in self.train_metrics:
                for name, val in zip(m.names, m.eval(s, self.objective)):
                    out.append(("training", name, val, m.higher_better))
        for vi, (vset, vname, vup) in enumerate(
                zip(self.valid_sets, self.valid_names, self.valid_updaters)):
            scores = vup.host_scores()
            s = scores[0] if self.num_class == 1 else scores
            for m in self.valid_metrics[vi]:
                for name, val in zip(m.names, m.eval(s, self.objective)):
                    out.append((vname, name, val, m.higher_better))
        return out

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        return len(self.models)

    @property
    def current_iteration(self) -> int:
        return len(self.models) // max(self.num_tree_per_iteration, 1)

    def invalidate_ensemble_cache(self) -> None:
        """Drop cached tensorized ensembles. The cache key already tracks
        tree-list growth/shrinkage; call this for IN-PLACE leaf edits
        (refit, set_leaf_output, DART renormalization) that the
        fingerprint cannot see."""
        self._ensemble_gen += 1
        self._ensemble_cache.clear()

    def ensemble_arrays(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0, bucket: bool = True):
        """Cached (EnsembleArrays, tree_class, n_models) for the model
        slice. Repeated predicts on an unchanged model reuse one
        tensorization + device upload instead of re-running
        trees_to_arrays per call; tree growth changes the fingerprint and
        naturally misses. tree_class is None for bucket=False (leaf-index
        prediction must not pad the tree axis)."""
        models = self._used_models(num_iteration, start_iteration)
        if not models:
            return None, None, 0
        fp = (len(self._models), id(self._models[-1]), self._ensemble_gen)
        key = (fp, start_iteration, len(models), bucket)
        hit = self._ensemble_cache.get(key)
        if hit is None:
            arrays = predict_ops.trees_to_arrays(models, bucket=bucket)
            tc = (predict_ops.padded_tree_class(
                arrays, np.arange(len(models)) % self.num_tree_per_iteration)
                if bucket else None)
            hit = (arrays, tc, len(models))
            if len(self._ensemble_cache) >= 16:   # bound stale slices
                self._ensemble_cache.clear()
            self._ensemble_cache[key] = hit
        return hit

    def predict_raw(self, x: np.ndarray, num_iteration: Optional[int] = None,
                    start_iteration: int = 0) -> np.ndarray:
        """(N, K) raw scores over raw feature values."""
        x = np.ascontiguousarray(np.asarray(x, dtype=np.float32))
        if x.ndim == 1:
            x = x.reshape(1, -1)
        arrays, tc, n_models = self.ensemble_arrays(
            num_iteration, start_iteration, bucket=True)
        if not n_models:
            return np.zeros((x.shape[0], self.num_class))
        out = predict_ops.predict_raw_ensemble(
            jnp.asarray(x), arrays, tc,
            max_depth=arrays.max_depth, num_class=self.num_class)
        out = np.asarray(jax.device_get(out), dtype=np.float64)
        if self.average_output:
            out /= max(1, n_models // self.num_tree_per_iteration)
        return out

    def predict_raw_early_stop(self, x: np.ndarray, num_iteration=None,
                               freq: int = 10, margin: float = 10.0,
                               start_iteration: int = 0) -> np.ndarray:
        """Raw scores with prediction early stopping (reference:
        src/boosting/prediction_early_stop.cpp): every `freq` trees, rows
        whose decision margin exceeds `margin` stop accumulating — binary
        margin = 2|score|, multiclass = top1 - top2."""
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x.reshape(1, -1)
        models = self._used_models(num_iteration, start_iteration)
        k = self.num_tree_per_iteration
        n = x.shape[0]
        scores = np.zeros((n, self.num_class))
        active = np.arange(n)
        step = max(1, freq) * k
        for start in range(0, len(models), step):
            if len(active) == 0:
                break
            chunk = models[start:start + step]
            # no bucketing here: x[active] shrinks every round, so the
            # changing row count forces a recompile regardless — padded
            # trees would only add traversal work
            arrays = predict_ops.trees_to_arrays(chunk)
            tree_class = jnp.asarray(
                (np.arange(len(chunk), dtype=np.int32) + start) % k)
            out = predict_ops.predict_raw_ensemble(
                jnp.asarray(x[active]), arrays, tree_class,
                max_depth=arrays.max_depth, num_class=self.num_class)
            scores[active] += np.asarray(jax.device_get(out))
            if self.num_class == 1:
                m = 2.0 * np.abs(scores[active, 0])
            else:
                srt = np.sort(scores[active], axis=1)
                m = srt[:, -1] - srt[:, -2]
            active = active[m <= margin]
        return scores

    def predict(self, x, num_iteration=None, raw_score=False,
                pred_leaf=False, pred_contrib=False, start_iteration=0,
                pred_early_stop=False, pred_early_stop_freq=10,
                pred_early_stop_margin=10.0):
        if pred_leaf:
            arrays, _, _ = self.ensemble_arrays(
                num_iteration, start_iteration, bucket=False)
            x = np.asarray(x, dtype=np.float32)
            if x.ndim == 1:
                x = x.reshape(1, -1)
            leaves = predict_ops.predict_leaf_index_ensemble(
                jnp.asarray(x), arrays, max_depth=arrays.max_depth)
            return np.asarray(jax.device_get(leaves))
        if pred_contrib:
            return self.predict_contrib(x, num_iteration)
        if pred_early_stop:
            raw = self.predict_raw_early_stop(
                x, num_iteration, pred_early_stop_freq,
                pred_early_stop_margin, start_iteration)
        else:
            raw = self.predict_raw(x, num_iteration, start_iteration)
        if raw_score:
            return raw[:, 0] if self.num_class == 1 else raw
        if self.objective is not None:
            converted = self.objective.convert_output(jnp.asarray(raw.T))
            out = np.asarray(jax.device_get(converted)).T
        else:
            out = raw
        return out[:, 0] if self.num_class == 1 else out

    def predict_contrib(self, x, num_iteration=None) -> np.ndarray:
        """TreeSHAP feature contributions (reference: tree.cpp:669-713
        PredictContrib). Host implementation — irregular recursion."""
        from .treeshap import predict_contrib
        return predict_contrib(self, x, num_iteration)

    def _used_models(self, num_iteration, start_iteration=0) -> List[Tree]:
        total_iter = len(self.models) // max(self.num_tree_per_iteration, 1)
        start_iteration = max(0, min(start_iteration, total_iter))
        start = start_iteration * self.num_tree_per_iteration
        if num_iteration is not None and num_iteration > 0:
            end = min((start_iteration + num_iteration)
                      * self.num_tree_per_iteration, len(self.models))
        else:
            end = len(self.models)
        return self.models[start:end]

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        n = self.max_feature_idx + 1
        out = np.zeros(n, dtype=np.float64)
        models = self._used_models(iteration)
        for tree in models:
            for node in range(tree.num_leaves - 1):
                if importance_type == "split":
                    out[tree.split_feature[node]] += 1.0
                else:
                    if tree.split_gain[node] > 0:
                        out[tree.split_feature[node]] += tree.split_gain[node]
        return out

    def refit_leaves(self, leaf_preds: np.ndarray, decay_rate: float) -> None:
        """Refit leaf values on new data keeping structure (reference:
        gbdt.cpp:298-321 RefitTree + FitByExistingTree): new_value =
        decay * old + (1 - decay) * regularized mean-gradient estimate.
        Gradients come from this booster's own objective/score context;
        the leaf update itself runs through `_refit_leaves_apply`."""
        grad, hess = self._compute_gradients()
        self._refit_leaves_apply(leaf_preds, grad, hess, decay_rate)

    def refit_leaves_on(self, dataset: Dataset, leaf_preds: np.ndarray,
                        decay_rate: float) -> None:
        """In-place `task=refit` against NEW data: gradients of the
        objective at its zero-score init over `dataset` — the same
        context the historical rebuild-a-Booster path produced (a fresh
        ScoreUpdater starts at zero), so the leaf values match it bit
        for bit — then one in-place leaf update on THIS model."""
        cfg = self.config
        obj = (create_objective(cfg.objective, cfg)
               if cfg.objective != "none" else None)
        if obj is None:
            raise ValueError("refit requires an objective "
                             "(objective=none has no gradients)")
        obj.init(dataset.metadata, dataset.num_data)
        num_class = obj.num_model_per_iteration
        score = jnp.zeros((num_class, dataset.num_data), dtype=jnp.float32)
        if num_class == 1:
            g, h = obj.get_gradients(score[0])
            g, h = g[None, :], h[None, :]
        else:
            g, h = obj.get_gradients(score)
        self._refit_leaves_apply(leaf_preds, g, h, decay_rate,
                                 num_tree_per_iteration=num_class)

    def _refit_leaves_apply(self, leaf_preds, grad, hess,
                            decay_rate: float,
                            num_tree_per_iteration: Optional[int] = None
                            ) -> None:
        """Shared refit tail: ONE ensemble-cache invalidation, then the
        device segment-sum program (continual/refit.py — one dispatch,
        leaf stats psum'd across ranks when row-sharded) or the
        historical host loop (LGBM_TPU_HOST_REFIT=1, the parity
        reference)."""
        per_iter = (num_tree_per_iteration if num_tree_per_iteration
                    else self.num_tree_per_iteration)
        self.invalidate_ensemble_cache()
        from ..continual import refit as continual_refit
        cfg = self.config
        if continual_refit.device_refit_enabled():
            continual_refit.refit_leaves_device(
                self.models, leaf_preds, grad, hess,
                lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
                max_delta_step=cfg.max_delta_step, decay_rate=decay_rate,
                shrinkage_rate=self.shrinkage_rate,
                num_tree_per_iteration=per_iter)
            return
        self._refit_leaves_host(leaf_preds, grad, hess, decay_rate,
                                per_iter)

    def _refit_leaves_host(self, leaf_preds, grad, hess,
                           decay_rate: float,
                           num_tree_per_iteration: int) -> None:
        """The original host per-leaf loop, kept as the device path's
        parity reference (tests/test_continual_refit.py)."""
        g = np.asarray(jax.device_get(grad))
        h = np.asarray(jax.device_get(hess))
        cfg = self.config
        for ti, tree in enumerate(self.models):
            k = ti % num_tree_per_iteration
            leaves = leaf_preds[:, ti]
            for leaf in range(tree.num_leaves):
                rows = np.nonzero(leaves == leaf)[0]
                if len(rows) == 0:
                    continue
                sg = float(g[k][rows].sum())
                sh = float(h[k][rows].sum())
                out = -_threshold_l1_np(sg, cfg.lambda_l1) / (sh + cfg.lambda_l2)
                if cfg.max_delta_step > 0:
                    out = float(np.clip(out, -cfg.max_delta_step,
                                        cfg.max_delta_step))
                old = float(tree.leaf_value[leaf])
                tree.set_leaf_output(
                    leaf, decay_rate * old
                    + (1.0 - decay_rate) * out * self.shrinkage_rate)

    # -- serving drift baseline (serving/drift.py) ---------------------
    def drift_baseline(self) -> Optional[Dict[str, Any]]:
        """Training-time drift baseline for serving: per-feature bin
        occupancy over the train set plus the *converted* train-score
        distribution (the same objective transform serving applies by
        default, so served predictions are directly comparable).
        Cached after the first call; None for model-only boosters (no
        train_set to baseline). The model text never changes — the CLI
        writes this to a ``<model>.drift.json`` sidecar."""
        if getattr(self, "train_set", None) is None \
                or getattr(self, "score_updater", None) is None:
            return None
        cached = getattr(self, "_drift_baseline", None)
        if cached is not None:
            return cached
        from ..serving import drift as serve_drift
        raw = _host_global(self.score_updater.score)   # (num_class, n)
        scores = raw
        if raw is not None and self.objective is not None:
            scores = np.asarray(jax.device_get(
                self.objective.convert_output(jnp.asarray(raw))))
        self._drift_baseline = serve_drift.compute_baseline(
            self.train_set, scores=scores)
        return self._drift_baseline

    # -- training-state capture/restore (resilience/checkpoint.py) -----
    def capture_state(self) -> Dict[str, Any]:
        """Live training state beyond the model text: everything a
        resumed run needs to continue bit-identically. Reading `models`
        first materializes any in-flight fused iteration, so the capture
        is a consistent iteration boundary."""
        if getattr(self, "_bag_rng", None) is None:
            log.fatal("checkpointing requires a booster constructed with "
                      "a train_set (model-only boosters have no training "
                      "state; use save_model instead)")
        _ = self.models
        st: Dict[str, Any] = {
            "iter": int(self.iter),
            "shrinkage_rate": float(self.shrinkage_rate),
            "best_iteration": int(self.best_iteration),
            "num_init_iteration": int(self.num_init_iteration),
            "bag_rng": self._bag_rng.get_state(),
            "bag_indices": (None if self._bag_indices is None
                            else np.asarray(self._bag_indices)),
            "train_score": (_host_global(self.score_updater.score)
                            if getattr(self, "score_updater", None)
                            is not None else None),
            "valid_scores": [_host_global(vu.score)
                             for vu in self.valid_updaters],
        }
        if isinstance(self, DART):
            st["dart"] = {"tree_weights": list(self._tree_weights),
                          "sum_weight": float(self._sum_weight),
                          "drop_rng": self._drop_rng.get_state()}
        stream = getattr(self.learner, "stream_state", lambda: None)()
        if stream is not None:
            st["stream"] = stream
        # serving drift baseline rides the checkpoint once computed
        # (cheap: it is a small dict of occupancy vectors) — a restore
        # can hand it straight to the serving registry
        if getattr(self, "_drift_baseline", None) is not None:
            st["drift_baseline"] = self._drift_baseline
        return st

    def restore_state(self, st: Dict[str, Any]) -> None:
        """Inverse of capture_state, applied after the model trees have
        been restored. Scores come back bit-exact from the stored f32
        arrays (NOT replayed through the trees: replay re-associates the
        float adds and the boost-from-average constant, which breaks
        kill-and-resume parity)."""
        if getattr(self, "_bag_rng", None) is None:
            log.fatal("restoring a checkpoint requires a booster "
                      "constructed with a train_set")
        self.iter = int(st["iter"])
        self.shrinkage_rate = float(st["shrinkage_rate"])
        self.best_iteration = int(st["best_iteration"])
        self.num_init_iteration = int(st["num_init_iteration"])
        self._bag_rng.set_state(st["bag_rng"])
        self._bag_indices = (None if st.get("bag_indices") is None
                             else np.asarray(st["bag_indices"],
                                             dtype=np.int32))
        if st.get("train_score") is not None \
                and getattr(self, "score_updater", None) is not None:
            self.score_updater.score = jnp.asarray(
                np.asarray(st["train_score"], dtype=np.float32))
        vs = st.get("valid_scores") or []
        if vs and len(vs) == len(self.valid_updaters):
            for vu, arr in zip(self.valid_updaters, vs):
                vu.score = jnp.asarray(np.asarray(arr, dtype=np.float32))
        elif self.valid_updaters:
            log.warning(
                "checkpoint carries %d valid-set scores, booster has %d "
                "valid sets: rebuilding scores by tree replay", len(vs),
                len(self.valid_updaters))
            per = max(self.num_tree_per_iteration, 1)
            for i, vset in enumerate(self.valid_sets):
                vu = ScoreUpdater(vset, self.num_class)
                for it in range(len(self._models) // per):
                    for k in range(per):
                        vu.add_tree(self._models[it * per + k], k)
                self.valid_updaters[i] = vu
        if "dart" in st and isinstance(self, DART):
            d = st["dart"]
            self._tree_weights = list(d["tree_weights"])
            self._sum_weight = float(d["sum_weight"])
            self._drop_rng.set_state(d["drop_rng"])
        if st.get("stream") is not None and hasattr(
                self.learner, "load_stream_state"):
            self.learner.load_stream_state(st["stream"])
        if isinstance(st.get("drift_baseline"), dict):
            self._drift_baseline = st["drift_baseline"]
        self._last_leaf_ids.clear()
        self._last_leaf_ids_iter = -1
        self.invalidate_ensemble_cache()

    # -- model serialization -------------------------------------------
    def save_model_to_string(self, start_iteration: int = 0,
                             num_iteration: int = -1) -> str:
        """reference: gbdt_model_text.cpp:250 SaveModelToString."""
        lines = ["tree", f"version={MODEL_VERSION}",
                 f"num_class={self.num_class}",
                 f"num_tree_per_iteration={self.num_tree_per_iteration}",
                 f"label_index={self.label_idx}",
                 f"max_feature_idx={self.max_feature_idx}"]
        if self.objective is not None:
            lines.append(f"objective={self.objective.to_string()}")
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        if self.config.monotone_constraints:
            lines.append("monotone_constraints=" + " ".join(
                str(c) for c in self.config.monotone_constraints))
        feature_infos = (self.train_set.feature_infos() if self.train_set
                         else getattr(self, "_feature_infos", []))
        lines.append("feature_infos=" + " ".join(feature_infos))

        models = self._used_models(
            num_iteration if num_iteration > 0 else None, start_iteration)
        tree_strs = []
        for i, tree in enumerate(models):
            s = f"Tree={i}\n" + tree.to_string() + "\n"
            tree_strs.append(s)
        sizes = [len(s) for s in tree_strs]
        lines.append("tree_sizes=" + " ".join(str(s) for s in sizes))
        lines.append("")
        body = "\n".join(lines) + "\n" + "".join(tree_strs)
        body += "end of trees\n"
        imp = self.feature_importance("split")
        pairs = [(int(imp[i]), self.feature_names[i])
                 for i in range(len(imp)) if imp[i] > 0]
        pairs.sort(key=lambda p: -p[0])
        body += "\nfeature importances:\n"
        for v, name in pairs:
            body += f"{name}={v}\n"
        body += "\nparameters:\n" + self.config.to_string() + "\n"
        body += "end of parameters\n"
        return body

    def save_model(self, filename: str, num_iteration: int = -1,
                   start_iteration: int = 0) -> None:
        from ..io.file_io import open_file
        with open_file(filename, "w") as f:
            f.write(self.save_model_to_string(start_iteration, num_iteration))

    @classmethod
    def load_model_from_string(cls, text: str,
                               config: Optional[Config] = None) -> "GBDT":
        """reference: gbdt_model_text.cpp:365 LoadModelFromString."""
        from ..objectives.objective import parse_objective_from_model
        config = config or Config()
        booster = cls(config, None)
        header, _, rest = text.partition("Tree=0")
        kv = {}
        for line in header.splitlines():
            if "=" in line:
                k, _, v = line.partition("=")
                kv[k.strip()] = v.strip()
        booster.num_class = int(kv.get("num_class", 1))
        booster.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
        booster.label_idx = int(kv.get("label_index", 0))
        booster.max_feature_idx = int(kv.get("max_feature_idx", 0))
        booster.feature_names = kv.get("feature_names", "").split()
        booster._feature_infos = kv.get("feature_infos", "").split()
        booster.average_output = "average_output" in header.split("\n")
        if "objective" in kv:
            config.num_class = booster.num_class
            booster.objective = parse_objective_from_model(kv["objective"], config)
        # parse trees
        tree_blocks = ("Tree=0" + rest).split("end of trees")[0]
        chunks = tree_blocks.split("Tree=")
        for chunk in chunks:
            chunk = chunk.strip()
            if not chunk:
                continue
            body = chunk.split("\n", 1)[1] if "\n" in chunk else ""
            booster.models.append(Tree.from_string(body))
        booster.num_init_iteration = (len(booster.models)
                                      // max(booster.num_tree_per_iteration, 1))
        booster.iter = 0
        return booster

    @classmethod
    def load_model(cls, filename: str,
                   config: Optional[Config] = None) -> "GBDT":
        from ..io.file_io import open_file
        with open_file(filename) as f:
            return cls.load_model_from_string(f.read(), config)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> dict:
        """reference: gbdt_model_text.cpp:28 DumpModel (JSON)."""
        models = self._used_models(num_iteration, start_iteration)
        return {
            "name": "tree",
            "version": MODEL_VERSION,
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_idx,
            "max_feature_idx": self.max_feature_idx,
            "objective": (self.objective.to_string() if self.objective else ""),
            "average_output": self.average_output,
            "feature_names": list(self.feature_names),
            "feature_importances": {
                self.feature_names[i]: float(v)
                for i, v in enumerate(self.feature_importance("split"))
                if v > 0},
            "tree_info": [
                dict(tree_index=i, **t.to_json()) for i, t in enumerate(models)],
        }


class DART(GBDT):
    """Dropout boosting (reference: src/boosting/dart.hpp)."""

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self._drop_rng = np.random.RandomState(
            (config.drop_seed) % (2**31 - 1))
        self._tree_weights: List[float] = []
        self._sum_weight = 0.0
        self.shrinkage_rate = config.learning_rate

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        drop_index = self._drop_trees()
        stop = super().train_one_iter(gradients, hessians)
        if not stop:
            self._normalize(drop_index)
        return stop

    def _drop_trees(self) -> List[int]:
        cfg = self.config
        drop_index: List[int] = []
        n_iter = self.iter
        if self._drop_rng.rand() >= cfg.skip_drop and n_iter > 0:
            drop_rate = cfg.drop_rate
            if cfg.uniform_drop:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / max(n_iter, 1))
                for i in range(n_iter):
                    if self._drop_rng.rand() < drop_rate:
                        drop_index.append(self.num_init_iteration + i)
                        if cfg.max_drop > 0 and len(drop_index) >= cfg.max_drop:
                            break
            else:
                inv_avg = len(self._tree_weights) / max(self._sum_weight, 1e-20)
                if cfg.max_drop > 0:
                    drop_rate = min(
                        drop_rate, cfg.max_drop * inv_avg / max(self._sum_weight, 1e-20))
                for i in range(n_iter):
                    if self._drop_rng.rand() < drop_rate * self._tree_weights[i] * inv_avg:
                        drop_index.append(self.num_init_iteration + i)
                        if cfg.max_drop > 0 and len(drop_index) >= cfg.max_drop:
                            break
        # un-apply dropped trees from train scores
        for i in drop_index:
            for k in range(self.num_tree_per_iteration):
                tree = self.models[i * self.num_tree_per_iteration + k]
                tree.apply_shrinkage(-1.0)
                self.score_updater.add_tree(tree, k)
        k_drop = len(drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k_drop)
        else:
            self.shrinkage_rate = (cfg.learning_rate if k_drop == 0 else
                                   cfg.learning_rate / (cfg.learning_rate + k_drop))
        self._drop_index = drop_index
        return drop_index

    def _normalize(self, drop_index: List[int]) -> None:
        cfg = self.config
        self.invalidate_ensemble_cache()
        k = float(len(drop_index))
        for i in drop_index:
            for c in range(self.num_tree_per_iteration):
                tree = self.models[i * self.num_tree_per_iteration + c]
                if not cfg.xgboost_dart_mode:
                    tree.apply_shrinkage(1.0 / (k + 1.0))
                    for vu in self.valid_updaters:
                        vu.add_tree(tree, c)
                    tree.apply_shrinkage(-k)
                    self.score_updater.add_tree(tree, c)
                    tree.apply_shrinkage(-1.0 / k if k else 1.0)
                else:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    for vu in self.valid_updaters:
                        vu.add_tree(tree, c)
                    tree.apply_shrinkage(-(1.0 + k) / k if k else 1.0)
                    self.score_updater.add_tree(tree, c)
                    tree.apply_shrinkage(-k / (1.0 + k))
            if not cfg.uniform_drop and self._tree_weights:
                ti = i - self.num_init_iteration
                self._sum_weight -= self._tree_weights[ti] * (1.0 / (k + 1.0))
                self._tree_weights[ti] *= k / (k + 1.0)
        self._tree_weights.append(self.shrinkage_rate)
        self._sum_weight += self.shrinkage_rate


class GOSS(GBDT):
    """Gradient-based one-side sampling (reference: src/boosting/goss.hpp)."""

    def _goss_sample(self):
        """Top |g*h| rows kept; others sampled with gradient amplification
        (reference goss.hpp:91 BaggingHelper)."""
        cfg = self.config
        grad, hess = self._last_grad_hess
        g = np.abs(np.asarray(jax.device_get(grad)) *
                   np.asarray(jax.device_get(hess))).sum(axis=0)
        n = self.num_data
        top_k, other_k, multiply = self._goss_params()
        order = np.argsort(-g, kind="stable")
        top_idx = order[:top_k]
        rest = order[top_k:]
        sampled = self._bag_rng.choice(
            len(rest), min(other_k, len(rest)), replace=False)
        other_idx = rest[sampled]
        self._goss_amplify = (other_idx, multiply)
        if hasattr(self.learner, "stream_note_top"):
            # streamed working-set policy: the top-|g*h| rows are the
            # ones worth keeping device-resident for the next iteration
            # (goss_working_set caps how many; 0 = the full top set)
            ws_k = int(getattr(self.config, "goss_working_set", 0) or 0)
            ws_k = top_k if ws_k <= 0 else min(ws_k, top_k)
            self.learner.stream_note_top(
                np.sort(top_idx[:ws_k]).astype(np.int32))
        idx = np.sort(np.concatenate([top_idx, other_idx])).astype(np.int32)
        return idx

    def _is_goss(self) -> bool:
        return True

    def _goss_params(self):
        cfg = self.config
        n = self.num_data
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        multiply = (n - top_k) / max(other_k, 1)
        return (top_k, other_k, float(multiply))

    def _fused_goss(self):
        # the reference trains on ALL rows for the first 1/learning_rate
        # iterations before sampling kicks in (goss.hpp:143-144)
        if self.iter < int(1.0 / max(self.config.learning_rate, 1e-12)):
            return None
        return self._goss_params()

    def _train_one_iter_generic(self, gradients=None,
                                hessians=None) -> bool:
        # compute gradients first so GOSS sampling can see them
        init_scores = [0.0] * self.num_tree_per_iteration
        with telem.phase("gradient"):
            if gradients is None or hessians is None:
                for k in range(self.num_tree_per_iteration):
                    init_scores[k] = self._boost_from_average(k, True)
                grad, hess = self._compute_gradients()
            else:
                grad = jnp.asarray(gradients, dtype=jnp.float32).reshape(
                    self.num_tree_per_iteration, self.num_data)
                hess = jnp.asarray(hessians, dtype=jnp.float32).reshape(
                    self.num_tree_per_iteration, self.num_data)
            guarded = self._guard_gradients(
                grad, hess,
                self._compute_gradients if gradients is None else None)
        if guarded is None:
            self.iter += 1
            return False
        grad, hess = guarded
        self._last_grad_hess = (grad, hess)
        if telemetry.events.enabled():
            self._ev_grad_norms = _grad_norm_summary(grad, hess)
        with telem.phase("bagging"):
            if self._fused_goss() is None:
                # reference warmup: no subsampling for the first
                # 1/learning_rate iterations (goss.hpp:143-144)
                bag_indices = None
            else:
                bag_indices = self._goss_sample()
                other_idx, multiply = self._goss_amplify
                amp = jnp.ones(self.num_data, dtype=jnp.float32).at[
                    jnp.asarray(other_idx)].set(float(multiply))
                grad = grad * amp[None, :]
                hess = hess * amp[None, :]

        should_continue = False
        sentry_dropped = False
        for k in range(self.num_tree_per_iteration):
            new_tree = Tree(2)
            if self._class_need_train[k] and self.train_set.num_features > 0:
                new_tree = self.learner.train(
                    grad[k], hess[k], bag_indices,
                    iter_seed=self.iter * self.num_tree_per_iteration + k)
                if not self._guard_tree(new_tree):
                    new_tree = Tree(2)
                    sentry_dropped = True
            if new_tree.num_leaves > 1:
                should_continue = True
                if (self.objective is not None
                        and self.objective.is_renew_tree_output):
                    self._renew_tree_output(new_tree, k)
                new_tree.apply_shrinkage(self.shrinkage_rate)
                self._update_score(new_tree, k)
                if abs(init_scores[k]) > K_EPSILON:
                    new_tree.add_bias(init_scores[k])
            else:
                if len(self.models) < self.num_tree_per_iteration:
                    output = init_scores[k]
                    new_tree.as_constant_tree(output)
                    self.score_updater.add_constant(output, k)
                    for vu in self.valid_updaters:
                        vu.add_constant(output, k)
            self.models.append(new_tree)
        if not should_continue:
            if sentry_dropped and \
                    len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
                self.iter += 1
                return False
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter += 1
        return False


class RF(GBDT):
    """Random forest mode (reference: src/boosting/rf.hpp): bagging
    mandatory, no shrinkage, fixed gradients from the init score, averaged
    output."""

    average_output = True

    def __init__(self, config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        self.shrinkage_rate = 1.0
        # gradients computed once from constant init scores
        init_scores = [self._boost_from_average(k, False)
                       for k in range(self.num_tree_per_iteration)]
        self._rf_init_scores = init_scores
        tmp = jnp.asarray(
            np.tile(np.asarray(init_scores, dtype=np.float32)[:, None],
                    (1, self.num_data)))
        if self.num_class == 1:
            g, h = self.objective.get_gradients(tmp[0])
            self._rf_grad, self._rf_hess = g[None, :], h[None, :]
        else:
            self._rf_grad, self._rf_hess = self.objective.get_gradients(tmp)

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        if self.objective is None:
            log.fatal("RF mode does not support custom objective")
        bag_indices = self._bagging(self.iter)
        grad, hess = self._rf_grad, self._rf_hess
        should_continue = False
        prev_iters = self.iter
        for k in range(self.num_tree_per_iteration):
            new_tree = Tree(2)
            if self._class_need_train[k] and self.train_set.num_features > 0:
                new_tree = self.learner.train(
                    grad[k], hess[k], bag_indices,
                    iter_seed=self.iter * self.num_tree_per_iteration + k)
            if new_tree.num_leaves > 1:
                should_continue = True
                if self.objective.is_renew_tree_output:
                    self._renew_tree_output_rf(new_tree, k)
                # running average: score = (score*t + tree)/(t+1)
                if prev_iters > 0:
                    self.score_updater.multiply(
                        prev_iters / (prev_iters + 1.0), k)
                    for vu in self.valid_updaters:
                        vu.multiply(prev_iters / (prev_iters + 1.0), k)
                new_tree.apply_shrinkage(1.0 / (prev_iters + 1.0))
                self._update_score(new_tree, k)
                new_tree.apply_shrinkage(prev_iters + 1.0)
            self.models.append(new_tree)
        if not should_continue:
            log.warning("Stopped training: no splittable leaves (RF)")
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter += 1
        return False

    def _renew_tree_output_rf(self, tree, class_id):
        init = self._rf_init_scores[class_id]
        label = np.asarray(self.train_set.label, dtype=np.float64)
        weights = self.train_set.metadata.weight
        for leaf in range(tree.num_leaves):
            rows = self.learner.leaf_rows(leaf)
            if len(rows) == 0:
                continue
            residuals = label[rows] - init
            w = weights[rows] if weights is not None else None
            tree.set_leaf_output(
                leaf, self.objective.renew_leaf_output(residuals, w))


def create_boosting(config: Config, train_set: Optional[Dataset],
                    objective=None) -> GBDT:
    """Factory (reference: src/boosting/boosting.cpp:35 CreateBoosting)."""
    name = config.boosting
    if name in ("gbdt", "gbrt", "plain"):
        return GBDT(config, train_set, objective)
    if name == "dart":
        return DART(config, train_set, objective)
    if name == "goss":
        return GOSS(config, train_set, objective)
    if name in ("rf", "random_forest"):
        return RF(config, train_set, objective)
    log.fatal("Unknown boosting type %s", name)
