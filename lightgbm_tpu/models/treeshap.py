"""TreeSHAP feature contributions.

Behavioral equivalent of the reference's per-tree SHAP recursion
(reference: src/io/tree.cpp:669-713 TreeSHAP + PredictContrib). Irregular
recursion with path bookkeeping — kept on host like the reference keeps it
out of the GPU path.
"""
from __future__ import annotations

import math
from typing import List

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth] = _PathElement(
        feature_index, zero_fraction, one_fraction,
        1.0 if unique_depth == 0 else 0.0)
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = (tmp - path[i].pweight * zero_fraction
                                * (unique_depth - i) / (unique_depth + 1))
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = (path[i].pweight - tmp * zero_fraction
                                * ((unique_depth - i) / (unique_depth + 1)))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def _tree_shap(tree, row: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int) -> None:
    path = [p.copy() for p in parent_path[:unique_depth]]
    path += [_PathElement() for _ in range(tree.num_leaves + 2 - unique_depth)]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += (w * (el.one_fraction - el.zero_fraction)
                                      * tree.leaf_value[leaf])
        return

    hot, cold = _decide_children(tree, row, node)
    w = float(tree.internal_count[node])
    hot_count = _child_count(tree, hot)
    cold_count = _child_count(tree, cold)
    hot_zero = hot_count / w if w else 0.0
    cold_zero = cold_count / w if w else 0.0
    incoming_zero = 1.0
    incoming_one = 1.0
    feat = int(tree.split_feature[node])
    path_index = 0
    while path_index <= unique_depth:
        if path[path_index].feature_index == feat:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, row, phi, hot, unique_depth + 1, path,
               hot_zero * incoming_zero, incoming_one, feat)
    _tree_shap(tree, row, phi, cold, unique_depth + 1, path,
               cold_zero * incoming_zero, 0.0, feat)


def _decide_children(tree, row, node):
    nxt = tree._decision(float(row[tree.split_feature[node]]), node)
    if nxt == tree.left_child[node]:
        return tree.left_child[node], tree.right_child[node]
    return tree.right_child[node], tree.left_child[node]


def _child_count(tree, child):
    if child < 0:
        return float(tree.leaf_count[~child])
    return float(tree.internal_count[child])


def _expected_value(tree) -> float:
    total = float(tree.leaf_count[: tree.num_leaves].sum())
    if total <= 0:
        return float(tree.leaf_value[0])
    return float(np.sum(tree.leaf_value[: tree.num_leaves]
                        * tree.leaf_count[: tree.num_leaves]) / total)


def predict_contrib(booster, x, num_iteration=None) -> np.ndarray:
    """(N, (F+1)*K) SHAP values; last column per class = expected value."""
    x = np.asarray(x, dtype=np.float64)
    if x.ndim == 1:
        x = x.reshape(1, -1)
    n, _ = x.shape
    nf = booster.max_feature_idx + 1
    k = booster.num_class
    models = booster._used_models(num_iteration)
    out = np.zeros((n, (nf + 1) * k))
    for ti, tree in enumerate(models):
        cls = ti % booster.num_tree_per_iteration
        base = cls * (nf + 1)
        if tree.num_leaves <= 1:
            out[:, base + nf] += float(tree.leaf_value[0])
            continue
        expected = _expected_value(tree)
        for i in range(n):
            phi = np.zeros(nf + 1)
            phi[nf] += expected
            init_path = [_PathElement() for _ in range(tree.num_leaves + 2)]
            _tree_shap(tree, x[i], phi, 0, 0, init_path, 1.0, 1.0, -1)
            out[i, base:base + nf + 1] += phi
    return out
