"""Non-finite sentries + loss-spike recovery.

Iteration boundaries are the natural consistency points of distributed
GBDT (one allreduce per histogram round, arXiv:1806.11248), so guards
live there: one fused device reduction over the iteration's operands —
gradients/hessians on the generic path, the updated score row on the
fused path (any non-finite gradient or leaf output propagates into it)
— and a host-side policy dispatch. The reduction is a single jitted
`all(isfinite)` lane; per-iteration overhead is the budget to defend
(arXiv:1809.04559), measured by tools/chaos_bench.py.

Policies (`on_nonfinite` parameter, dispatched in models/gbdt.py):

* ``raise``      — stop with NonFiniteError naming the iteration.
* ``skip_iter``  — drop the iteration (no tree, no score change); the
                   iteration counter advances so seeds keep moving.
* ``rollback``   — undo the previous iteration (whose tree corrupted the
                   scores, or simply re-establish a known-good state),
                   recompute gradients once, and continue; a second
                   consecutive failure raises.

The loss-spike detector is a callback: if the training metric worsens by
more than `threshold` (relative), the last iteration is rolled back and
the learning rate optionally cut — the boosting-level analog of gradient
clipping.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from ..utils import log
from ..utils.log import LightGBMError

__all__ = ["NonFiniteError", "all_finite", "loss_spike_guard", "POLICIES"]

POLICIES = ("off", "raise", "skip_iter", "rollback")


class NonFiniteError(LightGBMError):
    """Non-finite values detected in a guarded training step."""


_FINITE_FNS: Dict[int, Callable] = {}


def _finite_fn(arity: int):
    fn = _FINITE_FNS.get(arity)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def impl(*arrays):
            flag = jnp.all(jnp.isfinite(arrays[0]))
            for a in arrays[1:]:
                flag &= jnp.all(jnp.isfinite(a))
            return flag
        fn = jax.jit(impl)
        _FINITE_FNS[arity] = fn
    return fn


def all_finite(*arrays) -> bool:
    """ONE fused device reduction over any number of arrays; the bool()
    is the only host sync and rides the iteration's existing record
    fetch cadence."""
    return bool(_finite_fn(len(arrays))(*arrays))


def loss_spike_guard(threshold: float = 2.0, lr_cut: float = 1.0,
                     verbose: bool = True) -> Callable:
    """Callback: watch the training metric; on a relative worsening
    > `threshold` (or a non-finite value), roll back the iteration and
    multiply the learning rate by `lr_cut` (1.0 = keep it).

    Runs at order 22 — after record_evaluation, before early stopping —
    so a rolled-back spike cannot trip the early-stopping counters of
    later, healthier iterations.
    """
    if threshold <= 0:
        raise ValueError("threshold must be positive")
    if not (0.0 < lr_cut <= 1.0):
        raise ValueError("lr_cut must be in (0, 1]")
    state = {"prev": None, "rollbacks": 0}

    def _train_entry(env):
        train_name = getattr(env.model, "_train_data_name", "training")
        for item in env.evaluation_result_list or []:
            if item[0] in (train_name, "training"):
                return float(item[2]), bool(item[3])
        return None

    def _callback(env) -> None:
        import math
        entry = _train_entry(env)
        if entry is None:
            return
        val, higher_better = entry
        prev = state["prev"]
        if prev is None or not math.isfinite(prev):
            state["prev"] = val
            return
        denom = max(abs(prev), 1e-12)
        worsening = ((prev - val) if higher_better else (val - prev)) / denom
        if math.isfinite(val) and worsening <= threshold:
            state["prev"] = val
            return
        state["rollbacks"] += 1
        if verbose:
            log.warning(
                "loss spike at iteration %d (train metric %g -> %g): "
                "rolling back", env.iteration + 1, prev, val)
        from ..telemetry import events as telem_events
        telem_events.emit("rollback", iteration=env.iteration,
                          reason="loss_spike", prev=prev, value=val)
        env.model.rollback_one_iter()
        if lr_cut < 1.0 and hasattr(env.model, "reset_parameter"):
            cur = float(env.params.get("learning_rate", 0.1))
            new_lr = cur * lr_cut
            env.model.reset_parameter({"learning_rate": new_lr})
            env.params["learning_rate"] = new_lr
            if verbose:
                log.warning("loss spike: learning_rate cut %g -> %g",
                            cur, new_lr)
        # prev stays at the pre-spike value: the retrained iteration is
        # judged against the last healthy state
    _callback.order = 22
    _callback._spike_state = state
    return _callback
