"""Deterministic fault injection + transient-collective retry.

Production training has failure modes the happy path never exercises:
corrupted gradients out of a flaky objective/transport, collectives that
time out mid-allreduce, a predictor that stalls long enough to blow
request deadlines. This layer makes every one of them *reproducible* so
the guards (resilience/sentries.py), checkpoints (resilience/
checkpoint.py) and the serving batcher's timeout path can be tested
deterministically — the same role chaos harnesses play around the
reference's distributed learners (the socket linkers' retry loops,
linkers_socket.cpp), but seedable and in-process.

Fault spec grammar (env ``LGBM_TPU_FAULT_SPEC`` or ``faults.install``):

    clause[;clause...]

    nan_grad@iter=7[,frac=0.01]     poison `frac` of the gradient lanes
                                    with NaN at boosting iteration 7
                                    (one-shot: fires at most once)
    inf_grad@iter=7[,frac=0.01]     same with +inf
    nan_grad@p=0.05                 poison with probability p each
                                    iteration (seeded)
    fail_collective@n=2             fail the first 2 collective calls
                                    with TransientCollectiveError, then
                                    heal (exercises the retry path)
    fail_collective@p=0.1           fail each collective call with
                                    probability p (seeded)
    delay_ms=50                     sleep 50 ms at every fault site
                                    (collectives + serving flush)
    seed=123                        RNG seed for probabilistic clauses

Hook sites: ``GBDT._compute_gradients`` (gradient boundary), the host
parallel learners' sharded histogram/partition dispatches and
``network.init_from_params`` (collective boundary, wrapped in
``run_collective`` with bounded exponential backoff), and the serving
batcher's flush (``sleep_point``). All hooks are no-ops costing one
attribute read when no plan is installed.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..telemetry import recorder as telem
from ..utils import log

__all__ = ["TransientCollectiveError", "FaultPlan", "install", "clear",
           "active_plan", "run_collective", "sleep_point"]

_GLOBAL_KNOBS = ("seed", "delay_ms")
_KNOWN = ("nan_grad", "inf_grad", "fail_collective")


class TransientCollectiveError(RuntimeError):
    """A collective failed in a way worth retrying (injected here; the
    real-world analogs are preempted hosts and dropped DCN links)."""


class _Clause:
    __slots__ = ("name", "args", "fired")

    def __init__(self, name: str, args: Dict[str, str]):
        self.name = name
        self.args = args
        self.fired = False

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"_Clause({self.name}, {self.args}, fired={self.fired})"


def parse_spec(spec: str):
    """-> (clauses, seed, delay_ms). Raises ValueError on bad grammar."""
    clauses: List[_Clause] = []
    seed, delay_ms = 0, 0.0
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" in part:
            name, _, argstr = part.partition("@")
            name = name.strip()
            args = {}
            for kv in argstr.split(","):
                if not kv.strip():
                    continue
                if "=" not in kv:
                    raise ValueError(f"bad fault arg {kv!r} in {part!r}")
                k, _, v = kv.partition("=")
                args[k.strip()] = v.strip()
            if name not in _KNOWN:
                raise ValueError(f"unknown fault {name!r}")
            clauses.append(_Clause(name, args))
        elif "=" in part:
            k, _, v = part.partition("=")
            k = k.strip()
            if k == "seed":
                seed = int(v)
            elif k == "delay_ms":
                delay_ms = float(v)
            else:
                raise ValueError(f"unknown fault knob {k!r}")
        else:
            raise ValueError(f"bad fault clause {part!r}")
    return clauses, seed, delay_ms


class FaultPlan:
    """A parsed spec plus the seeded RNG and per-site call counters.

    One plan instance persists across the run so one-shot clauses fire
    exactly once and `n=`-bounded clauses count globally.
    """

    def __init__(self, spec: str, seed: Optional[int] = None):
        self.spec = spec
        self.clauses, spec_seed, self.delay_ms = parse_spec(spec)
        self.seed = spec_seed if seed is None else int(seed)
        self.rng = np.random.RandomState(self.seed % (2 ** 31 - 1))
        self.collective_calls = 0
        self.events: List[str] = []     # fired faults, for tests/forensics

    @property
    def has_gradient_faults(self) -> bool:
        """True when the plan poisons gradients. The fused device step
        computes gradients in-program where the host cannot reach them,
        so GBDT drops to the generic path while such a plan is active —
        the harness tests the guards, not the fused fast path."""
        return any(c.name in ("nan_grad", "inf_grad") for c in self.clauses)

    # -- gradient boundary ---------------------------------------------
    def inject_gradients(self, grad, hess, iteration: int):
        """Possibly poison (grad, hess) for this boosting iteration.
        Arrays are device (K, N) jax arrays; the poison path round-trips
        through host — it only runs when a fault actually fires."""
        for c in self.clauses:
            if c.name not in ("nan_grad", "inf_grad"):
                continue
            if "iter" in c.args:
                if c.fired or iteration != int(c.args["iter"]):
                    continue
            elif "p" in c.args:
                if self.rng.rand() >= float(c.args["p"]):
                    continue
            else:
                continue
            c.fired = True
            frac = float(c.args.get("frac", 0.01))
            val = np.inf if c.name == "inf_grad" else np.nan
            grad = self._poison(grad, frac, val)
            self.events.append(f"{c.name}@iter={iteration}")
            telem_events.emit("fault", fault=c.name, iteration=iteration,
                              frac=frac)
            log.warning("fault injection: %s at iteration %d (frac=%g)",
                        c.name, iteration, frac)
        return grad, hess

    def _poison(self, grad, frac: float, val: float):
        import jax
        import jax.numpy as jnp
        g = np.array(jax.device_get(grad))
        n = g.shape[-1]
        k = max(1, int(n * frac))
        rows = self.rng.choice(n, k, replace=False)
        g[..., rows] = val
        return jnp.asarray(g)

    # -- collective / serving boundaries --------------------------------
    def before_collective(self, site: str) -> None:
        """Called before each collective dispatch: may sleep, may raise
        TransientCollectiveError."""
        self.maybe_delay(site)
        call_n = self.collective_calls
        self.collective_calls += 1
        for c in self.clauses:
            if c.name != "fail_collective":
                continue
            if "n" in c.args:
                if call_n >= int(c.args["n"]):
                    continue
            elif "p" in c.args:
                if self.rng.rand() >= float(c.args["p"]):
                    continue
            else:
                continue
            self.events.append(f"fail_collective@{site}#{call_n}")
            telem_events.emit("fault", fault="fail_collective", site=site,
                              call=call_n)
            raise TransientCollectiveError(
                f"injected collective failure at {site} (call {call_n})")

    def maybe_delay(self, site: str) -> None:
        if self.delay_ms > 0:
            self.events.append(f"delay@{site}")
            time.sleep(self.delay_ms / 1e3)


# -- global plan -------------------------------------------------------
_plan: Optional[FaultPlan] = None
_env_plan: Optional[FaultPlan] = None
_env_spec: Optional[str] = None


def install(spec: Optional[str], seed: Optional[int] = None
            ) -> Optional[FaultPlan]:
    """Install a process-wide fault plan (None/'' clears). Returns it."""
    global _plan
    _plan = FaultPlan(spec, seed) if spec else None
    return _plan


def clear() -> None:
    install(None)


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one parsed (once) from
    LGBM_TPU_FAULT_SPEC, else None."""
    global _env_plan, _env_spec
    if _plan is not None:
        return _plan
    spec = os.environ.get("LGBM_TPU_FAULT_SPEC", "")
    if not spec:
        return None
    if spec != _env_spec:
        _env_spec = spec
        _env_plan = FaultPlan(spec)
    return _env_plan


def sleep_point(site: str) -> None:
    """Pure-delay fault site (serving flush, eval loops)."""
    plan = active_plan()
    if plan is not None:
        plan.maybe_delay(site)


def _retry_budget():
    return (int(os.environ.get("LGBM_TPU_COLLECTIVE_RETRIES", 3)),
            float(os.environ.get("LGBM_TPU_RETRY_BASE_MS", 10.0)) / 1e3)


def run_collective(fn, site: str = "collective",
                   retries: Optional[int] = None,
                   base_delay_s: Optional[float] = None):
    """Dispatch a host-side collective call with bounded exponential-
    backoff retry on TransientCollectiveError. With no active plan this
    is a plain call — zero overhead on the clean path. Retrying re-runs
    the same jitted program, which is side-effect-free, so a retry is
    always consistent."""
    # dispatch count is forensic ground truth either way (low-frequency:
    # bootstrap, barriers, ingest — never per-split), so it does not
    # gate on an active plan or on telemetry mode
    telem_counters.incr("collective_dispatches")
    plan = active_plan()
    if plan is None:
        # clean path: one recorder-gate read (a no-op context manager
        # while telemetry is off) on top of the plain call
        with telem.phase("collective"):
            return fn()
    env_retries, env_base = _retry_budget()
    budget = env_retries if retries is None else int(retries)
    delay = env_base if base_delay_s is None else float(base_delay_s)
    attempt = 0
    while True:
        try:
            plan.before_collective(site)
            with telem.phase("collective"):
                return fn()
        except TransientCollectiveError as exc:
            attempt += 1
            telem_counters.incr("collective_retries")
            if attempt > budget:
                telem_counters.incr("collective_failures")
                log.warning("collective %s failed after %d retries", site,
                            budget)
                raise
            log.warning("transient failure at %s (attempt %d/%d): %s; "
                        "retrying in %.0f ms", site, attempt, budget, exc,
                        delay * 1e3)
            time.sleep(delay)
            delay = min(delay * 2.0, 1.0)
