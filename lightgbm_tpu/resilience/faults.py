"""Deterministic fault injection + transient-collective retry.

Production training has failure modes the happy path never exercises:
corrupted gradients out of a flaky objective/transport, collectives that
time out mid-allreduce, a predictor that stalls long enough to blow
request deadlines. This layer makes every one of them *reproducible* so
the guards (resilience/sentries.py), checkpoints (resilience/
checkpoint.py) and the serving batcher's timeout path can be tested
deterministically — the same role chaos harnesses play around the
reference's distributed learners (the socket linkers' retry loops,
linkers_socket.cpp), but seedable and in-process.

Fault spec grammar (env ``LGBM_TPU_FAULT_SPEC`` or ``faults.install``):

    clause[;clause...]

    nan_grad@iter=7[,frac=0.01]     poison `frac` of the gradient lanes
                                    with NaN at boosting iteration 7
                                    (one-shot: fires at most once)
    inf_grad@iter=7[,frac=0.01]     same with +inf
    nan_grad@p=0.05                 poison with probability p each
                                    iteration (seeded)
    fail_collective@n=2             fail the first 2 collective calls
                                    with TransientCollectiveError, then
                                    heal (exercises the retry path)
    fail_collective@p=0.1           fail each collective call with
                                    probability p (seeded)
    kill_rank@iter=3[,code=137]     hard-exit THIS process (os._exit)
                                    at boosting iteration 3 — the chaos
                                    verb behind the two-process kill
                                    harness (install the spec only in
                                    the victim rank's environment)
    preempt@iter=3                  arm the graceful-preemption flag
                                    (resilience/preempt.py) at boosting
                                    iteration 3 — deterministic stand-in
                                    for a SIGTERM eviction notice: the
                                    loop checkpoints and exits 76
    fail_request@version=v2,n=5     fail the first 5 serving batches
                                    answered by model version v2 (omit
                                    version= to hit all versions; p=
                                    for probabilistic) — the router-
                                    chaos verb driving canary demotion
    delay_ms=50                     sleep 50 ms at every fault site
                                    (collectives + serving flush)
    seed=123                        RNG seed for probabilistic clauses

Hook sites: ``GBDT._compute_gradients`` (gradient boundary), the host
parallel learners' sharded histogram/partition dispatches and
``network.init_from_params`` (collective boundary, wrapped in
``run_collective`` with bounded exponential backoff), and the serving
batcher's flush (``sleep_point``). All hooks are no-ops costing one
attribute read when no plan is installed.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import bundle as telem_bundle
from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..telemetry import recorder as telem
from ..utils import log

__all__ = ["TransientCollectiveError", "CollectiveTimeout",
           "EpochDesyncError", "FaultPlan",
           "install", "clear", "active_plan", "run_collective",
           "sleep_point", "kill_point", "request_point", "jittered_delay",
           "set_collective_timeout_ms", "collective_timeout_ms",
           "set_epoch", "current_epoch", "iteration_fence", "fence_active"]

_GLOBAL_KNOBS = ("seed", "delay_ms")
_KNOWN = ("nan_grad", "inf_grad", "fail_collective", "kill_rank",
          "fail_request", "preempt")


class TransientCollectiveError(RuntimeError):
    """A collective failed in a way worth retrying (injected here; the
    real-world analogs are preempted hosts and dropped DCN links)."""


class EpochDesyncError(RuntimeError):
    """Two ranks met inside a collective while on DIFFERENT boosting
    iterations. Exchanging payloads across an epoch skew silently mixes
    stale histograms into a fresh iteration — this typed error (both
    epochs named) is raised by the wire framing in io/distributed.py
    instead. Not transient: a desync means the retry/rollback choreo-
    graphy itself diverged, so blind retry would re-fail identically."""

    def __init__(self, local_epoch: int, remote_epoch: int, rank: int):
        self.local_epoch = int(local_epoch)
        self.remote_epoch = int(remote_epoch)
        self.rank = int(rank)
        super().__init__(
            f"collective epoch desync: local iteration epoch "
            f"{self.local_epoch} but rank {self.rank} sent epoch "
            f"{self.remote_epoch}")


class CollectiveTimeout(RuntimeError):
    """A collective dispatch exceeded its deadline
    (``dist_collective_timeout_ms``). Deliberately NOT a
    TransientCollectiveError: a deadline miss means a peer is likely
    dead or wedged, and re-entering the same collective would block the
    survivor again — the caller must consult the supervision layer
    (distributed/supervisor.py) instead of retrying blindly."""


class _Clause:
    __slots__ = ("name", "args", "fired")

    def __init__(self, name: str, args: Dict[str, str]):
        self.name = name
        self.args = args
        self.fired = False

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"_Clause({self.name}, {self.args}, fired={self.fired})"


def parse_spec(spec: str):
    """-> (clauses, seed, delay_ms). Raises ValueError on bad grammar."""
    clauses: List[_Clause] = []
    seed, delay_ms = 0, 0.0
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" in part:
            name, _, argstr = part.partition("@")
            name = name.strip()
            args = {}
            for kv in argstr.split(","):
                if not kv.strip():
                    continue
                if "=" not in kv:
                    raise ValueError(f"bad fault arg {kv!r} in {part!r}")
                k, _, v = kv.partition("=")
                args[k.strip()] = v.strip()
            if name not in _KNOWN:
                raise ValueError(f"unknown fault {name!r}")
            clauses.append(_Clause(name, args))
        elif "=" in part:
            k, _, v = part.partition("=")
            k = k.strip()
            if k == "seed":
                seed = int(v)
            elif k == "delay_ms":
                delay_ms = float(v)
            else:
                raise ValueError(f"unknown fault knob {k!r}")
        else:
            raise ValueError(f"bad fault clause {part!r}")
    return clauses, seed, delay_ms


class FaultPlan:
    """A parsed spec plus the seeded RNG and per-site call counters.

    One plan instance persists across the run so one-shot clauses fire
    exactly once and `n=`-bounded clauses count globally.
    """

    def __init__(self, spec: str, seed: Optional[int] = None):
        self.spec = spec
        self.clauses, spec_seed, self.delay_ms = parse_spec(spec)
        self.seed = spec_seed if seed is None else int(seed)
        self.rng = np.random.RandomState(self.seed % (2 ** 31 - 1))
        self.collective_calls = 0
        self._request_fail_counts: Dict[int, int] = {}
        self.events: List[str] = []     # fired faults, for tests/forensics

    @property
    def has_gradient_faults(self) -> bool:
        """True when the plan poisons gradients. The fused device step
        computes gradients in-program where the host cannot reach them,
        so GBDT drops to the generic path while such a plan is active —
        the harness tests the guards, not the fused fast path."""
        return any(c.name in ("nan_grad", "inf_grad") for c in self.clauses)

    # -- gradient boundary ---------------------------------------------
    def inject_gradients(self, grad, hess, iteration: int):
        """Possibly poison (grad, hess) for this boosting iteration.
        Arrays are device (K, N) jax arrays; the poison path round-trips
        through host — it only runs when a fault actually fires."""
        for c in self.clauses:
            if c.name not in ("nan_grad", "inf_grad"):
                continue
            if "iter" in c.args:
                if c.fired or iteration != int(c.args["iter"]):
                    continue
            elif "p" in c.args:
                if self.rng.rand() >= float(c.args["p"]):
                    continue
            else:
                continue
            c.fired = True
            frac = float(c.args.get("frac", 0.01))
            val = np.inf if c.name == "inf_grad" else np.nan
            grad = self._poison(grad, frac, val)
            self.events.append(f"{c.name}@iter={iteration}")
            telem_events.emit("fault", fault=c.name, iteration=iteration,
                              frac=frac)
            log.warning("fault injection: %s at iteration %d (frac=%g)",
                        c.name, iteration, frac)
        return grad, hess

    def _poison(self, grad, frac: float, val: float):
        import jax
        import jax.numpy as jnp
        g = np.array(jax.device_get(grad))
        n = g.shape[-1]
        k = max(1, int(n * frac))
        rows = self.rng.choice(n, k, replace=False)
        g[..., rows] = val
        return jnp.asarray(g)

    # -- collective / serving boundaries --------------------------------
    def before_collective(self, site: str) -> None:
        """Called before each collective dispatch: may sleep, may raise
        TransientCollectiveError."""
        self.maybe_delay(site)
        call_n = self.collective_calls
        self.collective_calls += 1
        for c in self.clauses:
            if c.name != "fail_collective":
                continue
            if "n" in c.args:
                if call_n >= int(c.args["n"]):
                    continue
            elif "p" in c.args:
                if self.rng.rand() >= float(c.args["p"]):
                    continue
            else:
                continue
            self.events.append(f"fail_collective@{site}#{call_n}")
            telem_events.emit("fault", fault="fail_collective", site=site,
                              call=call_n)
            raise TransientCollectiveError(
                f"injected collective failure at {site} (call {call_n})")

    def maybe_delay(self, site: str) -> None:
        if self.delay_ms > 0:
            self.events.append(f"delay@{site}")
            time.sleep(self.delay_ms / 1e3)

    def before_request(self, version: str) -> None:
        """Called by the serving batcher before executing a batch for
        `version`: may raise to fail every request in that batch — the
        deterministic error spike the canary demotion gate watches for."""
        for idx, c in enumerate(self.clauses):
            if c.name != "fail_request":
                continue
            want = c.args.get("version")
            if want and want != str(version):
                continue
            if "n" in c.args:
                fired = self._request_fail_counts.get(idx, 0)
                if fired >= int(c.args["n"]):
                    continue
                self._request_fail_counts[idx] = fired + 1
            elif "p" in c.args:
                if self.rng.rand() >= float(c.args["p"]):
                    continue
            # bare fail_request@version=v: fail every matching batch
            self.events.append(f"fail_request@{version}")
            telem_events.emit("fault", fault="fail_request",
                              version=str(version))
            raise RuntimeError(
                f"injected request failure for version {version}")

    # -- process-death boundary -----------------------------------------
    def kill_code(self, iteration: int) -> Optional[int]:
        """Exit code to die with at this boosting iteration, or None.
        Pure decision logic so tests can pin it without dying; the
        actual os._exit lives in module-level `kill_point`."""
        for c in self.clauses:
            if c.name != "kill_rank" or c.fired:
                continue
            if "iter" not in c.args or iteration != int(c.args["iter"]):
                continue
            c.fired = True
            self.events.append(f"kill_rank@iter={iteration}")
            return int(c.args.get("code", 137))
        return None

    def preempt_at(self, iteration: int) -> bool:
        """True when a ``preempt@iter=`` clause fires at this boosting
        iteration (one-shot). Pure decision logic; arming the actual
        flag (resilience/preempt.py) happens in `kill_point`."""
        for c in self.clauses:
            if c.name != "preempt" or c.fired:
                continue
            if "iter" not in c.args or iteration != int(c.args["iter"]):
                continue
            c.fired = True
            self.events.append(f"preempt@iter={iteration}")
            return True
        return False


# -- global plan -------------------------------------------------------
_plan: Optional[FaultPlan] = None
_env_plan: Optional[FaultPlan] = None
_env_spec: Optional[str] = None


def install(spec: Optional[str], seed: Optional[int] = None
            ) -> Optional[FaultPlan]:
    """Install a process-wide fault plan (None/'' clears). Returns it."""
    global _plan
    _plan = FaultPlan(spec, seed) if spec else None
    return _plan


def clear() -> None:
    install(None)


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one parsed (once) from
    LGBM_TPU_FAULT_SPEC, else None."""
    global _env_plan, _env_spec
    if _plan is not None:
        return _plan
    spec = os.environ.get("LGBM_TPU_FAULT_SPEC", "")
    if not spec:
        return None
    if spec != _env_spec:
        _env_spec = spec
        _env_plan = FaultPlan(spec)
    return _env_plan


def sleep_point(site: str) -> None:
    """Pure-delay fault site (serving flush, eval loops)."""
    plan = active_plan()
    if plan is not None:
        plan.maybe_delay(site)


def request_point(version: str) -> None:
    """Request-failure fault site (`fail_request@` clauses); the serving
    batcher calls this with the resolved model version per flush."""
    plan = active_plan()
    if plan is not None:
        plan.before_request(version)


def kill_point(iteration: int) -> None:
    """Process-death fault site (`kill_rank@iter=` clauses). The engine
    loop calls this at the top of each boosting iteration; the victim
    dies with os._exit so no atexit/teardown runs — exactly how a
    preempted or OOM-killed rank disappears."""
    plan = active_plan()
    if plan is None:
        return
    if plan.preempt_at(iteration):
        # deterministic eviction notice: same flag, same downstream
        # path (checkpoint + exit 76) as a real SIGTERM
        from . import preempt
        telem_events.emit("fault", fault="preempt", iteration=iteration)
        preempt.arm(f"fault:preempt@iter={iteration}")
    code = plan.kill_code(iteration)
    if code is not None:
        telem_events.emit("fault", fault="kill_rank", iteration=iteration,
                          code=code)
        telem_events.flush()
        # the victim's last act: freeze its world before os._exit skips
        # every destructor (LGBM_TPU_BUNDLE_DIR unset = no-op)
        telem_bundle.maybe_capture("kill_rank", iteration=iteration,
                                   exit_code=code)
        log.warning("fault injection: kill_rank at iteration %d "
                    "(os._exit(%d))", iteration, code)
        os._exit(code)


def _retry_budget():
    return (int(os.environ.get("LGBM_TPU_COLLECTIVE_RETRIES", 3)),
            float(os.environ.get("LGBM_TPU_RETRY_BASE_MS", 10.0)) / 1e3)


# -- iteration epoch + fence --------------------------------------------
# The boosting loop stamps the current iteration here; the wire framing
# (io/distributed.py _allgather_host_bytes) carries it in every payload
# header so ranks meeting inside a collective can verify they are on the
# SAME iteration (EpochDesyncError otherwise). -1 = outside any loop
# (bootstrap, ingest, resume) — still exchanged and still compared:
# lockstep ranks agree on -1 exactly like they agree on an iteration.
_epoch = -1
_fence_depth = 0


def set_epoch(n: int) -> None:
    """Stamp the iteration-epoch sequence number (engine/cli loops)."""
    global _epoch
    _epoch = int(n)


def current_epoch() -> int:
    return _epoch


class iteration_fence:
    """Context manager marking "this code runs inside one boosting
    iteration whose caller can retry the WHOLE iteration from captured
    pre-iteration state". While active, ``run_collective`` re-raises
    TransientCollectiveError immediately instead of retrying the single
    dispatch blind — a mid-iteration transient leaves partially-applied
    per-dispatch state (histogram shards on some ranks, not others), so
    the iteration-level rollback (scores + RNG, PR 4) is the only retry
    that is actually consistent."""

    def __enter__(self):
        global _fence_depth
        _fence_depth += 1
        return self

    def __exit__(self, *exc):
        global _fence_depth
        _fence_depth -= 1
        return False


def fence_active() -> bool:
    return _fence_depth > 0


def jittered_delay(delay_s: float, rng) -> float:
    """Uniform jitter in [delay/2, delay): simultaneous retriers across
    a fleet decorrelate instead of re-colliding every backoff step
    (full backoff growth is preserved — only the sleep is jittered)."""
    return float(delay_s) * (0.5 + 0.5 * float(rng.rand()))


# -- collective deadline ------------------------------------------------
# Set from Config.dist_collective_timeout_ms by the distributed
# supervisor (or the env var below). 0 = off, which is the single-
# process default: the deadline thread costs a dispatch per collective,
# so it is strictly opt-in.
_timeout_override: Optional[float] = None


def set_collective_timeout_ms(ms: Optional[float]) -> None:
    """Install a process-wide collective deadline (None re-reads env)."""
    global _timeout_override
    _timeout_override = None if ms is None else float(ms)


def collective_timeout_ms() -> float:
    if _timeout_override is not None:
        return _timeout_override
    try:
        return float(os.environ.get("LGBM_TPU_COLLECTIVE_TIMEOUT_MS", 0))
    except ValueError:
        return 0.0


def _call_with_deadline(fn, site: str, timeout_ms: float):
    """Dispatch fn on a watchdog-timed worker thread. On deadline the
    worker is abandoned (it is blocked inside a dead collective; the
    caller is about to tear the process group down anyway) and a typed
    CollectiveTimeout is raised instead of hanging forever."""
    done = threading.Event()
    box: Dict[str, object] = {}

    def _runner():
        try:
            box["result"] = fn()
        except BaseException as exc:   # noqa: BLE001 — marshalled below
            box["error"] = exc
        finally:
            done.set()

    t = threading.Thread(target=_runner, daemon=True,
                         name=f"lgbm-tpu-collective-{site}")
    t.start()
    if not done.wait(timeout_ms / 1e3):
        telem_counters.incr("collective_timeouts")
        telem_events.emit("collective_timeout", site=site,
                          timeout_ms=timeout_ms)
        telem_bundle.maybe_capture("collective_timeout", site=site,
                                   timeout_ms=timeout_ms)
        log.warning("collective %s exceeded its %.0f ms deadline", site,
                    timeout_ms)
        raise CollectiveTimeout(
            f"collective {site} exceeded {timeout_ms:.0f} ms deadline")
    err = box.get("error")
    if err is not None:
        raise err
    return box.get("result")


def run_collective(fn, site: str = "collective",
                   retries: Optional[int] = None,
                   base_delay_s: Optional[float] = None):
    """Dispatch a host-side collective call with bounded exponential-
    backoff retry (jittered) on TransientCollectiveError, under the
    optional process-wide deadline (dist_collective_timeout_ms — a
    deadline miss raises CollectiveTimeout, which is NOT retried here).
    With no active plan and no deadline this is a plain call — zero
    overhead on the clean path. Retrying re-runs the same jitted
    program, which is side-effect-free, so a retry is always
    consistent."""
    # dispatch count is forensic ground truth either way (low-frequency:
    # bootstrap, barriers, ingest — never per-split), so it does not
    # gate on an active plan or on telemetry mode
    telem_counters.incr("collective_dispatches")
    deadline_ms = collective_timeout_ms()
    plan = active_plan()
    if plan is None:
        # clean path: one recorder-gate read (a no-op context manager
        # while telemetry is off) on top of the plain call
        with telem.phase("collective"):
            if deadline_ms > 0:
                return _call_with_deadline(fn, site, deadline_ms)
            return fn()
    env_retries, env_base = _retry_budget()
    budget = env_retries if retries is None else int(retries)
    delay = env_base if base_delay_s is None else float(base_delay_s)
    attempt = 0
    while True:
        try:
            plan.before_collective(site)
            with telem.phase("collective"):
                if deadline_ms > 0:
                    return _call_with_deadline(fn, site, deadline_ms)
                return fn()
        except TransientCollectiveError as exc:
            if _fence_depth > 0:
                # epoch-fenced mode: the engine retries the iteration
                # from its captured pre-iteration state; retrying the
                # single dispatch here would race that rollback
                log.warning("transient failure at %s under an iteration "
                            "fence: aborting the iteration for "
                            "epoch-level retry (%s)", site, exc)
                raise
            attempt += 1
            telem_counters.incr("collective_retries")
            if attempt > budget:
                telem_counters.incr("collective_failures")
                log.warning("collective %s failed after %d retries", site,
                            budget)
                raise
            sleep_s = jittered_delay(delay, plan.rng)
            log.warning("transient failure at %s (attempt %d/%d): %s; "
                        "retrying in %.0f ms", site, attempt, budget, exc,
                        sleep_s * 1e3)
            time.sleep(sleep_s)
            delay = min(delay * 2.0, 1.0)
