"""Graceful preemption: SIGTERM/SIGINT -> checkpoint -> exit 76.

Cloud TPU fleets evict hosts with a SIGTERM and a short grace window.
The stock outcome is the worst one: training dies mid-iteration and the
run restarts from whatever the last *periodic* checkpoint captured.
This module turns the notice into a clean, resumable exit:

* ``install_handlers`` arms SIGTERM/SIGINT to set a process-wide flag —
  nothing else happens in signal context (the handler is async-signal
  constrained; all real work runs at the next iteration boundary).
* The training loops (engine.train, cli._train) poll the flag at the
  same per-iteration site as ``faults.kill_point``/``sup.check``. When
  set, they write an *emergency checkpoint* through the ordinary
  rank-0 ``DistributedCheckpointManager`` path (atomic file + checksum
  + barrier) and exit with ``PREEMPT_EXIT_CODE`` (76) — a documented,
  launcher-visible contract: 76 means "checkpointed cleanly, relaunch
  with resume=auto" (docs/Reliability.md).
* Distributed, the flag is propagated over the existing
  ``_allgather_host_bytes`` lane (one byte per rank per iteration) so
  every rank checkpoints at the SAME iteration boundary even when only
  one host received the eviction notice. The vote is strictly opt-in
  (handlers installed, or ``LGBM_TPU_PREEMPT_SYNC=1``) and must be
  armed symmetrically on every rank — it is itself a collective.
* The fault verb ``preempt@iter=N`` (resilience/faults.py) arms the
  flag deterministically for tests, through the same code path a real
  SIGTERM takes.

The emergency checkpoint records the run's original round target
(``target_rounds`` in the manifest) so ``resume=auto`` finishes the
right budget without the operator restating it.
"""
from __future__ import annotations

import os
import signal
import threading

from ..telemetry import counters as telem_counters
from ..telemetry import events as telem_events
from ..utils import log

__all__ = ["PREEMPT_EXIT_CODE", "install_handlers", "arm", "requested",
           "reason", "clear", "sync_enabled", "resolve_group_sync",
           "group_requested"]

# exit-code contract (documented in docs/Reliability.md): the process
# wrote a durable emergency checkpoint and can be resumed bit-identically
# with resume=auto. Chosen clear of the shell (126/127/128+n) and
# sysexits ranges actually emitted by this stack.
PREEMPT_EXIT_CODE = 76

_requested = threading.Event()
_installed = False
_reason = ""
# group decision on the per-iteration vote: None until a training loop
# resolves it collectively (resolve_group_sync); then True/False is THE
# answer on every rank for that loop's lifetime
_group_sync = None


def _on_signal(signum, frame) -> None:   # pragma: no cover - signal ctx
    # async-signal context: set the flag, nothing else. The iteration
    # boundary does the checkpointing with a full Python stack.
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    arm(f"signal:{name}")


def install_handlers() -> bool:
    """Arm SIGTERM/SIGINT to request a graceful preemption. Idempotent;
    returns False (and stays un-armed) off the main thread, where
    CPython refuses signal.signal. ``LGBM_TPU_NO_SIGNAL_HANDLERS=1``
    disables installation entirely: a harness that owns the process's
    signal disposition (pytest under a watchdog timeout, notebook
    kernels) must keep it — a swallowed harness SIGTERM would otherwise
    arm the flag and turn every later train() in the process into an
    exit-76."""
    global _installed
    if os.environ.get("LGBM_TPU_NO_SIGNAL_HANDLERS", "") == "1":
        return False
    if _installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
    except ValueError:   # pragma: no cover - non-main interpreter thread
        return False
    _installed = True
    return True


def arm(why: str = "requested") -> None:
    """Set the preemption flag (signal handler, fault verb, or tests).
    First arm wins; re-arming is a no-op."""
    global _reason
    if _requested.is_set():
        return
    _reason = str(why)
    _requested.set()
    telem_counters.incr("preempts")
    telem_events.emit("preempt", phase="armed", reason=_reason)
    log.warning("preemption requested (%s): will checkpoint and exit %d "
                "at the next iteration boundary", _reason,
                PREEMPT_EXIT_CODE)


def requested() -> bool:
    """Local flag only — no collective. One Event read."""
    return _requested.is_set()


def reason() -> str:
    return _reason


def clear() -> None:
    """Reset the flag (tests; a resumed process starts clean anyway)."""
    global _reason, _group_sync
    _requested.clear()
    _reason = ""
    _group_sync = None


def sync_enabled() -> bool:
    """This process's LOCAL arming of the per-iteration preempt vote:
    True when it installed signal handlers or ``LGBM_TPU_PREEMPT_SYNC=1``.
    The vote itself is a collective, so the group decision is made by
    ``resolve_group_sync`` (an allgather at training-loop entry), never
    from this value alone — ``install_handlers`` silently declines off
    the main thread, so local arming can be asymmetric across ranks."""
    return _installed or os.environ.get("LGBM_TPU_PREEMPT_SYNC", "") == "1"


def resolve_group_sync() -> bool:
    """Agree ONCE, collectively, on whether the per-iteration preempt
    vote runs — called at training-loop entry (engine.train,
    cli._boost_loop), a point every rank reaches together.

    Each rank contributes its local ``sync_enabled()`` byte; the vote is
    enabled only when EVERY rank is armed. On a mismatch (one rank's
    ``install_handlers`` declined off the main thread, an env var set on
    some hosts only) the vote is disabled everywhere with a loud warning
    instead of the armed ranks blocking in the per-iteration allgather
    until CollectiveTimeout. Single-process (or not distributed) the
    local value IS the decision."""
    global _group_sync
    from ..distributed import bootstrap
    local = sync_enabled()
    if not bootstrap.is_distributed():
        _group_sync = local
        return _group_sync
    from ..io.distributed import _allgather_host_bytes
    votes = _allgather_host_bytes(b"\x01" if local else b"\x00")
    armed = [v[:1] == b"\x01" for v in votes]
    _group_sync = all(armed)
    if not _group_sync and any(armed):
        unarmed = [i for i, a in enumerate(armed) if not a]
        telem_events.emit("preempt", phase="vote_disabled",
                          unarmed_ranks=unarmed)
        log.warning(
            "preempt vote disabled: arming is asymmetric (rank(s) %s "
            "un-armed) — a SIGTERM will only checkpoint the signaled "
            "rank's group when every rank installs handlers or sets "
            "LGBM_TPU_PREEMPT_SYNC=1", unarmed)
    return _group_sync


def group_requested() -> bool:
    """True when ANY rank has the preemption flag set.

    Single-process (or with the vote un-armed) this is the local flag —
    zero overhead. Distributed with the vote armed, each rank
    contributes one byte over the ``_allgather_host_bytes`` lane so all
    ranks agree on the SAME iteration boundary to checkpoint at; the
    payload rides the iteration-epoch header like every other lane
    user, so a desynced rank fails typed instead of checkpointing a
    mixed iteration. Whether the vote runs is the GROUP decision from
    ``resolve_group_sync`` when one was made (it is a collective:
    asymmetric local arming must not reach the allgather below)."""
    local = _requested.is_set()
    enabled = _group_sync if _group_sync is not None else sync_enabled()
    if not enabled:
        return local
    from ..distributed import bootstrap
    if not bootstrap.is_distributed():
        return local
    from ..io.distributed import _allgather_host_bytes
    votes = _allgather_host_bytes(b"\x01" if local else b"\x00")
    hit = any(v[:1] == b"\x01" for v in votes)
    if hit and not local:
        arm("peer")
    return hit
