"""Fault-tolerant training: checkpoints, non-finite sentries, fault
injection.

Three coupled pieces (see docs/Reliability.md):

* ``checkpoint`` — atomic, checksummed, rotated full-state checkpoints
  and ``engine.train(resume_from=...)`` restore.
* ``sentries``  — fused non-finite guards over each boosting iteration
  (``on_nonfinite = raise | skip_iter | rollback``) and a loss-spike
  rollback callback.
* ``faults``    — deterministic, seedable fault injection
  (``LGBM_TPU_FAULT_SPEC``) at the gradient and collective boundaries,
  with bounded exponential-backoff retry for transient collectives.
"""
from . import faults                               # noqa: F401
from .checkpoint import (CheckpointData, CheckpointError,       # noqa: F401
                         CheckpointManager, atomic_write_text,
                         find_checkpoint, load_checkpoint,
                         restore_checkpoint, save_checkpoint)
from .faults import (FaultPlan, TransientCollectiveError,       # noqa: F401
                     run_collective)
from .sentries import NonFiniteError, all_finite, loss_spike_guard  # noqa: F401

__all__ = [
    "faults", "FaultPlan", "TransientCollectiveError", "run_collective",
    "CheckpointData", "CheckpointError", "CheckpointManager",
    "atomic_write_text", "find_checkpoint", "load_checkpoint",
    "restore_checkpoint", "save_checkpoint",
    "NonFiniteError", "all_finite", "loss_spike_guard",
]
