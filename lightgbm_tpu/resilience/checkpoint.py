"""Full training checkpoints: atomic, checksummed, rotated, resumable.

A checkpoint captures everything ``engine.train`` needs to continue a
boosting run exactly where it stopped — not just the model text the
CLI's ``snapshot_freq`` saves, but the live training state: iteration
counter, raw score tensors (train + every valid set, bit-exact f32, so
resumed gradients match the uninterrupted run to the last ulp), the
bagging RNG, the current bag, DART's tree weights, and the engine-level
eval history that early stopping is computed from. Iteration boundaries
are the consistency point (per-iteration allreduce structure,
arXiv:1806.11248): a checkpoint is only ever written between updates.

File format (single file, designed so a mid-write kill can never be
mistaken for a valid checkpoint):

    LGBMTPUCKPT1\\n
    {manifest json: format, version, iteration, payload_sha256, ...}\\n
    <npz payload: model_text, state_json, score arrays, rng keys>

Writes go to a temp file in the destination directory, are fsynced, and
``os.replace``d into place; reads verify size + SHA-256 before touching
the payload. ``CheckpointManager`` names files ``ckpt_iter_NNNNNNN.ckpt``,
keeps the last K, and ``latest()`` skips corrupt/truncated files.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import re
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from ..utils.log import LightGBMError

__all__ = ["CheckpointError", "CheckpointData", "CheckpointManager",
           "atomic_write_text", "atomic_write_bytes", "save_checkpoint",
           "load_checkpoint", "find_checkpoint", "restore_checkpoint"]

MAGIC = b"LGBMTPUCKPT1\n"
FORMAT = "lgbm-tpu-checkpoint"
# Version 2 adds out-of-core streaming state (stream cursor +
# GOSS working-set membership, io/stream.py). Writers only stamp 2 —
# with a matching min_reader_version — when stream state is present, so
# non-streamed checkpoints stay readable by version-1 readers.
VERSION = 2
_CKPT_RE = re.compile(r"_iter_(\d+)\.ckpt$")


class CheckpointError(LightGBMError):
    """Missing, truncated, or corrupt checkpoint."""


# -- atomic filesystem primitives --------------------------------------

def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-to-temp + fsync + rename: readers never observe a partial
    file, and a kill mid-write leaves the previous version intact."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.",
                               dir=d or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


# -- file format --------------------------------------------------------

def write_checkpoint_file(path: str, meta: Dict[str, Any],
                          arrays: Dict[str, np.ndarray]) -> None:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    manifest = dict(meta)
    manifest["payload_sha256"] = hashlib.sha256(payload).hexdigest()
    manifest["payload_size"] = len(payload)
    header = MAGIC + (json.dumps(manifest, sort_keys=True) + "\n").encode()
    atomic_write_bytes(path, header + payload)


def read_checkpoint_file(path: str) -> Tuple[Dict[str, Any], Any]:
    if not os.path.isfile(path):
        raise CheckpointError(f"no checkpoint at {path}")
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(MAGIC):
        raise CheckpointError(f"{path}: not a lightgbm_tpu checkpoint")
    try:
        nl = blob.index(b"\n", len(MAGIC))
        manifest = json.loads(blob[len(MAGIC):nl].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise CheckpointError(f"{path}: unreadable manifest ({exc})")
    payload = blob[nl + 1:]
    if len(payload) != int(manifest.get("payload_size", -1)):
        raise CheckpointError(
            f"{path}: truncated payload ({len(payload)} bytes, manifest "
            f"says {manifest.get('payload_size')})")
    digest = hashlib.sha256(payload).hexdigest()
    if digest != manifest.get("payload_sha256"):
        raise CheckpointError(f"{path}: payload checksum mismatch")
    npz = np.load(io.BytesIO(payload), allow_pickle=False)
    return manifest, npz


# -- capture / restore --------------------------------------------------

def _gbdt_of(booster):
    return getattr(booster, "_gbdt", booster)


def _params_hash(gbdt) -> str:
    try:
        return hashlib.sha256(gbdt.config.to_string().encode()).hexdigest()
    except Exception:   # model-only boosters carry no full config
        return ""


def _pack_rng(state) -> Tuple[list, np.ndarray]:
    name, keys, pos, has_gauss, cached = state
    return ([str(name), int(pos), int(has_gauss), float(cached)],
            np.asarray(keys, dtype=np.uint32))


def _unpack_rng(meta: list, keys: np.ndarray):
    return (meta[0], np.asarray(keys, dtype=np.uint32), int(meta[1]),
            int(meta[2]), float(meta[3]))


class CheckpointData:
    """Decoded checkpoint: manifest meta, model text, training state dict
    (the shape GBDT.restore_state expects), and engine eval history."""

    def __init__(self, meta, model_text, state, history, path=None):
        self.meta = meta
        self.model_text = model_text
        self.state = state
        self.history = history
        self.path = path

    @property
    def iteration(self) -> int:
        return int(self.meta.get("iteration", 0))


def capture(booster, history: Optional[list] = None,
            extra_meta: Optional[Dict[str, Any]] = None
            ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """-> (meta, arrays) ready for write_checkpoint_file. Accessing the
    model list first materializes any in-flight fused iteration, so the
    capture is always at a consistent iteration boundary.

    ``extra_meta`` merges caller context into the manifest — e.g. the
    run's original round budget (``target_rounds``) so a resume after
    preemption finishes the right count, or ``preempted=True`` marking
    an emergency checkpoint. Reserved keys (format/version/iteration/
    checksums) cannot be overridden."""
    gbdt = _gbdt_of(booster)
    st = gbdt.capture_state()
    model_text = gbdt.save_model_to_string(0, -1)
    arrays: Dict[str, np.ndarray] = {"model_text": np.array(model_text)}
    rng_meta, rng_keys = _pack_rng(st["bag_rng"])
    arrays["bag_rng_keys"] = rng_keys
    state_json: Dict[str, Any] = {
        "iter": int(st["iter"]),
        "shrinkage_rate": float(st["shrinkage_rate"]),
        "best_iteration": int(st["best_iteration"]),
        "num_init_iteration": int(st["num_init_iteration"]),
        "bag_rng": rng_meta,
        "n_valid": len(st["valid_scores"]),
    }
    if st.get("bag_indices") is not None:
        arrays["bag_indices"] = np.asarray(st["bag_indices"], dtype=np.int32)
    if st.get("train_score") is not None:
        arrays["train_score"] = np.asarray(st["train_score"],
                                           dtype=np.float32)
    for i, vs in enumerate(st["valid_scores"]):
        arrays[f"valid_score_{i}"] = np.asarray(vs, dtype=np.float32)
    if st.get("dart") is not None:
        d = st["dart"]
        drop_meta, drop_keys = _pack_rng(d["drop_rng"])
        arrays["dart_drop_rng_keys"] = drop_keys
        state_json["dart"] = {"tree_weights": [float(w) for w
                                               in d["tree_weights"]],
                              "sum_weight": float(d["sum_weight"]),
                              "drop_rng": drop_meta}
    version = 1
    if st.get("stream") is not None:
        # streaming state only exists when stream_mode is active; old
        # readers cannot resume it bit-identically, so the manifest
        # demands a version-2 reader in exactly that case
        stream = st["stream"]
        arrays["stream_ws_ids"] = np.asarray(
            stream.get("ws_ids", np.zeros(0, np.int32)), dtype=np.int32)
        state_json["stream"] = {"cursor": int(stream.get("cursor", 0))}
        version = VERSION
    arrays["state_json"] = np.array(json.dumps(state_json))
    arrays["history_json"] = np.array(json.dumps(history or []))
    meta = dict(extra_meta or {})
    meta.update({
        "format": FORMAT,
        "version": version,
        "min_reader_version": version,
        "iteration": int(st["iter"]),
        "num_class": int(gbdt.num_class),
        "num_trees": len(gbdt.models),
        "params_sha256": _params_hash(gbdt),
    })
    return meta, arrays


def save_checkpoint(path: str, booster, history: Optional[list] = None,
                    extra_meta: Optional[Dict[str, Any]] = None) -> str:
    meta, arrays = capture(booster, history, extra_meta=extra_meta)
    write_checkpoint_file(path, meta, arrays)
    return path


def load_checkpoint(path: str) -> CheckpointData:
    manifest, npz = read_checkpoint_file(path)
    if manifest.get("format") != FORMAT:
        raise CheckpointError(f"{path}: unknown format "
                              f"{manifest.get('format')!r}")
    need = int(manifest.get("min_reader_version", 1))
    if need > VERSION:
        raise CheckpointError(
            f"{path}: checkpoint requires reader version {need} "
            f"(this build reads up to {VERSION}); it was written by a "
            "newer build — resume with that build or retrain")
    state_json = json.loads(str(npz["state_json"].item()))
    st: Dict[str, Any] = {
        "iter": int(state_json["iter"]),
        "shrinkage_rate": float(state_json["shrinkage_rate"]),
        "best_iteration": int(state_json["best_iteration"]),
        "num_init_iteration": int(state_json["num_init_iteration"]),
        "bag_rng": _unpack_rng(state_json["bag_rng"], npz["bag_rng_keys"]),
        "bag_indices": (np.asarray(npz["bag_indices"])
                        if "bag_indices" in npz else None),
        "train_score": (np.asarray(npz["train_score"])
                        if "train_score" in npz else None),
        "valid_scores": [np.asarray(npz[f"valid_score_{i}"])
                         for i in range(int(state_json.get("n_valid", 0)))],
    }
    if "dart" in state_json:
        d = state_json["dart"]
        st["dart"] = {
            "tree_weights": list(d["tree_weights"]),
            "sum_weight": float(d["sum_weight"]),
            "drop_rng": _unpack_rng(d["drop_rng"],
                                    npz["dart_drop_rng_keys"]),
        }
    if "stream" in state_json:
        st["stream"] = {
            "cursor": int(state_json["stream"].get("cursor", 0)),
            "ws_ids": (np.asarray(npz["stream_ws_ids"], dtype=np.int32)
                       if "stream_ws_ids" in npz
                       else np.zeros(0, np.int32)),
        }
    history = json.loads(str(npz["history_json"].item()))
    return CheckpointData(manifest, str(npz["model_text"].item()), st,
                          history, path=path)


def restore_checkpoint(booster, data) -> None:
    """Restore a CheckpointData (or a path to one) into a live booster
    whose train/valid Datasets are already attached. Models are replaced
    wholesale, scores come back bit-exact from the stored arrays, and
    RNG state resumes mid-stream."""
    if isinstance(data, str):
        data = find_checkpoint(data)
    gbdt = _gbdt_of(booster)
    ph = _params_hash(gbdt)
    if ph and data.meta.get("params_sha256") and \
            ph != data.meta["params_sha256"]:
        log.warning("resuming with different parameters than the "
                    "checkpointed run; results may diverge")
    if data.meta.get("num_class", gbdt.num_class) != gbdt.num_class:
        raise CheckpointError(
            f"checkpoint num_class={data.meta.get('num_class')} does not "
            f"match booster num_class={gbdt.num_class}")
    from ..config import Config
    from ..models.gbdt import GBDT
    tmp = GBDT.load_model_from_string(data.model_text, Config())
    gbdt.models = list(tmp.models)
    gbdt.invalidate_ensemble_cache()
    gbdt.restore_state(data.state)
    log.info("restored checkpoint %s at iteration %d (%d trees)",
             data.path or "<mem>", data.iteration, len(gbdt.models))


def find_checkpoint(path: str) -> CheckpointData:
    """Load a checkpoint from a file path, or the newest valid one from
    a checkpoint directory."""
    if os.path.isdir(path):
        data = CheckpointManager(path).latest()
        if data is None:
            raise CheckpointError(f"no usable checkpoint in {path}")
        return data
    return load_checkpoint(path)


# -- rotation -----------------------------------------------------------

class CheckpointManager:
    """Names, rotates, and scans checkpoints in one directory."""

    def __init__(self, directory: str, keep_last: int = 3,
                 prefix: str = "ckpt"):
        self.directory = str(directory)
        self.keep_last = max(1, int(keep_last))
        self.prefix = prefix

    def path_for(self, iteration: int) -> str:
        return os.path.join(self.directory,
                            f"{self.prefix}_iter_{int(iteration):07d}.ckpt")

    def checkpoints(self) -> List[Tuple[int, str]]:
        """[(iteration, path)] ascending; unparseable names ignored."""
        out = []
        if not os.path.isdir(self.directory):
            return out
        for name in os.listdir(self.directory):
            if not name.startswith(self.prefix):
                continue
            m = _CKPT_RE.search(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    def save(self, booster, history: Optional[list] = None,
             extra_meta: Optional[Dict[str, Any]] = None) -> str:
        return self.save_captured(*capture(booster, history,
                                           extra_meta=extra_meta))

    def save_captured(self, meta: Dict[str, Any],
                      arrays: Dict[str, np.ndarray]) -> str:
        """Write an already-captured state (distributed/checkpoint.py
        captures on every rank — a collective — but writes on rank 0)."""
        path = self.path_for(meta["iteration"])
        write_checkpoint_file(path, meta, arrays)
        self._rotate()
        return path

    def _rotate(self) -> None:
        ckpts = self.checkpoints()
        for _, path in ckpts[:max(0, len(ckpts) - self.keep_last)]:
            try:
                os.unlink(path)
            except OSError:   # pragma: no cover - already gone
                pass

    def latest(self) -> Optional[CheckpointData]:
        """Newest checkpoint that passes validation; corrupt/truncated
        files are skipped with a warning (a kill mid-rotation must not
        strand the run)."""
        for _, path in reversed(self.checkpoints()):
            try:
                return load_checkpoint(path)
            except CheckpointError as exc:
                log.warning("skipping unusable checkpoint: %s", exc)
        return None
