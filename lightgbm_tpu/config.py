"""Config: typed parameter container with alias resolution and validation.

Equivalent surface to the reference's ``struct Config`` + ``ParameterAlias``
(reference: include/LightGBM/config.h:31-969, src/io/config.cpp:209-347).
The parameter table itself lives in ``params_schema.py`` (generated, single
source of truth, like the reference's helpers/parameter_generator.py flow).
"""
from __future__ import annotations

import copy
import operator as _operator
from typing import Any, Dict, Iterable, Optional

from .params_schema import PARAMS
from .utils import log

# name -> schema entry
_SCHEMA: Dict[str, dict] = {p["name"]: p for p in PARAMS}

# alias -> canonical name (reference: config.h:927 KeyAliasTransform)
_ALIASES: Dict[str, str] = {}
for _p in PARAMS:
    for _a in _p["aliases"]:
        _ALIASES.setdefault(_a, _p["name"])

# defaults that the extractor kept as C++ expressions
_DEFAULT_FIXUPS: Dict[str, Any] = {
    "label_gain": [],          # filled at use time: 2^i - 1
    "eval_at": [1, 2, 3, 4, 5],
    "metric": [],
    "snapshot_freq": -1,
    "valid": [],
    "categorical_feature": [],
    "ignore_column": [],
    "interaction_constraints": [],
    "max_bin_by_feature": [],
    "cegb_penalty_feature_lazy": [],
    "cegb_penalty_feature_coupled": [],
    "monotone_constraints": [],
    "feature_contri": [],
}

# objective aliases (reference: config.cpp ParseObjectiveAlias semantics)
_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression",
    "l2_root": "regression", "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

# metric aliases (reference: metric.cpp:16-61 + config metric parsing)
_METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "regression_l2": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "quantile": "quantile", "huber": "huber", "fair": "fair", "poisson": "poisson",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def _coerce(name: str, value: Any, ptype: str) -> Any:
    if ptype == "bool":
        if isinstance(value, str):
            return value.lower() in ("true", "1", "+", "yes")
        return bool(value)
    if ptype == "int":
        return int(float(value)) if not isinstance(value, bool) else int(value)
    if ptype == "float":
        return float(value)
    if ptype in ("vec_int", "vec_float", "vec_str", "multi-enum", "multi-int", "multi-double"):
        if value is None or value == "":
            return []
        if isinstance(value, str):
            parts = [v for v in value.replace(",", " ").split() if v]
        elif isinstance(value, Iterable) and not isinstance(value, str):
            parts = list(value)
        else:
            parts = [value]
        if ptype in ("vec_int", "multi-int"):
            return [int(float(v)) for v in parts]
        if ptype in ("vec_float", "multi-double"):
            return [float(v) for v in parts]
        return [str(v) for v in parts]
    return str(value)


def resolve_aliases(params: Dict[str, Any]) -> Dict[str, Any]:
    """Map alias keys to canonical names.

    Conflict resolution matches the reference (config.h:927): when several
    aliases of one parameter are given, the shortest (then alphabetically
    first) name wins; an explicitly-set canonical name always wins.
    """
    out: Dict[str, Any] = {}
    pending: Dict[str, tuple] = {}
    for key, value in params.items():
        canonical = _ALIASES.get(key)
        if canonical is None:
            if key not in _SCHEMA:
                log.warning("Unknown parameter: %s", key)
                continue
            out[key] = value
        else:
            prev = pending.get(canonical)
            if prev is None or (len(key), key) < (len(prev[0]), prev[0]):
                pending[canonical] = (key, value)
    for canonical, (src, value) in pending.items():
        if canonical in out:
            log.warning(
                "%s is set, %s=%s will be ignored", canonical, src, value)
        else:
            out[canonical] = value
    return out


_CHECK_OPS = {">": _operator.gt, ">=": _operator.ge,
              "<": _operator.lt, "<=": _operator.le}


def _check_constraints(name: str, value, schema: dict) -> None:
    """Enforce the schema's range constraints (the reference's CHECK
    macros on Config members, include/LightGBM/config.h doc tags)."""
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return
    for chk in schema.get("check", ()):
        for op in (">=", "<=", ">", "<"):    # longest match first
            if chk.startswith(op):
                if not _CHECK_OPS[op](float(value), float(chk[len(op):])):
                    log.fatal("Parameter %s=%s should be %s %s",
                              name, value, op, chk[len(op):])
                break


class Config:
    """All training/IO/prediction parameters as attributes."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        for p in PARAMS:
            default = _DEFAULT_FIXUPS.get(p["name"], p["default"])
            setattr(self, p["name"], copy.copy(default))
        self.raw: Dict[str, Any] = {}
        if params:
            self.update(params)

    def update(self, params: Dict[str, Any]) -> None:
        resolved = resolve_aliases(params)
        # two-phase + rollback: coerce/range-check everything first; if
        # anything (including _post_process conflict checks) rejects, the
        # config is restored exactly — no partially-applied params, no
        # skipped post-processing.
        coerced_all = []
        for name, value in resolved.items():
            schema = _SCHEMA[name]
            coerced = _coerce(name, value, schema["type"])
            _check_constraints(name, coerced, schema)
            coerced_all.append((name, coerced))
        snapshot = {p["name"]: copy.copy(getattr(self, p["name"]))
                    for p in PARAMS}
        raw_snapshot = dict(self.raw)
        try:
            for name, coerced in coerced_all:
                setattr(self, name, coerced)
            self.raw.update(resolved)
            self._post_process(resolved)
        except Exception:
            for name, old in snapshot.items():
                setattr(self, name, old)
            self.raw = raw_snapshot
            raise

    def _post_process(self, resolved: Dict[str, Any]) -> None:
        self.objective = _OBJECTIVE_ALIASES.get(
            str(self.objective).lower(), str(self.objective).lower())
        metrics = []
        for m in (self.metric if isinstance(self.metric, list) else [self.metric]):
            mname = str(m).lower()
            if mname == "":
                continue
            metrics.append(_METRIC_ALIASES.get(mname, mname))
        # dedup keeping order (reference keeps a set)
        seen = set()
        self.metric = [m for m in metrics if not (m in seen or seen.add(m))]
        if not self.label_gain:
            self.label_gain = [float((1 << i) - 1) for i in range(31)]
        self._check_conflicts(resolved)

    def _check_conflicts(self, resolved: Dict[str, Any]) -> None:
        """Parameter-conflict checks (reference: config.cpp:268 CheckParamConflict)."""
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            log.fatal("Cannot set is_unbalance and scale_pos_weight at the same time")
        # num_leaves / bagging_fraction ranges are owned by the schema
        # constraint checks (_check_constraints)
        if self.boosting in ("rf", "random_forest"):
            self.boosting = "rf"
            if self.bagging_freq <= 0 or self.bagging_fraction >= 1.0 or self.bagging_fraction <= 0.0:
                log.fatal("Random forest needs bagging_freq > 0 and bagging_fraction in (0, 1)")
        if self.boosting == "goss":
            if self.top_rate + self.other_rate > 1.0:
                log.fatal("top_rate + other_rate must be <= 1.0 for GOSS")
        if self.on_nonfinite not in ("off", "raise", "skip_iter", "rollback"):
            log.fatal("on_nonfinite must be one of off/raise/skip_iter/"
                      "rollback, got %s", self.on_nonfinite)
        if self.telemetry not in ("off", "summary", "trace"):
            log.fatal("telemetry must be one of off/summary/trace, got %s",
                      self.telemetry)
        if self.grow_program not in ("per_split", "fused_tree"):
            log.fatal("grow_program must be one of per_split/fused_tree, "
                      "got %s", self.grow_program)
        if self.stream_mode not in ("off", "chunked", "goss"):
            log.fatal("stream_mode must be one of off/chunked/goss, got %s",
                      self.stream_mode)
        if self.stream_mode == "goss" and self.boosting != "goss":
            log.fatal("stream_mode=goss reuses GOSS sampling as the "
                      "working-set policy and needs boosting=goss "
                      "(got boosting=%s); use stream_mode=chunked for "
                      "plain streaming", self.boosting)
        if self.continual_policy not in ("refit", "continue", "auto"):
            log.fatal("continual_policy must be one of refit/continue/auto, "
                      "got %s", self.continual_policy)
        if self.on_rank_failure not in ("raise", "shrink"):
            log.fatal("on_rank_failure must be one of raise/shrink, "
                      "got %s", self.on_rank_failure)
        if self.dist_shard_mode not in ("replicated", "rows"):
            log.fatal("dist_shard_mode must be one of replicated/rows, "
                      "got %s", self.dist_shard_mode)
        if self.dist_shard_mode == "rows" and self.tree_learner in (
                "feature", "voting"):
            log.fatal(
                "dist_shard_mode=rows keeps each host only its own row "
                "block, which only the data-parallel learner can train "
                "on (histograms are the cross-host exchange); "
                "tree_learner=%s needs every rank to hold all rows. Use "
                "tree_learner=data or dist_shard_mode=replicated",
                self.tree_learner)

    # -- helpers used by the trainer -------------------------------------
    @property
    def is_parallel(self) -> bool:
        return self.tree_learner not in ("serial",)

    @property
    def quant_bits(self) -> int:
        """The ONE resolution point of the quantized-gradient knobs:
        grad_bits when quantized_grad is on, else 0 (float histograms).
        Learners key their jit caches on this static."""
        return int(self.grad_bits) if self.quantized_grad else 0

    def to_dict(self) -> Dict[str, Any]:
        return {p["name"]: getattr(self, p["name"]) for p in PARAMS}

    def to_string(self) -> str:
        """Save non-default parameters (model-file 'parameters:' section)."""
        lines = []
        for p in PARAMS:
            name = p["name"]
            if name in ("task", "machines", "config"):
                continue
            val = getattr(self, name)
            if isinstance(val, list):
                sval = ",".join(str(v) for v in val)
            else:
                sval = str(val).lower() if isinstance(val, bool) else str(val)
            lines.append(f"[{name}: {sval}]")
        return "\n".join(lines)


def param_dict_to_str(params: Dict[str, Any]) -> str:
    """Python-dict -> 'k=v k2=v2' string (reference: basic.py param_dict_to_str)."""
    pairs = []
    for key, val in params.items():
        if isinstance(val, (list, tuple)):
            pairs.append(f"{key}={','.join(map(str, val))}")
        elif isinstance(val, bool):
            pairs.append(f"{key}={'true' if val else 'false'}")
        elif val is None:
            continue
        else:
            pairs.append(f"{key}={val}")
    return " ".join(pairs)


def parse_config_str(text: str) -> Dict[str, Any]:
    """Parse 'k=v' lines / CLI args (reference: config.cpp KV2Map)."""
    out: Dict[str, Any] = {}
    for token in text.replace("\n", " ").split():
        token = token.strip()
        if not token or token.startswith("#"):
            continue
        if "=" in token:
            k, v = token.split("=", 1)
            out[k.strip()] = v.strip()
    return out
