"""Objective functions (gradient/hessian producers).

Full parity set with the reference factory (reference:
src/objective/objective_function.cpp:15-50).
"""
from .objective import OBJECTIVE_NAMES, Objective, create_objective

__all__ = ["Objective", "create_objective", "OBJECTIVE_NAMES"]
