"""All objective functions as jitted device math.

Each objective mirrors the reference class of the same config name
(reference: src/objective/{regression,binary,multiclass,xentropy,rank}_objective.hpp)
— same gradients/hessians, boost-from-score, output transform and leaf-renewal
semantics, restructured as whole-array jax ops instead of OMP loops.

Scores/gradients for K classes use shape (K, N) (reference uses the same
class-major flattening, multiclass_objective.hpp:88 idx = num_data*k + i).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log

K_EPSILON = 1e-15


def _to_f32(x):
    return jnp.asarray(x, dtype=jnp.float32)


def _percentile(values: np.ndarray, weights: Optional[np.ndarray],
                alpha: float) -> float:
    """Weighted percentile, reference semantics (regression_objective.hpp:20-76
    PercentileFun/WeightedPercentileFun)."""
    n = len(values)
    if n == 0:
        return 0.0
    if weights is None:
        if n <= 1:
            return float(values[0])
        order = np.argsort(values, kind="stable")
        float_pos = (1.0 - alpha) * n
        pos = int(math.floor(float_pos))
        if pos < 1:
            return float(values[order[0]])
        if pos >= n:
            return float(values[order[n - 1]])
        bias = float_pos - pos
        v1 = float(values[order[pos - 1]])
        v2 = float(values[order[pos]])
        return v1 * (1.0 - bias) + v2 * bias
    order = np.argsort(values, kind="stable")
    w = weights[order]
    v = values[order]
    cum = np.cumsum(w) - 0.5 * w
    threshold = alpha * np.sum(w)
    idx = int(np.searchsorted(cum, threshold, side="left"))
    idx = min(max(idx, 0), n - 1)
    if idx > 0 and cum[idx] > threshold:
        # interpolate like the reference's weighted percentile
        c1, c2 = cum[idx - 1], cum[idx]
        if c2 > c1:
            t = (threshold - c1) / (c2 - c1)
            return float(v[idx - 1] * (1 - t) + v[idx] * t)
    return float(v[idx])


class Objective:
    """Base objective (reference: include/LightGBM/objective_function.h)."""

    name = "none"

    def __init__(self, config):
        self.config = config
        self.num_class = 1
        self.label: Optional[np.ndarray] = None
        self.weight = None

    # -- lifecycle ------------------------------------------------------
    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self._label_dev = _to_f32(self.label) if self.label is not None else None
        self._weight_dev = _to_f32(self.weight) if self.weight is not None else None

    # -- core -----------------------------------------------------------
    def get_gradients(self, score: jax.Array):
        raise NotImplementedError

    def device_buffer_names(self):
        """Attribute names of the device buffers get_gradients reads.
        The fused training step passes these as jit ARGUMENTS (via a
        trace-time attribute swap) so they lower as parameters instead
        of per-dataset HLO constants — see device_learner
        objective_buffer_names. Default: every nontrivial device array
        attribute (covers label/weight/transformed-label vectors AND
        shaped buffers like lambdarank's (Q, L) segment tensors)."""
        return sorted(
            k for k, v in vars(self).items()
            if isinstance(v, jax.Array) and v.ndim >= 1 and v.size >= 256)

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, scores: jax.Array) -> jax.Array:
        return scores

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    @property
    def is_renew_tree_output(self) -> bool:
        return False

    def renew_leaf_output(self, residuals: np.ndarray,
                          weights: Optional[np.ndarray]) -> float:
        raise NotImplementedError

    def class_need_train(self, class_id: int) -> bool:
        return True

    def to_string(self) -> str:
        return self.name

    def _apply_weight(self, grad, hess):
        if self._weight_dev is not None:
            return grad * self._weight_dev, hess * self._weight_dev
        return grad, hess


# ----------------------------------------------------------------------
class RegressionL2(Objective):
    """reference: regression_objective.hpp:78 RegressionL2loss."""
    name = "regression"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(getattr(config, "reg_sqrt", False))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lbl = np.sign(self.label) * np.sqrt(np.abs(self.label))
            self._label_dev = _to_f32(lbl)
            self._trans_label = lbl
        else:
            self._trans_label = self.label

    def get_gradients(self, score):
        grad = score - self._label_dev
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    @property
    def is_constant_hessian(self) -> bool:
        return self.weight is None

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return float(np.sum(self._trans_label * self.weight) / np.sum(self.weight))
        return float(np.mean(self._trans_label))

    def convert_output(self, scores):
        if self.sqrt:
            return jnp.sign(scores) * scores * scores
        return scores

    def to_string(self):
        return f"{self.name} sqrt" if self.sqrt else self.name


class RegressionL1(RegressionL2):
    """reference: regression_objective.hpp:189 RegressionL1loss."""
    name = "regression_l1"

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        return _percentile(np.asarray(self.label, dtype=np.float64),
                           self.weight, 0.5)

    @property
    def is_renew_tree_output(self) -> bool:
        return True

    def renew_leaf_output(self, residuals, weights):
        return _percentile(residuals, weights, 0.5)

    @property
    def is_constant_hessian(self) -> bool:
        return self.weight is None


class Huber(RegressionL2):
    """reference: regression_objective.hpp:275 RegressionHuberLoss."""
    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    @property
    def is_constant_hessian(self) -> bool:
        return self.weight is None


class Fair(RegressionL2):
    """reference: regression_objective.hpp:337 RegressionFairLoss."""
    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self._label_dev
        ax = jnp.abs(x)
        grad = self.c * x / (ax + self.c)
        hess = self.c * self.c / ((ax + self.c) ** 2)
        return self._apply_weight(grad, hess)

    @property
    def is_constant_hessian(self) -> bool:
        return False


class Poisson(RegressionL2):
    """reference: regression_objective.hpp:384 RegressionPoissonLoss (log link)."""
    name = "poisson"

    def __init__(self, config):
        super().__init__(config)
        self.max_delta_step = float(config.poisson_max_delta_step)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self.label < 0):
            log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score):
        grad = jnp.exp(score) - self._label_dev
        hess = jnp.exp(score + self.max_delta_step)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        mean = RegressionL2.boost_from_score(self, class_id)
        return math.log(max(mean, 1e-20))

    def convert_output(self, scores):
        return jnp.exp(scores)

    @property
    def is_constant_hessian(self) -> bool:
        return False


class Quantile(RegressionL2):
    """reference: regression_objective.hpp:464 RegressionQuantileloss."""
    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        delta = score - self._label_dev
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        return _percentile(np.asarray(self.label, dtype=np.float64),
                           self.weight, self.alpha)

    @property
    def is_renew_tree_output(self) -> bool:
        return True

    def renew_leaf_output(self, residuals, weights):
        return _percentile(residuals, weights, self.alpha)

    @property
    def is_constant_hessian(self) -> bool:
        return self.weight is None


class MAPE(RegressionL1):
    """reference: regression_objective.hpp:562 RegressionMAPELOSS."""
    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = np.asarray(self.label, dtype=np.float64)
        w = 1.0 / np.maximum(1.0, np.abs(label))
        if self.weight is not None:
            w = w * self.weight
        self._mape_w = w
        self._mape_w_dev = _to_f32(w)

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff) * self._mape_w_dev
        hess = self._mape_w_dev
        return grad, hess

    def boost_from_score(self, class_id):
        return _percentile(np.asarray(self.label, dtype=np.float64),
                           self._mape_w, 0.5)

    def renew_leaf_output(self, residuals, weights):
        # weights here are the MAPE weights gathered per-leaf by the caller
        return _percentile(residuals, weights, 0.5)

    @property
    def leaf_renew_weight(self):
        return self._mape_w

    @property
    def is_constant_hessian(self) -> bool:
        return False


class Gamma(Poisson):
    """reference: regression_objective.hpp:661 RegressionGammaLoss."""
    name = "gamma"

    def get_gradients(self, score):
        inv = jnp.exp(-score)
        grad = 1.0 - self._label_dev * inv
        hess = self._label_dev * inv
        return self._apply_weight(grad, hess)


class Tweedie(Poisson):
    """reference: regression_objective.hpp:696 RegressionTweedieLoss."""
    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -self._label_dev * e1 + e2
        hess = (-self._label_dev * (1.0 - self.rho) * e1
                + (2.0 - self.rho) * e2)
        return self._apply_weight(grad, hess)


# ----------------------------------------------------------------------
class BinaryLogloss(Objective):
    """reference: binary_objective.hpp:21 BinaryLogloss."""
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid parameter %f should be greater than zero",
                      self.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        is_pos = self.label > 0
        cnt_pos = int(np.sum(is_pos))
        cnt_neg = num_data - cnt_pos
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        if not self.need_train:
            log.warning("Contains only one class")
        w_neg, w_pos = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self._signed_label = _to_f32(np.where(is_pos, 1.0, -1.0))
        self._label_weight = _to_f32(np.where(is_pos, w_pos, w_neg))
        self._pavg = (np.sum(self.weight[is_pos]) / np.sum(self.weight)
                      if self.weight is not None
                      else cnt_pos / max(1, num_data))

    def get_gradients(self, score):
        lbl = self._signed_label
        response = -lbl * self.sigmoid / (1.0 + jnp.exp(lbl * self.sigmoid * score))
        abs_r = jnp.abs(response)
        grad = response * self._label_weight
        hess = abs_r * (self.sigmoid - abs_r) * self._label_weight
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        pavg = min(max(self._pavg, K_EPSILON), 1.0 - K_EPSILON)
        return math.log(pavg / (1.0 - pavg)) / self.sigmoid

    def convert_output(self, scores):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * scores))

    def class_need_train(self, class_id):
        return self.need_train

    def to_string(self):
        return f"{self.name} sigmoid:{self.sigmoid:g}"


class CrossEntropy(Objective):
    """reference: xentropy_objective.hpp:44 CrossEntropy."""
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any((self.label < 0) | (self.label > 1)):
            log.fatal("[%s]: label must be in [0, 1]", self.name)

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        grad = z - self._label_dev
        hess = z * (1.0 - z)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        if self.weight is not None:
            pavg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, scores):
        return 1.0 / (1.0 + jnp.exp(-scores))


class CrossEntropyLambda(Objective):
    """reference: xentropy_objective.hpp:148 CrossEntropyLambda."""
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any((self.label < 0) | (self.label > 1)):
            log.fatal("[%s]: label must be in [0, 1]", self.name)

    def get_gradients(self, score):
        if self._weight_dev is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - self._label_dev, z * (1.0 - z)
        w = self._weight_dev
        y = self._label_dev
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id):
        if self.weight is not None:
            havg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            havg = float(np.mean(self.label))
        return math.log(max(math.exp(havg) - 1.0, K_EPSILON))

    def convert_output(self, scores):
        return jnp.log1p(jnp.exp(scores))


# ----------------------------------------------------------------------
class MulticlassSoftmax(Objective):
    """reference: multiclass_objective.hpp:24 MulticlassSoftmax."""
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = self.label.astype(np.int32)
        if np.any((label_int < 0) | (label_int >= self.num_class)):
            log.fatal("Label must be in [0, %d) for multiclass", self.num_class)
        self._label_int = _to_f32(label_int)
        counts = np.bincount(label_int, minlength=self.num_class,
                             weights=self.weight)
        total = counts.sum()
        self._class_probs = counts / max(total, 1e-10)

    def get_gradients(self, score):
        # score: (K, N)
        p = jax.nn.softmax(score, axis=0)
        onehot = (jnp.arange(self.num_class, dtype=jnp.float32)[:, None]
                  == self._label_int[None, :])
        grad = p - onehot
        hess = 2.0 * p * (1.0 - p)
        if self._weight_dev is not None:
            grad = grad * self._weight_dev[None, :]
            hess = hess * self._weight_dev[None, :]
        return grad, hess

    def boost_from_score(self, class_id):
        return math.log(max(K_EPSILON, self._class_probs[class_id]))

    def convert_output(self, scores):
        return jax.nn.softmax(scores, axis=0)

    def class_need_train(self, class_id):
        p = self._class_probs[class_id]
        return K_EPSILON < abs(p) < 1.0 - K_EPSILON

    def to_string(self):
        return f"{self.name} num_class:{self.num_class}"


class MulticlassOVA(Objective):
    """reference: multiclass_objective.hpp:180 MulticlassOVA."""
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self._binary = [BinaryLogloss(config) for _ in range(self.num_class)]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = self.label.astype(np.int32)
        self._onehot = (np.arange(self.num_class)[:, None]
                        == label_int[None, :]).astype(np.float32)

        class _Meta:
            pass

        for k, b in enumerate(self._binary):
            m = _Meta()
            m.label = self._onehot[k]
            m.weight = self.weight
            b.init(m, num_data)

    def get_gradients(self, score):
        grads, hesses = [], []
        for k, b in enumerate(self._binary):
            g, h = b.get_gradients(score[k])
            grads.append(g)
            hesses.append(h)
        return jnp.stack(grads), jnp.stack(hesses)

    def boost_from_score(self, class_id):
        return self._binary[class_id].boost_from_score(0)

    def convert_output(self, scores):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * scores))

    def to_string(self):
        return (f"{self.name} num_class:{self.num_class} "
                f"sigmoid:{self.sigmoid:g}")


# ----------------------------------------------------------------------
class LambdarankNDCG(Objective):
    """LambdaRank with NDCG weighting (reference: rank_objective.hpp:23).

    TPU-native formulation: queries are padded into (Q, L) segment tensors;
    the per-query pairwise lambda accumulation (rank_objective.hpp:83-190)
    becomes masked (L, L) outer products batched over query chunks. The
    sigmoid table is replaced by the exact sigmoid (accuracy >= table).
    """
    name = "lambdarank"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.norm = bool(config.lambdamart_norm)
        self.optimize_pos_at = int(config.max_position)
        self.label_gain = np.asarray(config.label_gain, dtype=np.float64)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        qb = metadata.query_boundaries
        if qb is None:
            log.fatal("Lambdarank tasks require query information")
        self.query_boundaries = np.asarray(qb, dtype=np.int64)
        counts = np.diff(self.query_boundaries)
        self.num_queries = len(counts)
        lmax = int(counts.max())
        # pad to a lane-friendly length
        self.pad_len = max(8, 1 << (lmax - 1).bit_length())
        q, L = self.num_queries, self.pad_len
        idx = np.zeros((q, L), dtype=np.int32)
        mask = np.zeros((q, L), dtype=bool)
        for i in range(q):
            c = counts[i]
            idx[i, :c] = np.arange(self.query_boundaries[i],
                                   self.query_boundaries[i + 1])
            mask[i, :c] = True
        self._idx = jnp.asarray(idx)
        self._mask = jnp.asarray(mask)
        labels = np.where(mask, self.label[idx.clip(0, num_data - 1)], 0.0)
        # max DCG at top-k per query (reference DCGCalculator::CalMaxDCGAtK)
        inv_max_dcg = np.zeros(q)
        gains = self.label_gain[labels.astype(np.int32)]
        discounts = 1.0 / np.log2(np.arange(L) + 2.0)
        for i in range(q):
            srt = np.sort(gains[i][mask[i]])[::-1]
            k = min(self.optimize_pos_at, len(srt))
            m = float(np.sum(srt[:k] * discounts[:k]))
            inv_max_dcg[i] = 1.0 / m if m > 0 else 0.0
        self._inv_max_dcg = jnp.asarray(inv_max_dcg, dtype=jnp.float32)
        self._gains = jnp.asarray(gains, dtype=jnp.float32)
        self._labels_pad = jnp.asarray(labels, dtype=jnp.float32)
        self._discount = jnp.asarray(discounts, dtype=jnp.float32)
        self._grad_fn = jax.jit(self._gradients_impl)

    def _gradients_impl(self, score):
        q, L = self._idx.shape
        s = score[self._idx] * self._mask  # (Q, L)
        s = jnp.where(self._mask, s, -jnp.inf)
        order = jnp.argsort(-s, axis=1)  # rank -> doc position within query
        s_srt = jnp.take_along_axis(s, order, axis=1)
        lbl_srt = jnp.take_along_axis(self._labels_pad, order, axis=1)
        gain_srt = jnp.take_along_axis(self._gains, order, axis=1)
        valid_srt = jnp.take_along_axis(self._mask, order, axis=1)
        disc = self._discount[None, :] * valid_srt  # (Q, L) discount by rank

        best = s_srt[:, 0]
        nvalid = jnp.sum(valid_srt, axis=1).astype(jnp.int32)
        worst = jnp.take_along_axis(
            s_srt, jnp.maximum(nvalid - 1, 0)[:, None], axis=1)[:, 0]

        # pair tensors over rank positions (i=high, j=low)
        delta_s = s_srt[:, :, None] - s_srt[:, None, :]
        pair_ok = (valid_srt[:, :, None] & valid_srt[:, None, :]
                   & (lbl_srt[:, :, None] > lbl_srt[:, None, :]))
        dcg_gap = gain_srt[:, :, None] - gain_srt[:, None, :]
        paired_disc = jnp.abs(disc[:, :, None] - disc[:, None, :])
        delta_ndcg = dcg_gap * paired_disc * self._inv_max_dcg[:, None, None]
        if self.norm:
            norm_ok = (best != worst)[:, None, None]
            delta_ndcg = jnp.where(
                norm_ok, delta_ndcg / (0.01 + jnp.abs(delta_s)), delta_ndcg)
        p = 1.0 / (1.0 + jnp.exp(self.sigmoid * delta_s))  # GetSigmoid(delta)
        p_lambda = -self.sigmoid * delta_ndcg * p
        p_hess = self.sigmoid * self.sigmoid * delta_ndcg * p * (1.0 - p)
        p_lambda = jnp.where(pair_ok, p_lambda, 0.0)
        p_hess = jnp.where(pair_ok, p_hess, 0.0)

        lam_srt = jnp.sum(p_lambda, axis=2) - jnp.sum(p_lambda, axis=1)
        hes_srt = jnp.sum(p_hess, axis=2) + jnp.sum(p_hess, axis=1)
        if self.norm:
            sum_lambdas = -2.0 * jnp.sum(p_lambda, axis=(1, 2))
            factor = jnp.where(
                sum_lambdas > 0,
                jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, 1e-20),
                1.0)
            lam_srt = lam_srt * factor[:, None]
            hes_srt = hes_srt * factor[:, None]

        # unsort back to doc positions, then scatter to flat rows
        inv_order = jnp.argsort(order, axis=1)
        lam = jnp.take_along_axis(lam_srt, inv_order, axis=1)
        hes = jnp.take_along_axis(hes_srt, inv_order, axis=1)
        grad = jnp.zeros_like(score).at[self._idx.reshape(-1)].add(
            jnp.where(self._mask, lam, 0.0).reshape(-1))
        hess = jnp.zeros_like(score).at[self._idx.reshape(-1)].add(
            jnp.where(self._mask, hes, 0.0).reshape(-1))
        if self._weight_dev is not None:
            grad = grad * self._weight_dev
            hess = hess * self._weight_dev
        return grad, hess

    def get_gradients(self, score):
        import jax.core as _core
        if isinstance(score, _core.Tracer):
            # already under a jit trace (the fused step): call the impl
            # directly so the swapped buffer tracers flow through —
            # dispatching into the cached inner jit would splice its
            # previously-traced jaxpr with the buffers as constants
            return self._gradients_impl(score)
        return self._grad_fn(score)


# ----------------------------------------------------------------------
class NoneObjective(Objective):
    """objective=none: gradients supplied externally (custom fobj)."""
    name = "custom"

    def get_gradients(self, score):
        log.fatal("objective=none requires externally-supplied gradients")


_CLASSES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": MAPE,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}

OBJECTIVE_NAMES = sorted(_CLASSES)


def create_objective(name: str, config) -> Optional[Objective]:
    """Factory (reference: objective_function.cpp:15-50); None for custom."""
    name = str(name).lower()
    if name in ("none", "null", "custom", "na"):
        return None
    cls = _CLASSES.get(name)
    if cls is None:
        log.fatal("Unknown objective type name: %s", name)
    obj = cls(config)
    return obj


def parse_objective_from_model(text: str, config) -> Optional[Objective]:
    """Recreate an objective from its model-file string, e.g.
    'binary sigmoid:1' or 'multiclass num_class:3'."""
    parts = text.strip().split()
    if not parts:
        return None
    name = parts[0]
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            if k == "num_class":
                config.num_class = int(v)
            elif k == "sigmoid":
                config.sigmoid = float(v)
    return create_objective(name, config)
