"""Python side of the C ABI (capi/c_api.cpp).

Handle tables + buffer marshalling for the LGBM_* entry points. The
reference implements this layer in C++ (reference: src/c_api.cpp Booster
wrapper class + dataset constructors); here the native shim embeds CPython
and calls these functions with zero-copy memoryviews.
"""
from __future__ import annotations

import itertools
from typing import Dict

import numpy as np

from .basic import Booster, Dataset
from .config import parse_config_str

_handles: Dict[int, object] = {}
_handle_counter = itertools.count(1)
_field_cache: Dict[tuple, np.ndarray] = {}

C_DTYPE = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _new_handle(obj) -> int:
    h = next(_handle_counter)
    _handles[h] = obj
    return h


def _get(h: int):
    return _handles[int(h)]


# ---------------------------------------------------------------------------
# Dataset
# ---------------------------------------------------------------------------

def dataset_create_from_file(filename: str, parameters: str, reference: int):
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_create_from_mat(mv, data_type, nrow, ncol, is_row_major,
                            parameters, reference):
    arr = np.frombuffer(mv, dtype=C_DTYPE[data_type])
    if is_row_major:
        mat = arr.reshape(nrow, ncol)
    else:
        mat = arr.reshape(ncol, nrow).T
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(mat, dtype=np.float64), reference=ref,
                 params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_create_from_csr(indptr_mv, indptr_type, indices_mv, data_mv,
                            data_type, nindptr, nelem, num_col, parameters,
                            reference):
    # the matrix stays sparse end-to-end: io/dataset.py bins straight
    # off the CSC structure (reference: src/io/sparse_bin.hpp:73)
    mat = _csr_view(indptr_mv, indptr_type, indices_mv, data_mv,
                    data_type, nindptr, nelem, num_col)
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(mat, reference=ref, params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_free(h):
    _handles.pop(int(h), None)
    for k in [k for k in _field_cache if k[0] == int(h)]:
        del _field_cache[k]


def dataset_get_num_data(h):
    return _get(h).num_data()


def dataset_get_num_feature(h):
    return _get(h).num_feature()


def dataset_set_field(h, field_name, mv, num_element, type_):
    arr = np.frombuffer(mv, dtype=C_DTYPE[type_])[:num_element].copy()
    ds = _get(h)
    if field_name == "group":
        ds.set_group(arr.astype(np.int64))
    else:
        ds.set_field(field_name, arr.astype(np.float64))
    return 0


def dataset_get_field(h, field_name):
    ds = _get(h)
    val = ds.get_field(field_name)
    if val is None:
        raise ValueError(f"field {field_name} not set")
    if field_name == "group":
        arr = np.ascontiguousarray(val, dtype=np.int32)
        type_ = 2
    else:
        arr = np.ascontiguousarray(val, dtype=np.float32)
        type_ = 0
    _field_cache[(int(h), field_name)] = arr  # keep buffer alive
    return (arr.ctypes.data, len(arr), type_)


# ---------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------

def _as_dataset(obj):
    """Materialize streaming datasets into real Dataset instances."""
    if isinstance(obj, _StreamingDataset):
        return obj._materialize()
    return obj


def booster_create(train_h, parameters):
    params = parse_config_str(parameters or "")
    bst = Booster(params=params, train_set=_as_dataset(_get(train_h)))
    return _new_handle(bst)


def booster_create_from_modelfile(filename):
    bst = Booster(model_file=filename)
    return (_new_handle(bst), bst.current_iteration())


def booster_load_from_string(model_str):
    bst = Booster(model_str=model_str)
    return (_new_handle(bst), bst.current_iteration())


def booster_free(h):
    _handles.pop(int(h), None)


def booster_add_valid(h, valid_h):
    bst = _get(h)
    bst.add_valid(_get(valid_h), f"valid_{len(bst.name_valid_sets)}")


def booster_update_one_iter(h):
    return 1 if _get(h).update() else 0


def booster_num_total_rows(h):
    bst = _get(h)
    return bst._gbdt.num_data * bst._gbdt.num_tree_per_iteration


def booster_update_one_iter_custom(h, grad_mv, hess_mv):
    bst = _get(h)
    grad = np.frombuffer(grad_mv, dtype=np.float32)
    hess = np.frombuffer(hess_mv, dtype=np.float32)
    return 1 if bst._gbdt.train_one_iter(grad, hess) else 0


def booster_rollback_one_iter(h):
    _get(h).rollback_one_iter()


def booster_current_iteration(h):
    return _get(h).current_iteration()


def booster_num_classes(h):
    return _get(h)._gbdt.num_class


def booster_num_feature(h):
    return _get(h).num_feature()


def booster_eval_counts(h):
    bst = _get(h)
    return sum(len(m.names) for m in bst._gbdt.train_metrics)


def booster_eval_names(h):
    """Metric display names, order-aligned with booster_get_eval results
    (reference: LGBM_BoosterGetEvalNames, c_api.cpp)."""
    bst = _get(h)
    names = []
    for m in bst._gbdt.train_metrics:
        names.extend(m.names)
    return [str(n) for n in names]


def booster_eval_higher_better(h):
    """1/0 per eval slot: whether larger metric values are better."""
    bst = _get(h)
    out = []
    for m in bst._gbdt.train_metrics:
        out.extend([1 if m.higher_better else 0] * len(m.names))
    return out


def booster_get_eval(h, data_idx):
    """data_idx 0 = train, i>0 = valid i-1 (reference c_api semantics)."""
    bst = _get(h)
    if data_idx == 0:
        results = bst.eval_train()
    else:
        name = bst.name_valid_sets[data_idx - 1]
        results = [r for r in bst.eval_valid() if r[0] == name]
    return [float(r[2]) for r in results]


def booster_predict_for_mat(h, mv, data_type, nrow, ncol, is_row_major,
                            predict_type, num_iteration, parameter):
    bst = _get(h)
    arr = np.frombuffer(mv, dtype=C_DTYPE[data_type])
    mat = arr.reshape(nrow, ncol) if is_row_major else arr.reshape(ncol, nrow).T
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    preds = bst.predict(np.asarray(mat, dtype=np.float64),
                        num_iteration=num_iteration if num_iteration > 0 else None,
                        **kwargs)
    return np.ascontiguousarray(preds, dtype=np.float64).tobytes()


def booster_save_model(h, start_iteration, num_iteration, filename):
    _get(h)._gbdt.save_model(filename, num_iteration, start_iteration)


def booster_save_model_to_string(h, start_iteration, num_iteration):
    return _get(h)._gbdt.save_model_to_string(start_iteration, num_iteration)


def booster_feature_importance(h, num_iteration, importance_type):
    itype = "split" if importance_type == 0 else "gain"
    imp = _get(h)._gbdt.feature_importance(
        itype, num_iteration if num_iteration > 0 else None)
    return [float(v) for v in imp]


# ---------------------------------------------------------------------------
# Network
# ---------------------------------------------------------------------------

def network_init(machines, local_listen_port, listen_time_out, num_machines):
    from .parallel import network
    network.init_from_params(machines, local_listen_port, num_machines)


def network_free():
    from .parallel import network
    network.free()


# ---------------------------------------------------------------------------
# Extended dataset constructors (reference: src/c_api.cpp dataset section)
# ---------------------------------------------------------------------------

def _csc_view(col_ptr_mv, col_ptr_type, indices_mv, data_mv,
              data_type, ncol_ptr, nelem, num_row):
    """scipy CSC over the caller's buffers — no dense materialization."""
    import scipy.sparse as sp
    col_ptr = np.frombuffer(col_ptr_mv, dtype=C_DTYPE[col_ptr_type])[:ncol_ptr]
    indices = np.frombuffer(indices_mv, dtype=np.int32)[:nelem]
    data = np.frombuffer(data_mv, dtype=C_DTYPE[data_type])[:nelem]
    return sp.csc_matrix((data, indices, col_ptr),
                         shape=(num_row, ncol_ptr - 1), copy=True)


def dataset_create_from_csc(col_ptr_mv, col_ptr_type, indices_mv, data_mv,
                            data_type, ncol_ptr, nelem, num_row, parameters,
                            reference):
    """reference: LGBM_DatasetCreateFromCSC (c_api.h:191)."""
    mat = _csc_view(col_ptr_mv, col_ptr_type, indices_mv, data_mv,
                    data_type, ncol_ptr, nelem, num_row)
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(mat, reference=ref, params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_create_from_mats(mats, data_type, nrows, ncol, is_row_major,
                             parameters, reference):
    """reference: LGBM_DatasetCreateFromMats — vertically stacked chunks."""
    parts = []
    for mv, nrow in zip(mats, nrows):
        arr = np.frombuffer(mv, dtype=C_DTYPE[data_type])
        parts.append(arr.reshape(nrow, ncol) if is_row_major
                     else arr.reshape(ncol, nrow).T)
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(np.vstack(parts).astype(np.float64), reference=ref,
                 params=params)
    ds.construct()
    return _new_handle(ds)


class _StreamingDataset:
    """Pre-allocated dataset filled by PushRows (reference streaming path:
    LGBM_DatasetCreateFromSampledColumn / CreateByReference + PushRows,
    c_api.cpp). Constructs lazily on first real use; fields set before or
    between pushes are buffered and re-applied on every materialization
    (the reference allows SetField and PushRows in any order)."""

    def __init__(self, num_row: int, num_col: int, params: str,
                 reference=None):
        self.shape = (num_row, num_col)
        self.buf = None                       # dense buffer, lazy
        self._sparse_chunks = []              # [(start_row, csr)]
        self.params = parse_config_str(params or "")
        self.reference = reference
        self.filled = 0
        self._ds = None
        self._pending_fields: Dict[str, np.ndarray] = {}
        self._pending_names = None

    def _dense_buf(self) -> np.ndarray:
        if self.buf is None:
            self.buf = np.zeros(self.shape, dtype=np.float64)
            for start, chunk in self._sparse_chunks:
                co = chunk.tocoo()
                self.buf[co.row + start, co.col] = co.data
            self._sparse_chunks = []
        return self.buf

    def push_rows(self, arr: np.ndarray, start_row: int) -> None:
        self._dense_buf()[start_row:start_row + arr.shape[0], :] = arr
        self.filled = max(self.filled, start_row + arr.shape[0])
        self._ds = None

    def push_rows_sparse(self, csr, start_row: int) -> None:
        """CSR push that never densifies: chunks accumulate and assemble
        into ONE sparse matrix at materialization (unless a dense
        push_rows already forced the dense buffer, then they scatter into
        it). The reference's PushRowsByCSR feeds sparse bins the same
        way (c_api.cpp PushRowsByCSR -> sparse_bin.hpp Push)."""
        if self.buf is not None:
            co = csr.tocoo()
            self.buf[co.row + start_row, co.col] = co.data
        else:
            self._sparse_chunks.append((start_row, csr))
        self.filled = max(self.filled, start_row + csr.shape[0])
        self._ds = None

    def _assembled(self):
        """The pushed data in its cheapest faithful form."""
        if self.buf is not None:
            return self.buf
        if self._sparse_chunks:
            import scipy.sparse as sp
            rows, cols, vals = [], [], []
            for start, c in self._sparse_chunks:
                co = c.tocoo()
                rows.append(co.row.astype(np.int64) + start)
                cols.append(co.col)
                vals.append(co.data)
            return sp.csr_matrix(
                (np.concatenate(vals),
                 (np.concatenate(rows), np.concatenate(cols))),
                shape=self.shape)
        return self._dense_buf()

    def set_field(self, name, data):
        self._pending_fields[name] = np.asarray(data)
        if self._ds is not None:
            self._ds.set_field(name, data)
        return self

    def set_group(self, data):
        self._pending_fields["group"] = np.asarray(data)
        if self._ds is not None:
            self._ds.set_group(data)
        return self

    def _update_params(self, params):
        self.params.update(params or {})
        self._ds = None
        return self

    def set_feature_name(self, names):
        self._pending_names = list(names)
        if self._ds is not None:
            self._ds.set_feature_name(self._pending_names)
            # already constructed: the wrapper attr alone won't reach the
            # binned dataset, rename it in place like the C API does
            inner = getattr(self._ds, "_inner", None)
            if inner is not None:
                inner.feature_names = list(names)
        return self

    def _materialize(self) -> Dataset:
        if self._ds is None:
            ds = Dataset(self._assembled(), reference=self.reference,
                         params=self.params)
            if getattr(self, "_pending_names", None):
                ds.set_feature_name(self._pending_names)
            for name, data in self._pending_fields.items():
                if name == "group":
                    ds.set_group(data)
                else:
                    ds.set_field(name, data)
            ds.construct()
            if getattr(self, "_pending_names", None):
                ds._inner.feature_names = list(self._pending_names)
            self._ds = ds
        return self._ds

    # duck-typed Dataset surface used by the other entry points
    def construct(self):
        return self._materialize().construct()

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._materialize(), name)


def dataset_create_from_sampled_column(num_row, num_col, parameters):
    return _new_handle(_StreamingDataset(num_row, num_col, parameters))


def dataset_create_by_reference(ref_h, num_row):
    ref = _get(ref_h)
    return _new_handle(_StreamingDataset(
        num_row, ref.num_feature(), "", reference=ref))


def dataset_push_rows(h, mv, data_type, nrow, ncol, start_row):
    ds = _get(h)
    arr = np.frombuffer(mv, dtype=C_DTYPE[data_type]).reshape(nrow, ncol)
    if not isinstance(ds, _StreamingDataset):
        raise ValueError("PushRows requires a dataset created by "
                         "CreateFromSampledColumn/CreateByReference")
    ds.push_rows(np.asarray(arr, dtype=np.float64), start_row)
    return 0


def dataset_push_rows_by_csr(h, indptr_mv, indptr_type, indices_mv, data_mv,
                             data_type, nindptr, nelem, num_col, start_row):
    ds = _get(h)
    mat = _csr_view(indptr_mv, indptr_type, indices_mv, data_mv,
                    data_type, nindptr, nelem, num_col)
    if not isinstance(ds, _StreamingDataset):
        raise ValueError("PushRowsByCSR requires a streaming dataset")
    ds.push_rows_sparse(mat, start_row)
    return 0


def dataset_get_subset(h, indices_mv, num_indices, parameters):
    ds = _as_dataset(_get(h))
    idx = np.frombuffer(indices_mv, dtype=np.int32)[:num_indices]
    sub = ds.subset(idx.astype(np.int64),
                    parse_config_str(parameters or "") or None)
    sub.construct()
    return _new_handle(sub)


def dataset_save_binary(h, filename):
    _get(h).save_binary(filename)
    return 0


def dataset_dump_text(h, filename):
    ds = _as_dataset(_get(h))
    ds.construct()
    inner = ds._inner
    with open(filename, "w") as fh:
        fh.write("num_data: %d\n" % inner.num_data)
        fh.write("num_feature: %d\n" % inner.num_features)
        for fi, m in enumerate(inner.bin_mappers):
            fh.write("feature %d: num_bin=%d missing=%d\n"
                     % (fi, m.num_bin, m.missing_type))
        binned = np.asarray(inner.binned)
        for i in range(min(inner.num_data, 1000)):
            fh.write(" ".join(str(int(v)) for v in binned[i]) + "\n")
    return 0


def dataset_set_feature_names(h, names):
    ds = _get(h)
    names = list(names)
    ds.set_feature_name(names)
    # C-API datasets are already constructed; rename in place
    inner = getattr(ds, "_inner", None)
    if inner is not None:
        inner.feature_names = list(names)
    return 0


def dataset_get_feature_names(h):
    return [str(n) for n in _get(h).get_feature_name()]


def dataset_update_param(h, parameters):
    # note: a constructed (non-streaming) dataset is already binned; like
    # the reference, updates then only affect params consumed later
    _get(h)._update_params(parse_config_str(parameters or ""))
    return 0


def dataset_add_features_from(h, other_h):
    ds, other = _as_dataset(_get(h)), _as_dataset(_get(other_h))
    ds.construct()
    other.construct()
    ds.add_features_from(other)
    return 0


# ---------------------------------------------------------------------------
# Extended booster entry points
# ---------------------------------------------------------------------------

def booster_merge(h, other_h):
    """reference: LGBM_BoosterMerge (c_api.h:437) — PREPEND the other
    booster's models (GBDT::MergeFrom, reference gbdt.h:60: other first,
    then own). When the target is a freshly-created training booster with
    no trees yet (the R bindings' init_model flow: BoosterCreate +
    BoosterMerge, reference R lgb.Booster.R:65), the merged trees are
    also replayed into the score updaters so continued training sees the
    previous model — the role the reference fills by seeding the train
    set's init_score from a Predictor."""
    import copy as _copy
    bst, other = _get(h), _get(other_h)
    g = bst._gbdt
    merged = [_copy.deepcopy(t) for t in other._gbdt.models]
    continuation = (not g.models
                    and getattr(g, "score_updater", None) is not None)
    g.models = merged + g.models
    g.num_init_iteration = len(merged) // max(g.num_tree_per_iteration, 1)
    if continuation:
        for k in range(g.num_tree_per_iteration):
            for it in range(g.num_init_iteration):
                tree = merged[it * g.num_tree_per_iteration + k]
                g.score_updater.add_tree(tree, k)
                for vu in g.valid_updaters:
                    vu.add_tree(tree, k)
    return 0


def booster_reset_parameter(h, parameters):
    _get(h).reset_parameter(parse_config_str(parameters or ""))
    return 0


def booster_reset_training_data(h, train_h):
    """reference: LGBM_BoosterResetTrainingData — swap the train set,
    keeping the model."""
    bst = _get(h)
    new_set = _as_dataset(_get(train_h))
    new_set.construct()
    old = bst._gbdt
    # trees store bin-space thresholds: the new data must be binned with
    # the same mappers (reference fatals on misaligned bin mappers)
    old_m = old.train_set.bin_mappers
    new_m = new_set._inner.bin_mappers
    def _mappers_equal(a, b):
        if a.num_bin != b.num_bin or a.bin_type != b.bin_type:
            return False
        ua, ub = np.asarray(a.bin_upper_bound, np.float64), \
            np.asarray(b.bin_upper_bound, np.float64)
        if ua.shape != ub.shape or not np.array_equal(ua, ub,
                                                      equal_nan=True):
            return False
        return getattr(a, "categorical_2_bin", None) == \
            getattr(b, "categorical_2_bin", None)

    same = (new_m is old_m) or (
        len(new_m) == len(old_m)
        and all(_mappers_equal(a, b) for a, b in zip(new_m, old_m)))
    if not same:
        raise ValueError(
            "ResetTrainingData requires a dataset binned against the "
            "booster's training data (create it with reference=)")
    import copy as _copy
    from .models.gbdt import create_boosting
    cfg = _copy.deepcopy(new_set._inner.config)
    cfg.update(bst.params)
    g = create_boosting(cfg, new_set._inner)
    g.models = old.models
    g.iter = old.iter
    # registered validation sets survive the train-set swap (reference
    # ResetTrainingData keeps valid data)
    g.valid_sets = old.valid_sets
    g.valid_names = old.valid_names
    g.valid_updaters = old.valid_updaters
    g.valid_metrics = old.valid_metrics
    # rebuild training scores from the carried model over the new binned
    # data (the reference re-scores via the score updater the same way)
    k = max(g.num_tree_per_iteration, 1)
    for i, tree in enumerate(g.models):
        g.score_updater.add_tree(tree, i % k)
    bst._gbdt = g
    bst.train_set = new_set
    return 0


def booster_shuffle_models(h, start_iter, end_iter):
    _get(h).shuffle_models(start_iter, end_iter)
    return 0


def booster_refit(h, leaf_preds_mv, nrow, ncol):
    """reference: LGBM_BoosterRefit — refit leaf values with the given
    leaf predictions over the CURRENT training data."""
    bst = _get(h)
    leaf = np.frombuffer(leaf_preds_mv, dtype=np.int32).reshape(nrow, ncol)
    decay = float(bst.params.get("refit_decay_rate", 0.9))
    bst._gbdt.refit_leaves(leaf, decay)
    return 0


def booster_get_leaf_value(h, tree_idx, leaf_idx):
    return float(_get(h)._gbdt.models[tree_idx].leaf_value[leaf_idx])


def booster_set_leaf_value(h, tree_idx, leaf_idx, val):
    gbdt = _get(h)._gbdt
    gbdt.models[tree_idx].set_leaf_output(leaf_idx, float(val))
    gbdt.invalidate_ensemble_cache()   # in-place edit: drop tensorized cache
    return 0


def booster_number_of_total_model(h):
    return _get(h).num_trees()


def booster_num_model_per_iteration(h):
    return _get(h).num_model_per_iteration()


def booster_get_num_predict(h, data_idx):
    bst = _get(h)
    g = bst._gbdt
    n = (g.num_data if data_idx == 0
         else g.valid_sets[data_idx - 1].num_data)
    return n * g.num_class


def booster_get_predict(h, data_idx):
    """Raw converted predictions for train (0) / valid i (i>0) — the
    reference's GetPredict over the internal score (c_api.cpp)."""
    bst = _get(h)
    g = bst._gbdt
    updater = (g.score_updater if data_idx == 0
               else g.valid_updaters[data_idx - 1])
    scores = updater.host_scores()           # (K, N)
    if g.objective is not None:
        import jax.numpy as jnp
        conv = np.asarray(g.objective.convert_output(jnp.asarray(scores.T)))
    else:
        conv = scores.T
    return np.ascontiguousarray(conv, dtype=np.float64).tobytes()


def booster_dump_model(h, start_iteration, num_iteration):
    import json
    d = _get(h).dump_model(
        num_iteration if num_iteration > 0 else None, start_iteration)
    return json.dumps(d)


def booster_get_feature_names(h):
    return [str(n) for n in _get(h).feature_name()]


def booster_calc_num_predict(h, num_row, predict_type, num_iteration):
    bst = _get(h)
    g = bst._gbdt
    iters = g.current_iteration
    if num_iteration > 0:
        iters = min(iters, num_iteration)
    if predict_type == 2:        # leaf index
        return num_row * g.num_tree_per_iteration * iters
    if predict_type == 3:        # contrib
        return num_row * g.num_class * (g.max_feature_idx + 2)
    return num_row * g.num_class


def booster_predict_for_file(h, data_filename, data_has_header,
                             predict_type, num_iteration, parameter,
                             result_filename):
    bst = _get(h)
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    # honor parser overrides from the parameter string (reference passes
    # them into the Predictor's parser config)
    pconf = parse_config_str(parameter or "")
    label_col = pconf.get("label_column", 0)
    if isinstance(label_col, str):
        if label_col.startswith("name:"):
            # name: form resolves against the file header (reference
            # config.h label_column doc: names require has_header)
            name = label_col[5:]
            # same first-line rule as parse_file: skip comments/blanks
            from .io.file_io import open_file
            with open_file(data_filename) as fh:
                first = fh.readline()
                while first and (first.startswith("#")
                                 or not first.strip()):
                    first = fh.readline()
            if not first:
                raise ValueError(f"data file is empty: {data_filename}")
            first = first.strip()
            delim = "," if "," in first else "\t" if "\t" in first else None
            cols = [c.strip() for c in first.split(delim)]
            if name not in cols:
                raise ValueError(
                    f"label_column name '{name}' not in file header")
            label_col = cols.index(name)
            data_has_header = 1
        else:
            label_col = int(label_col.split(":")[-1])
    from .io.parser import parse_file
    x, _, _ = parse_file(data_filename, label_column=int(label_col),
                         has_header=bool(data_has_header) or None)
    preds = bst.predict(
        x, num_iteration=num_iteration if num_iteration > 0 else None,
        **kwargs)
    preds = np.asarray(preds, dtype=np.float64)
    rows = preds[:, None] if preds.ndim == 1 else preds
    from .io.file_io import open_file
    with open_file(result_filename, "w") as fh:
        for row in rows:
            fh.write("\t".join(repr(float(v)) for v in row) + "\n")
    return 0


def _csr_view(indptr_mv, indptr_type, indices_mv, data_mv, data_type,
              nindptr, nelem, num_col):
    """scipy CSR over the caller's buffers — no dense materialization."""
    import scipy.sparse as sp
    indptr = np.frombuffer(indptr_mv, dtype=C_DTYPE[indptr_type])[:nindptr]
    indices = np.frombuffer(indices_mv, dtype=np.int32)[:nelem]
    data = np.frombuffer(data_mv, dtype=C_DTYPE[data_type])[:nelem]
    return sp.csr_matrix((data, indices, indptr),
                         shape=(nindptr - 1, num_col), copy=True)


def booster_predict_for_csr(h, indptr_mv, indptr_type, indices_mv, data_mv,
                            data_type, nindptr, nelem, num_col,
                            predict_type, num_iteration, parameter):
    # basic.Booster.predict row-batches sparse input; memory stays
    # bounded by the batch, not the matrix
    mat = _csr_view(indptr_mv, indptr_type, indices_mv, data_mv,
                    data_type, nindptr, nelem, num_col)
    return _predict_dense(_get(h), mat, predict_type, num_iteration)


def booster_predict_for_csc(h, col_ptr_mv, col_ptr_type, indices_mv, data_mv,
                            data_type, ncol_ptr, nelem, num_row,
                            predict_type, num_iteration, parameter):
    mat = _csc_view(col_ptr_mv, col_ptr_type, indices_mv, data_mv,
                    data_type, ncol_ptr, nelem, num_row).tocsr()
    return _predict_dense(_get(h), mat, predict_type, num_iteration)


def _predict_dense(bst, mat, predict_type, num_iteration):
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    preds = bst.predict(mat, num_iteration=num_iteration
                        if num_iteration > 0 else None, **kwargs)
    return np.ascontiguousarray(preds, dtype=np.float64).tobytes()


def booster_predict_for_mat_single_row(h, mv, data_type, ncol, is_row_major,
                                       predict_type, num_iteration,
                                       parameter):
    arr = np.frombuffer(mv, dtype=C_DTYPE[data_type])[:ncol]
    return _predict_dense(_get(h), arr.reshape(1, ncol), predict_type,
                          num_iteration)


def booster_predict_for_csr_single_row(h, indptr_mv, indptr_type, indices_mv,
                                       data_mv, data_type, nindptr, nelem,
                                       num_col, predict_type, num_iteration,
                                       parameter):
    mat = _csr_view(indptr_mv, indptr_type, indices_mv, data_mv,
                    data_type, nindptr, nelem, num_col)
    return _predict_dense(_get(h), mat, predict_type, num_iteration)


def network_init_with_functions(num_machines, rank):
    """reference: LGBM_NetworkInitWithFunctions (c_api.h:1018). The
    reference lets hosts inject reduce-scatter/allgather callbacks; here
    collectives are XLA ops over the mesh, so the injected functions are
    recorded for the host-side metadata sync only."""
    from .parallel import network
    network.init_external(int(num_machines), int(rank))
    return 0
