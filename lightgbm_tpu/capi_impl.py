"""Python side of the C ABI (capi/c_api.cpp).

Handle tables + buffer marshalling for the LGBM_* entry points. The
reference implements this layer in C++ (reference: src/c_api.cpp Booster
wrapper class + dataset constructors); here the native shim embeds CPython
and calls these functions with zero-copy memoryviews.
"""
from __future__ import annotations

import itertools
from typing import Dict

import numpy as np

from .basic import Booster, Dataset
from .config import parse_config_str

_handles: Dict[int, object] = {}
_handle_counter = itertools.count(1)
_field_cache: Dict[tuple, np.ndarray] = {}

C_DTYPE = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _new_handle(obj) -> int:
    h = next(_handle_counter)
    _handles[h] = obj
    return h


def _get(h: int):
    return _handles[int(h)]


# ---------------------------------------------------------------------------
# Dataset
# ---------------------------------------------------------------------------

def dataset_create_from_file(filename: str, parameters: str, reference: int):
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_create_from_mat(mv, data_type, nrow, ncol, is_row_major,
                            parameters, reference):
    arr = np.frombuffer(mv, dtype=C_DTYPE[data_type])
    if is_row_major:
        mat = arr.reshape(nrow, ncol)
    else:
        mat = arr.reshape(ncol, nrow).T
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(mat, dtype=np.float64), reference=ref,
                 params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_create_from_csr(indptr_mv, indptr_type, indices_mv, data_mv,
                            data_type, nindptr, nelem, num_col, parameters,
                            reference):
    indptr = np.frombuffer(indptr_mv, dtype=C_DTYPE[indptr_type])[:nindptr]
    indices = np.frombuffer(indices_mv, dtype=np.int32)[:nelem]
    data = np.frombuffer(data_mv, dtype=C_DTYPE[data_type])[:nelem]
    nrow = nindptr - 1
    mat = np.zeros((nrow, num_col))
    for i in range(nrow):
        lo, hi = indptr[i], indptr[i + 1]
        mat[i, indices[lo:hi]] = data[lo:hi]
    params = parse_config_str(parameters or "")
    ref = _get(reference) if reference else None
    ds = Dataset(mat, reference=ref, params=params)
    ds.construct()
    return _new_handle(ds)


def dataset_free(h):
    _handles.pop(int(h), None)
    for k in [k for k in _field_cache if k[0] == int(h)]:
        del _field_cache[k]


def dataset_get_num_data(h):
    return _get(h).num_data()


def dataset_get_num_feature(h):
    return _get(h).num_feature()


def dataset_set_field(h, field_name, mv, num_element, type_):
    arr = np.frombuffer(mv, dtype=C_DTYPE[type_])[:num_element].copy()
    ds = _get(h)
    if field_name == "group":
        ds.set_group(arr.astype(np.int64))
    else:
        ds.set_field(field_name, arr.astype(np.float64))
    return 0


def dataset_get_field(h, field_name):
    ds = _get(h)
    val = ds.get_field(field_name)
    if val is None:
        raise ValueError(f"field {field_name} not set")
    if field_name == "group":
        arr = np.ascontiguousarray(val, dtype=np.int32)
        type_ = 2
    else:
        arr = np.ascontiguousarray(val, dtype=np.float32)
        type_ = 0
    _field_cache[(int(h), field_name)] = arr  # keep buffer alive
    return (arr.ctypes.data, len(arr), type_)


# ---------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------

def booster_create(train_h, parameters):
    params = parse_config_str(parameters or "")
    bst = Booster(params=params, train_set=_get(train_h))
    return _new_handle(bst)


def booster_create_from_modelfile(filename):
    bst = Booster(model_file=filename)
    return (_new_handle(bst), bst.current_iteration())


def booster_load_from_string(model_str):
    bst = Booster(model_str=model_str)
    return (_new_handle(bst), bst.current_iteration())


def booster_free(h):
    _handles.pop(int(h), None)


def booster_add_valid(h, valid_h):
    bst = _get(h)
    bst.add_valid(_get(valid_h), f"valid_{len(bst.name_valid_sets)}")


def booster_update_one_iter(h):
    return 1 if _get(h).update() else 0


def booster_num_total_rows(h):
    bst = _get(h)
    return bst._gbdt.num_data * bst._gbdt.num_tree_per_iteration


def booster_update_one_iter_custom(h, grad_mv, hess_mv):
    bst = _get(h)
    grad = np.frombuffer(grad_mv, dtype=np.float32)
    hess = np.frombuffer(hess_mv, dtype=np.float32)
    return 1 if bst._gbdt.train_one_iter(grad, hess) else 0


def booster_rollback_one_iter(h):
    _get(h).rollback_one_iter()


def booster_current_iteration(h):
    return _get(h).current_iteration()


def booster_num_classes(h):
    return _get(h)._gbdt.num_class


def booster_num_feature(h):
    return _get(h).num_feature()


def booster_eval_counts(h):
    bst = _get(h)
    return sum(len(m.names) for m in bst._gbdt.train_metrics)


def booster_eval_names(h):
    """Metric display names, order-aligned with booster_get_eval results
    (reference: LGBM_BoosterGetEvalNames, c_api.cpp)."""
    bst = _get(h)
    names = []
    for m in bst._gbdt.train_metrics:
        names.extend(m.names)
    return [str(n) for n in names]


def booster_eval_higher_better(h):
    """1/0 per eval slot: whether larger metric values are better."""
    bst = _get(h)
    out = []
    for m in bst._gbdt.train_metrics:
        out.extend([1 if m.higher_better else 0] * len(m.names))
    return out


def booster_get_eval(h, data_idx):
    """data_idx 0 = train, i>0 = valid i-1 (reference c_api semantics)."""
    bst = _get(h)
    if data_idx == 0:
        results = bst.eval_train()
    else:
        name = bst.name_valid_sets[data_idx - 1]
        results = [r for r in bst.eval_valid() if r[0] == name]
    return [float(r[2]) for r in results]


def booster_predict_for_mat(h, mv, data_type, nrow, ncol, is_row_major,
                            predict_type, num_iteration, parameter):
    bst = _get(h)
    arr = np.frombuffer(mv, dtype=C_DTYPE[data_type])
    mat = arr.reshape(nrow, ncol) if is_row_major else arr.reshape(ncol, nrow).T
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    preds = bst.predict(np.asarray(mat, dtype=np.float64),
                        num_iteration=num_iteration if num_iteration > 0 else None,
                        **kwargs)
    return np.ascontiguousarray(preds, dtype=np.float64).tobytes()


def booster_save_model(h, start_iteration, num_iteration, filename):
    _get(h)._gbdt.save_model(filename, num_iteration, start_iteration)


def booster_save_model_to_string(h, start_iteration, num_iteration):
    return _get(h)._gbdt.save_model_to_string(start_iteration, num_iteration)


def booster_feature_importance(h, num_iteration, importance_type):
    itype = "split" if importance_type == 0 else "gain"
    imp = _get(h)._gbdt.feature_importance(
        itype, num_iteration if num_iteration > 0 else None)
    return [float(v) for v in imp]


# ---------------------------------------------------------------------------
# Network
# ---------------------------------------------------------------------------

def network_init(machines, local_listen_port, listen_time_out, num_machines):
    from .parallel import network
    network.init_from_params(machines, local_listen_port, num_machines)


def network_free():
    from .parallel import network
    network.free()
